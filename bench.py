"""Headline benchmark: ResNet-50 training MFU on one TPU chip.

The reference publishes no benchmark numbers (BASELINE.md); the driver's
north-star is ResNet-50 at >=60% MFU on v5e. This bench runs the flagship
training step (fwd+bwd+SGD in one jit, bf16, synthetic data — measuring the
compute path, not input pipeline) and prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` = measured MFU / 0.60 target (>=1.0 beats the north-star).
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp

try:  # persistent compile cache: tunnel compiles run 20-50 s
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax-cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass

TARGET_MFU = 0.60


def _batch_candidates() -> list:
    # 512 is viable again: the round-1 "batch-512 hang" was the image batch
    # being a closure constant — serialized into the remote-compile request
    # body (308 MiB at 512; the backend 413s past ~256 MiB). Data is now a
    # jitted ARGUMENT, so the compile payload is shape-only.
    # 256 first: it measures marginally better than 512 on this chip
    # (2507 vs 2417 img/s — batch 512 spills more activations), and the
    # first batch that completes is the headline.
    try:
        override = os.environ.get("BENCH_BATCH")
        return [int(override)] if override else [256, 512, 128, 64, 32]
    except ValueError:
        return [256, 512, 128, 64, 32]


def _timed_steps() -> int:
    # 50 steps in one scan: long enough that fixed dispatch/tunnel overhead
    # is <5% of the window (measured: 10 steps -> 26.5% MFU, 30 -> 29.9%,
    # 60 -> 30.9% on a tunneled v5e chip; the curve flattens by ~50).
    try:
        return int(os.environ.get("BENCH_STEPS", "50"))
    except ValueError:
        return 50


def _repeats() -> int:
    # Repeat the timed window and take the MEDIAN (VERDICT r4 #7: the
    # flagship number must reproduce across cold driver runs within
    # ±0.5 MFU). In-process windows measure dead-stable (30.79 ±0.01 MFU
    # over 6 consecutive windows, round 5); the median + reported spread
    # makes transient tunnel contention visible instead of becoming the
    # headline.
    try:
        return max(1, int(os.environ.get("BENCH_REPEATS", "3")))
    except ValueError:
        return 3


def _timed_windows(fn, repeats: int):
    """Run ``fn()`` (one fetched-checksum window) ``repeats`` times; return
    (median_seconds, [per-window seconds])."""
    import statistics

    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
        # every window's fetched results must be finite — the median time
        # may come from any of them, so none may be a corrupted run
        fn.check()
    return statistics.median(times), times

# XLA cost-analysis fallback: ResNet-50 fwd ~8.2 GFLOP/image @224 (2*MACs),
# train step ~3x forward.
ANALYTIC_FWD_FLOPS_PER_IMAGE = 8.2e9


def _step_breakdown(clock, timed_steps: int) -> dict:
    """StepClock summary → the per-step dict bench rows carry. One clock
    "step" is one timed WINDOW (timed_steps scan iterations), so window
    phases are normalized back to per-step seconds; compile stays a
    one-time total."""
    s = clock.summary()
    return {
        "compile_s": round(s.get("compile_s", 0.0), 3),
        "data_wait_s_per_step": round(s.get("data_wait", 0.0) / timed_steps, 6),
        "device_compute_s_per_step": round(s.get("compute", 0.0) / timed_steps, 6),
        "fetch_s_per_step": round(s.get("fetch", 0.0) / timed_steps, 6),
        "host_other_s_per_step": round(s.get("other", 0.0) / timed_steps, 6),
    }


def _attribution_row(make_costs, clock, timed_steps: int, generation: str):
    """Per-module attribution for a bench row (BENCH_ATTRIBUTION=0 skips).
    ``make_costs`` prices the model walk — compile-time only, nothing
    executes — and the report decomposes the clock's measured window into
    data-wait / fused-compute / un-fused-compute / other step fractions.
    Guarded: attribution failing must never fail the bench."""
    if os.environ.get("BENCH_ATTRIBUTION", "1") != "1":
        return None
    try:
        from kubeflow_tpu.training.attribution import attribution_report

        report = attribution_report(make_costs(), clock=clock,
                                    steps_per_record=timed_steps,
                                    generation=generation)
        return report.to_dict(top_n=5)
    except Exception as e:
        return {"error": str(e)[:160]}


def _bench(batch: int):
    from kubeflow_tpu.models import ResNet50
    from kubeflow_tpu.training import ClassifierTask, mfu
    from kubeflow_tpu.training.classifier import sgd_momentum
    from kubeflow_tpu.training.flops import compiled_with_cost, detect_generation
    from kubeflow_tpu.runtime.tracing import TRACER
    from kubeflow_tpu.tpu.profiling import StepClock

    # s2d stem: measured +0.4 MFU on v5e (e2e/conv_experiments.py); opt-in
    # on the model (param-tree compat) but the bench always wants the fast path.
    stem = os.environ.get("BENCH_STEM", "s2d")
    timed_steps = _timed_steps()
    rng = jax.random.PRNGKey(0)
    images = jax.random.normal(rng, (batch, 224, 224, 3), jnp.float32)
    labels = jax.random.randint(rng, (batch,), 0, 1000)

    def make_step(fused: bool):
        model = ResNet50(num_classes=1000, stem=stem, fused_blocks=fused)
        task = ClassifierTask(
            model=model, optimizer=sgd_momentum(lr=0.1, total_steps=1000))
        return task, task.make_train_step()

    # Both paths declare the SAME variable tree (resnet._ConvKernel /
    # _FoldedNorm), so one init serves fused and unfused executables.
    task0, _ = make_step(False)
    state = task0.init(rng, images)

    # All timed steps run inside ONE executable (lax.scan): a single
    # dispatch covers the whole window, so per-dispatch/tunnel latency and
    # async-dispatch artifacts cannot distort the measurement. The fetched
    # outputs depend on the LAST step's update (param checksum) and loss,
    # so no step can be dead-code-eliminated. Images/labels are ARGUMENTS —
    # a closure-captured batch is serialized into the remote-compile request
    # on this backend (413 past ~256 MiB; hung batch 512 in round 1).
    def make_window(fused: bool, steps: int):
        _, step = make_step(fused)

        @jax.jit
        def run_steps(state, images, labels):
            def body(s, _):
                s2, metrics = step(s, images, labels)
                return s2, metrics["loss"]
            final, losses = jax.lax.scan(body, state, None, length=steps)
            checksum = sum(jnp.sum(p.astype(jnp.float32))
                           for p in jax.tree_util.tree_leaves(final.params))
            return losses[-1], checksum

        return run_steps

    # BENCH_FUSED: 1 = Pallas fused bottlenecks, 0 = XLA composite,
    # auto (default) = measured head-to-head via the autotune sweep, keep
    # the winner. Auto because the acceptance bar is "never slower than the
    # composite" and BASELINE round 5 measured the kernel BEHIND XLA on the
    # tunneled dev backend — the bench measures instead of assuming.
    # BENCH_AUTOTUNE=0 skips the measurement and pins the backend default.
    fused_mode = os.environ.get("BENCH_FUSED", "auto")
    autotune_on = os.environ.get("BENCH_AUTOTUNE", "1") != "0"
    calibration = None
    autotune_row = None
    if fused_mode in ("0", "1"):
        use_fused = fused_mode == "1"
        autotune_row = {"family": "resnet",
                        "chosen": {"fused_blocks": use_fused},
                        "pinned": f"BENCH_FUSED={fused_mode}"}
    elif not autotune_on:
        use_fused = jax.default_backend() == "tpu"
    else:
        from kubeflow_tpu.training.autotune import sweep as _autotune_sweep

        calib_steps = max(4, min(10, timed_steps))

        def _measure(knobs):
            run = make_window(knobs["fused_blocks"], calib_steps)
            loss, cs = run(state, images, labels)  # compile + warmup
            _ = (float(loss), float(cs))
            t0 = time.perf_counter()
            loss, cs = run(state, images, labels)
            _ = (float(loss), float(cs))
            return (time.perf_counter() - t0) / calib_steps

        result = _autotune_sweep(
            "resnet",
            [{"fused_blocks": False}, {"fused_blocks": True}],
            measure=_measure, log=lambda s: print(s, file=sys.stderr))
        use_fused = bool(result.chosen["fused_blocks"])
        autotune_row = result.to_row()
        # legacy row shape, kept for cross-round history comparisons
        calibration = {}
        for c in result.candidates:
            key = "fused" if c.knobs["fused_blocks"] else "unfused"
            calibration[key] = (round(c.measured_seconds, 6)
                                if c.measured_seconds is not None else None)
            if c.error:
                calibration[f"{key}_error"] = c.error[:120]

    clock = StepClock(tracer=TRACER)
    run_steps = make_window(use_fused, timed_steps)

    # Per-step FLOPs always from the UNFUSED step: XLA credits ZERO flops
    # inside a Pallas custom call (same blindness as flash attention), so
    # probing the fused executable would drop most of the conv work from
    # the numerator and fake an MFU collapse. Same model math either way.
    # (And never the whole window: cost analysis counts a while-loop body
    # once, not × trip count.) compiled_with_cost times this compile; the
    # window compile below is also charged to the clock so compile_s never
    # pollutes a timed window.
    flops = None
    try:
        _, step_ref = make_step(False)
        with clock.compile():
            _, flops, _ = compiled_with_cost(step_ref, state, images, labels)
    except Exception:
        pass
    if not flops:
        flops = 3.0 * ANALYTIC_FWD_FLOPS_PER_IMAGE * batch

    # AOT-compile the window under the compile clock, then one warmup
    # execution OUTSIDE it, forced to completion by the host fetch
    # (block_until_ready alone can be a no-op on proxied backends).
    try:
        with clock.compile():
            run_steps, _, _ = compiled_with_cost(run_steps, state, images, labels)
    except Exception:
        pass  # jit dispatch compiles lazily; first window absorbs it
    loss, checksum = run_steps(state, images, labels)
    _ = (float(loss), float(checksum))
    clock.mark()  # warmup execution is untimed — keep it out of "other"

    import math

    results = {}

    def window():
        with clock.compute():
            loss, checksum = run_steps(state, images, labels)
            jax.block_until_ready((loss, checksum))
        with clock.fetch():
            # host fetch = real barrier; finiteness checked outside the timer
            results["loss"], results["checksum"] = float(loss), float(checksum)
        clock.end_step()

    def check():
        if not all(math.isfinite(v) for v in results.values()):
            raise RuntimeError(f"non-finite bench result: {results}")

    window.check = check
    total, window_times = _timed_windows(window, _repeats())
    dt = total / timed_steps

    gen = detect_generation()
    # HBM telemetry from the window executable's memory_analysis (the loop
    # reuses temps, so the window's resident bytes ARE the step's peak);
    # published as the training_step_peak_hbm_bytes gauge and the bench row.
    mem = None
    try:
        from kubeflow_tpu.training.attribution import record_step_peak_hbm
        from kubeflow_tpu.training.flops import memory_stats

        mem = memory_stats(run_steps)
        record_step_peak_hbm(mem)
    except Exception:
        mem = None
    def _resnet_costs():
        from kubeflow_tpu.training.attribution import attribute_resnet

        return attribute_resnet(batch=batch, image=224, stem=stem,
                                fused_blocks=use_fused, generation=gen)

    attribution = _attribution_row(_resnet_costs, clock, timed_steps, gen)
    return {
        "images_per_sec_per_chip": batch / dt,
        "step_seconds": dt,
        "mfu": mfu(flops, dt, num_chips=1, generation=gen),
        "window_mfus": [round(mfu(flops, t / timed_steps, 1, gen) * 100, 2)
                        for t in window_times],
        "generation": gen,
        "batch": batch,
        "flops_per_step": flops,
        "fused_blocks": use_fused,
        "fused_calibration": calibration,
        "autotune": autotune_row,
        "step_breakdown": _step_breakdown(clock, timed_steps),
        "peak_hbm_bytes": (mem or {}).get("peak_hbm_bytes"),
        "memory": mem,
        "attribution": attribution,
    }


def _bench_gpt(batch: int, seq: int):
    """GPT-2-medium-class causal LM train step (AdamW, bf16 compute, Pallas
    flash attention). The matmul-dominated counterpart to the ResNet row:
    its op mix runs near the measured 175 TF/s matmul ceiling
    (e2e/ceiling.py), so it shows the MFU the framework reaches when the
    model shape suits the 128x128 MXU — ResNet's 64-wide convs cannot."""
    import optax as _optax

    from kubeflow_tpu.models.gpt import (
        GptConfig, GptLM, blockwise_causal_lm_loss, causal_lm_loss)
    from kubeflow_tpu.training import mfu
    from kubeflow_tpu.training.flops import compiled_with_cost, detect_generation
    from kubeflow_tpu.runtime.tracing import TRACER
    from kubeflow_tpu.tpu.profiling import StepClock

    # Fast paths default ON (BENCH_GPT_SCAN=0 / BENCH_FUSED_LOSS=0 to
    # compare): scan_blocks compiles one block instead of 24 unrolled;
    # the blockwise loss never materializes the [b, L, 32000] f32 logits
    # (1 GiB at b8/L1024 — THE cap on benchable batch before this).
    # With BENCH_AUTOTUNE on (default), unpinned remat/scan knobs are
    # swept by training.autotune: priced first (AOT compile, no steps),
    # survivors measured with short windows, the winner drives the run.
    scan_env = os.environ.get("BENCH_GPT_SCAN")
    remat_env = os.environ.get("BENCH_REMAT")
    fused_loss = os.environ.get("BENCH_FUSED_LOSS", "1") == "1"
    autotune_on = os.environ.get("BENCH_AUTOTUNE", "1") != "0"
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (batch, seq), 0, 32000)
    opt = _optax.adamw(3e-4, weight_decay=0.01)
    timed_steps = _timed_steps()

    def make_cfg(scan_blocks, remat):
        return GptConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096,
                         max_seq=seq, vocab_size=32000,
                         remat=remat, scan_blocks=scan_blocks)

    def build(cfg):
        model = GptLM(cfg)

        def loss_fn(p, ids):
            if fused_loss:
                hidden = model.apply({"params": p}, ids, return_hidden=True)
                return blockwise_causal_lm_loss(
                    hidden, p["embedding"]["embedding"], ids)
            return causal_lm_loss(model.apply({"params": p}, ids), ids)

        def train_step(params, opt_state, ids):
            loss, grads = jax.value_and_grad(loss_fn)(params, ids)
            updates, opt_state = opt.update(grads, opt_state, params)
            return _optax.apply_updates(params, updates), opt_state, loss

        def make_run(n):
            def run_steps(params, opt_state, ids):
                def body(carry, _):
                    p, s = carry
                    p, s, loss = train_step(p, s, ids)
                    return (p, s), loss
                (p, s), losses = jax.lax.scan(
                    body, (params, opt_state), None, length=n)
                checksum = sum(jnp.sum(x.astype(jnp.float32))
                               for x in jax.tree_util.tree_leaves(p))
                return losses[-1], checksum
            return run_steps

        return model, train_step, make_run

    default_knobs = {"scan_blocks": scan_env != "0" if scan_env is not None
                     else True,
                     "remat": remat_env == "1"}
    autotune_row = None
    if autotune_on and (scan_env is None or remat_env is None):
        from kubeflow_tpu.training.attribution import price_callable
        from kubeflow_tpu.training.autotune import sweep as _autotune_sweep

        scan_opts = ([scan_env == "1"] if scan_env is not None
                     else [True, False])
        remat_opts = ([remat_env == "1"] if remat_env is not None
                      else [False, True])
        candidates = [{"scan_blocks": sb, "remat": rm}
                      for sb in scan_opts for rm in remat_opts]
        if scan_env is None and remat_env is None:
            # remat-without-scan compiles 24 unrolled remat blocks for a
            # config the scanned one dominates — not worth the compile.
            candidates = [c for c in candidates
                          if not (c["remat"] and not c["scan_blocks"])]
        calib_steps = max(2, min(4, timed_steps))

        def _price(knobs):
            model_c, step_c, _ = build(make_cfg(**knobs))
            p_s = jax.eval_shape(model_c.init, rng, ids)["params"]
            o_s = jax.eval_shape(opt.init, p_s)
            return price_callable(
                step_c, p_s, o_s, ids, name="gpt_bench",
                kind="model", train_factor=1.0).est_seconds

        def _measure(knobs):
            model_c, _, make_run_c = build(make_cfg(**knobs))
            p = model_c.init(rng, ids)["params"]
            o = opt.init(p)
            run = jax.jit(make_run_c(calib_steps))
            out = run(p, o, ids)  # compile + warmup
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            out = run(p, o, ids)
            jax.block_until_ready(out)
            return (time.perf_counter() - t0) / calib_steps

        result = _autotune_sweep(
            "gpt", candidates, measure=_measure, price=_price, keep=2,
            log=lambda s: print(s, file=sys.stderr))
        chosen = dict(default_knobs)
        chosen.update(result.chosen)
        autotune_row = result.to_row()
    else:
        chosen = default_knobs
        autotune_row = {"family": "gpt", "chosen": dict(chosen),
                        "pinned": "env"}

    scan_blocks = bool(chosen["scan_blocks"])
    cfg = make_cfg(scan_blocks, bool(chosen["remat"]))
    model, train_step, make_run = build(cfg)
    params = model.init(rng, ids)["params"]
    opt_state = opt.init(params)
    run_steps = jax.jit(make_run(timed_steps))

    clock = StepClock(tracer=TRACER)
    # FLOPs numerator from the REFERENCE path (unrolled blocks, plain
    # loss): XLA cost analysis counts a while-loop body ONCE, so probing
    # the scanned / vocab-chunked executables would undercount the blocks
    # 24x and the LM head ~8x — the fast paths would fake an MFU drop.
    # Lowering from eval_shape structs keeps the probe allocation-free.
    flops = None
    try:
        import dataclasses as _dc

        # remat=False too: rematerialized flops are recompute, not model
        # work — counting them would inflate the numerator when the
        # autotuner picks a remat config.
        ref_model = GptLM(_dc.replace(cfg, scan_blocks=False, remat=False))

        def ref_step(params, opt_state, ids):
            loss, grads = jax.value_and_grad(
                lambda p: causal_lm_loss(ref_model.apply({"params": p}, ids), ids)
            )(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return _optax.apply_updates(params, updates), opt_state, loss

        ref_params = jax.eval_shape(ref_model.init, rng, ids)["params"]
        ref_opt_state = jax.eval_shape(opt.init, ref_params)
        with clock.compile():
            _, flops, _ = compiled_with_cost(
                jax.jit(ref_step), ref_params, ref_opt_state, ids)
    except Exception:
        pass
    if not flops:
        n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
        flops = 6.0 * n_params * batch * seq  # 6ND
    # XLA cost analysis counts ZERO flops inside the Pallas flash-attention
    # custom call (verified: identical totals for b8xL1024 and b4xL2048,
    # whose attention flops differ 2x) — add the causal attention work the
    # kernel actually executes, or attention-heavy configs are
    # under-credited. Convention matches the rest of the numerator
    # (2 flops/MAC): one causal dot = 2*L^2*d/2 flops per (b, head); fwd
    # has 2 dots (QK^T, PV), bwd 5 (recomputed s, dp, dq, dk, dv) = 3.5x.
    causal_dot = 2.0 * batch * cfg.n_heads * seq * seq * cfg.head_dim / 2
    flops += 3.5 * (2 * causal_dot) * cfg.n_layers

    try:
        with clock.compile():
            run_steps, _, _ = compiled_with_cost(run_steps, params, opt_state, ids)
    except Exception:
        pass
    loss, checksum = run_steps(params, opt_state, ids)
    _ = (float(loss), float(checksum))
    clock.mark()  # warmup execution is untimed — keep it out of "other"
    import math

    results = {}

    def window():
        with clock.compute():
            loss, checksum = run_steps(params, opt_state, ids)
            jax.block_until_ready((loss, checksum))
        with clock.fetch():
            results["loss"], results["checksum"] = float(loss), float(checksum)
        clock.end_step()

    def check():
        if not all(math.isfinite(v) for v in results.values()):
            raise RuntimeError(f"non-finite gpt bench: {results}")

    window.check = check
    total, window_times = _timed_windows(window, _repeats())
    dt = total / timed_steps
    gen = detect_generation()
    mem = None
    try:
        from kubeflow_tpu.training.attribution import record_step_peak_hbm
        from kubeflow_tpu.training.flops import memory_stats

        mem = memory_stats(run_steps)
        record_step_peak_hbm(mem)
    except Exception:
        mem = None

    def _gpt_costs():
        from kubeflow_tpu.training.attribution import attribute_gpt

        return attribute_gpt(cfg, batch=batch, seq=seq,
                             fused_loss=fused_loss, generation=gen)

    attribution = _attribution_row(_gpt_costs, clock, timed_steps, gen)
    return {
        "tokens_per_sec_per_chip": batch * seq / dt,
        "step_seconds": dt,
        "mfu": mfu(flops, dt, num_chips=1, generation=gen),
        "window_mfus": [round(mfu(flops, t / timed_steps, 1, gen) * 100, 2)
                        for t in window_times],
        "generation": gen,
        "batch": batch,
        "seq": seq,
        "scan_blocks": scan_blocks,
        "remat": cfg.remat,
        "fused_loss": fused_loss,
        "autotune": autotune_row,
        "step_breakdown": _step_breakdown(clock, timed_steps),
        "peak_hbm_bytes": (mem or {}).get("peak_hbm_bytes"),
        "memory": mem,
        "attribution": attribution,
    }


def _multichip_mesh_sizes(n_devices: int) -> dict:
    """Default dp x fsdp x tp x pp factorization for ``n_devices``: peel
    off pipe, model, fsdp as factors of 2 (innermost axes smallest), data
    absorbs the rest. Overridable per axis via BENCH_MC_{PP,TP,FSDP}."""
    def _env(name, default):
        try:
            return int(os.environ.get(name) or default)
        except ValueError:
            return default

    rest = n_devices
    pp = _env("BENCH_MC_PP", 2 if rest % 2 == 0 else 1)
    rest //= pp
    tp = _env("BENCH_MC_TP", 2 if rest % 2 == 0 else 1)
    rest //= tp
    fs = _env("BENCH_MC_FSDP", 2 if rest % 2 == 0 else 1)
    return {"pipe": pp, "model": tp, "fsdp": fs, "data": n_devices // (pp * tp * fs)}


def _bench_multichip():
    """Composed 4D (dp x fsdp x tp x pp) GPT train-step throughput across
    ALL local devices — the multi-chip half of the bench story. Emits
    tokens/sec/chip, weak-scaling efficiency vs a 1-chip run of the same
    per-chip token load, the schedule's bubble fraction, and the analytic
    per-axis comm bytes (parallel/comm.py), all surfaced through
    StepClock/MetricsRegistry."""
    from kubeflow_tpu.parallel import composite as composite_mod
    from kubeflow_tpu.parallel.comm import composite_comm_bytes, composite_step_flops
    from kubeflow_tpu.parallel.mesh import MeshConfig, make_mesh
    from kubeflow_tpu.parallel.pipeline import schedule_stats
    from kubeflow_tpu.runtime.metrics import METRICS
    from kubeflow_tpu.runtime.tracing import TRACER
    from kubeflow_tpu.tpu.profiling import StepClock

    devices = jax.devices()
    n_dev = len(devices)
    sizes = _multichip_mesh_sizes(n_dev)
    d_model = int(os.environ.get("BENCH_MC_DMODEL", "128"))
    cfg = composite_mod.CompositeConfig(
        vocab_size=int(os.environ.get("BENCH_MC_VOCAB", "512")),
        d_model=d_model,
        n_heads=int(os.environ.get("BENCH_MC_HEADS", "4")),
        d_ff=int(os.environ.get("BENCH_MC_FF", str(4 * d_model))),
        n_layers=int(os.environ.get("BENCH_MC_LAYERS", "8")),
        seq=int(os.environ.get("BENCH_MC_SEQ", "128")),
    )
    num_micro = int(os.environ.get("BENCH_MC_MICRO", "8"))
    mb = int(os.environ.get("BENCH_MC_MB", "8"))  # global microbatch size
    virtual_stages = int(os.environ.get("BENCH_PP_VIRTUAL", "2"))
    gather_mode = os.environ.get("BENCH_GATHER_MODE", "overlap")
    timed_steps = int(os.environ.get("BENCH_MC_STEPS", "5"))
    if cfg.n_layers % (sizes["pipe"] * virtual_stages):
        virtual_stages = 1  # odd factorization: fall back to GPipe

    mesh = make_mesh(MeshConfig(**sizes))
    rng = jax.random.PRNGKey(0)
    ids = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (num_micro, mb, cfg.seq),
                           0, cfg.vocab_size),
        composite_mod.batch_sharding(mesh))

    clock = StepClock(metrics=METRICS.namespace("multichip"), tracer=TRACER)

    def timed_run(use_mesh, use_v, use_gather, use_ids, label, use_clock):
        """Compile + warm one train step on ``use_mesh``, then time
        ``timed_steps`` chained steps per window (param updates chain, so
        no step is dead code; windows restart from the same init). Each run
        gets its OWN clock: the 1-chip reference must not pollute the
        multichip row's step_breakdown."""
        params0 = composite_mod.init_params(rng, cfg, use_mesh,
                                            virtual_stages=use_v)
        with use_clock.compile():
            step = composite_mod.make_train_step(
                cfg, use_mesh, virtual_stages=use_v, gather_mode=use_gather)
            p, loss = step(params0, use_ids)  # first call compiles
            jax.block_until_ready(loss)
        mem = None
        try:  # jit cache is warm; this only re-runs the (cached) AOT path
            from kubeflow_tpu.training.flops import memory_stats

            mem = memory_stats(step.lower(params0, use_ids).compile())
        except Exception:
            mem = None
        use_clock.mark()
        results = {}

        def window():
            with use_clock.compute():
                p, loss = params0, None
                for _ in range(timed_steps):
                    p, loss = step(p, use_ids)
                jax.block_until_ready(loss)
            with use_clock.fetch():
                results["loss"] = float(loss)
            use_clock.end_step()

        def check():
            import math
            if not math.isfinite(results.get("loss", float("nan"))):
                raise RuntimeError(f"non-finite {label} bench loss: {results}")

        window.check = check
        total, _times = _timed_windows(window, _repeats())
        return total / timed_steps, results["loss"], mem

    dt, loss, mem = timed_run(mesh, virtual_stages, gather_mode, ids,
                              "multichip", clock)
    tokens_per_step = num_micro * mb * cfg.seq
    tok_per_chip = tokens_per_step / dt / n_dev

    # Weak-scaling reference: ONE device, same per-chip token load
    # (mb/n_dev), full model, no pipeline — what this chip would do alone.
    scaling_efficiency = tok_1chip = None
    mb1 = max(1, mb // n_dev)
    if os.environ.get("BENCH_MC_1CHIP", "1") == "1":
        mesh1 = make_mesh(MeshConfig(), devices=[devices[0]])
        ids1 = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1),
                               (num_micro, mb1, cfg.seq), 0, cfg.vocab_size),
            composite_mod.batch_sharding(mesh1))
        clock_ref = StepClock(metrics=METRICS.namespace("multichip_ref"),
                              tracer=TRACER, span_name="bench.1chip_ref")
        dt1, _, _ = timed_run(mesh1, 1, "eager", ids1, "1chip", clock_ref)
        tok_1chip = num_micro * mb1 * cfg.seq / dt1
        scaling_efficiency = tok_per_chip / tok_1chip

    stats = schedule_stats(num_micro, sizes["pipe"], virtual_stages)
    stats_gpipe = schedule_stats(num_micro, sizes["pipe"], 1)
    comm = composite_comm_bytes(cfg, mesh, num_micro, mb,
                                virtual_stages=virtual_stages,
                                gather_mode=gather_mode)
    clock.note("tokens_per_sec_per_chip", tok_per_chip)
    clock.note("bubble_fraction", stats["bubble_fraction"])
    if scaling_efficiency is not None:
        clock.note("scaling_efficiency", scaling_efficiency)
    for axis, b in comm.items():
        clock.note(f"comm_bytes_{axis}", b)

    flops = composite_step_flops(cfg, tokens_per_step)
    from kubeflow_tpu.training.flops import detect_generation

    gen = detect_generation()
    if mem:
        try:
            from kubeflow_tpu.training.attribution import record_step_peak_hbm

            record_step_peak_hbm(mem, metrics=METRICS.namespace("multichip"))
        except Exception:
            pass
    # fractions-only attribution: no per-module walk for the composite
    # (pipeline stages aren't flax blocks), but the step decomposition
    # still rides along so the row explains its own wall clock
    attribution = _attribution_row(lambda: [], clock, timed_steps, gen)
    return {
        "tokens_per_sec_per_chip": tok_per_chip,
        "tokens_per_sec_1chip": tok_1chip,
        "scaling_efficiency": scaling_efficiency,
        "n_devices": n_dev,
        "mesh": sizes,
        "virtual_stages": virtual_stages,
        "gather_mode": gather_mode,
        "num_micro": num_micro,
        "microbatch": mb,
        "microbatch_1chip": mb1,
        "seq": cfg.seq,
        "n_layers": cfg.n_layers,
        "d_model": cfg.d_model,
        "bubble_fraction": stats["bubble_fraction"],
        "bubble_fraction_gpipe": stats_gpipe["bubble_fraction"],
        "comm_bytes_per_step": {k: round(v) for k, v in comm.items()},
        "flops_per_step": flops,
        "step_seconds": dt,
        "loss": loss,
        "step_breakdown": _step_breakdown(clock, timed_steps),
        "peak_hbm_bytes": (mem or {}).get("peak_hbm_bytes"),
        "memory": mem,
        "attribution": attribution,
    }


def _run_multichip(platform: str) -> dict:
    try:
        r = _bench_multichip()
        return _emit({
            "metric": f"multichip_composite_tokens_per_sec_per_chip_{r['n_devices']}dev",
            "value": round(r["tokens_per_sec_per_chip"], 1),
            "unit": "tokens_per_sec_per_chip",
            "vs_baseline": None,  # reference publishes no multichip numbers
            "scaling_efficiency": (round(r["scaling_efficiency"], 4)
                                   if r["scaling_efficiency"] is not None else None),
            "tokens_per_sec_1chip": (round(r["tokens_per_sec_1chip"], 1)
                                     if r["tokens_per_sec_1chip"] is not None else None),
            "n_devices": r["n_devices"],
            "mesh": r["mesh"],
            "virtual_stages": r["virtual_stages"],
            "gather_mode": r["gather_mode"],
            "num_micro": r["num_micro"],
            "microbatch": r["microbatch"],
            "bubble_fraction": round(r["bubble_fraction"], 4),
            "bubble_fraction_gpipe": round(r["bubble_fraction_gpipe"], 4),
            "comm_bytes_per_step": r["comm_bytes_per_step"],
            "loss": round(r["loss"], 4),
            "step_breakdown": r["step_breakdown"],
            "peak_hbm_bytes": r.get("peak_hbm_bytes"),
            "attribution": r.get("attribution"),
            "platform": platform,
        })
    except Exception as e:
        return _emit({"metric": "multichip_composite_tokens_per_sec_per_chip",
                      "value": 0.0, "unit": "tokens_per_sec_per_chip",
                      "vs_baseline": None, "error": str(e)[:200]})


def _emit(row: dict) -> dict:
    print(json.dumps(row), flush=True)
    return row


def _run_resnet(platform: str) -> dict:
    last_err = None
    for batch in _batch_candidates():
        try:
            r = _bench(batch)
            return _emit({
                "metric": f"resnet50_train_mfu_{r['generation']}_1chip",
                "value": round(r["mfu"] * 100, 2),
                "unit": "percent_mfu",
                "vs_baseline": round(r["mfu"] / TARGET_MFU, 4),
                "images_per_sec_per_chip": round(r["images_per_sec_per_chip"], 1),
                "batch": r["batch"],
                "window_mfus": r.get("window_mfus"),
                "fused_blocks": r.get("fused_blocks"),
                "fused_calibration": r.get("fused_calibration"),
                "autotune": r.get("autotune"),
                "step_breakdown": r.get("step_breakdown"),
                "peak_hbm_bytes": r.get("peak_hbm_bytes"),
                "attribution": r.get("attribution"),
                "platform": platform,
            })
        except Exception as e:  # OOM at this batch -> try smaller
            last_err = e
    return _emit({"metric": "resnet50_train_mfu", "value": 0.0, "unit": "percent_mfu",
                  "vs_baseline": 0.0, "error": str(last_err)[:200]})


def _run_gpt(platform: str, allow_legacy_batch: bool = False) -> dict:
    # BENCH_GPT_BATCH disambiguates from the resnet BENCH_BATCH in suite
    # mode; BENCH_MODEL=gpt keeps honoring BENCH_BATCH (the round-3 knob).
    legacy = os.environ.get("BENCH_BATCH") if allow_legacy_batch else None
    batch = int(os.environ.get("BENCH_GPT_BATCH") or legacy or "8")
    seq = int(os.environ.get("BENCH_SEQ", "1024"))
    try:
        r = _bench_gpt(batch, seq)
        return _emit({
            "metric": f"gpt2_medium_train_mfu_{r['generation']}_1chip",
            "value": round(r["mfu"] * 100, 2),
            "unit": "percent_mfu",
            "vs_baseline": round(r["mfu"] / TARGET_MFU, 4),
            "tokens_per_sec_per_chip": round(r["tokens_per_sec_per_chip"], 1),
            "batch": r["batch"], "seq": r["seq"],
            "window_mfus": r.get("window_mfus"),
            "scan_blocks": r.get("scan_blocks"),
            "remat": r.get("remat"),
            "fused_loss": r.get("fused_loss"),
            "autotune": r.get("autotune"),
            "step_breakdown": r.get("step_breakdown"),
            "peak_hbm_bytes": r.get("peak_hbm_bytes"),
            "attribution": r.get("attribution"),
            "platform": platform,
        })
    except Exception as e:
        return _emit({"metric": "gpt2_medium_train_mfu", "value": 0.0,
                      "unit": "percent_mfu", "vs_baseline": 0.0,
                      "error": str(e)[:200]})


def _run_serving(platform: str) -> dict:
    """Serving rows condensed for the summary: BERT HTTP p50 at batch 8 and
    KV-decode tokens/s at batch 8 (full sweep on the per-metric line)."""
    try:
        from e2e.serving_bench import (bench_bert_http, bench_continuous,
                                       bench_disagg, bench_gpt_decode)

        bert = bench_bert_http()
        decode = bench_gpt_decode()
        cont = (bench_continuous()
                if os.environ.get("BENCH_CONTINUOUS", "1") == "1" else None)
        disagg = (bench_disagg()
                  if os.environ.get("BENCH_DISAGG", "1") == "1" else None)
        b8 = next((r for r in bert if r["batch"] == 8), bert[-1])
        d8 = next((r for r in decode if r["batch"] == 8), decode[-1])
        return _emit({
            "metric": "serving_gpt_kv_decode_tokens_per_sec_b8",
            "value": d8["decode_tokens_per_sec"],
            "unit": "tokens_per_sec",
            "vs_baseline": None,  # reference publishes no serving numbers (BASELINE.md)
            "bert_http_p50_ms_b8": b8["p50_ms"],
            "bert_http_rows": bert,
            "decode_rows": decode,
            "continuous_batching": cont,
            # SLO quantiles from the engine run's histograms (registry
            # bucket interpolation — the serving row's latency headline)
            "ttft_p50": cont.get("ttft_p50") if cont else None,
            "ttft_p99": cont.get("ttft_p99") if cont else None,
            "queue_wait_p99": cont.get("queue_wait_p99") if cont else None,
            # paged/chunked/speculative knob readout (ISSUE 12): the spec
            # accept rate rides into the summary line so the bench gate can
            # track it round over round
            "spec_accept_rate": cont.get("spec_accept_rate") if cont else None,
            # disaggregated heterogeneous-mix pass (ISSUE 18): aggregate
            # decode tok/s across two multiplexed models with prefill/decode
            # pools and the quantized KV handoff in the serving path
            "disagg": disagg,
            "decode_tok_s_heterogeneous": (
                disagg.get("decode_tok_s_heterogeneous") if disagg else None),
            "kv_handoff_p99_s": (
                disagg.get("kv_handoff_p99_s") if disagg else None),
            "platform": platform,
        })
    except Exception as e:
        return _emit({"metric": "serving_gpt_kv_decode_tokens_per_sec_b8", "value": 0.0,
                      "unit": "tokens_per_sec", "vs_baseline": 0.0, "error": str(e)[:200]})


def _run_hpo(platform: str) -> dict:
    """Real-objective HPO study throughput (BASELINE Katib row: trials/hour)."""
    try:
        from e2e.studyjob_driver import run_studyjob_e2e

        max_trials = int(os.environ.get("BENCH_HPO_TRIALS", "16"))
        early = os.environ.get("BENCH_HPO_EARLYSTOP", "1") == "1"
        status = run_studyjob_e2e(
            "mnist", max_trials=max_trials, parallel=4, timeout=900.0,
            early_stopping=early)
        return _emit({
            "metric": "hpo_mnist_trials_per_hour",
            "value": status["trialsPerHour"],
            "unit": "trials_per_hour",
            "vs_baseline": None,  # reference publishes no Katib throughput (BASELINE.md)
            "trials": max_trials,
            "trials_succeeded": status.get("trialsSucceeded"),
            "trials_pruned": status.get("trialsPruned", 0),
            "elapsed_seconds": status["elapsedSeconds"],
            "best_accuracy": (status.get("currentOptimalTrial") or {})
                .get("observation", {}).get("accuracy"),
            "platform": platform,
        })
    except Exception as e:
        return _emit({"metric": "hpo_mnist_trials_per_hour", "value": 0.0,
                      "unit": "trials_per_hour", "vs_baseline": 0.0,
                      "error": str(e)[:200]})


def main() -> int:
    """Default: run EVERY flagship bench, one JSON line each, then a final
    summary line holding all of them (VERDICT r3 #2: the driver keeps the
    last line — it must carry the build's actual best numbers, not just the
    ResNet row). ``BENCH_MODEL=resnet|gpt|serving|hpo|multichip`` runs one
    bench only; the multichip row joins the suite when >1 device is up."""
    platform = jax.devices()[0].platform
    mode = os.environ.get("BENCH_MODEL", "all")
    if mode == "serving":
        from e2e.serving_bench import main as serving_main

        return serving_main()
    if mode == "gpt":
        r = _run_gpt(platform, allow_legacy_batch=True)
        return 0 if not r.get("error") else 1
    if mode == "hpo":
        r = _run_hpo(platform)
        return 0 if not r.get("error") else 1
    if mode == "resnet":
        r = _run_resnet(platform)
        return 0 if not r.get("error") else 1
    if mode == "multichip":
        r = _run_multichip(platform)
        return 0 if not r.get("error") else 1

    skip = set(filter(None, os.environ.get("BENCH_SKIP", "").split(",")))
    benches = [("resnet", _run_resnet), ("gpt", _run_gpt),
               ("serving", _run_serving), ("hpo", _run_hpo)]
    if len(jax.devices()) > 1:  # multichip row only means something on >1 chip
        benches.append(("multichip", _run_multichip))
    rows = {}
    for name, fn in benches:
        if name in skip:
            continue
        rows[name] = fn(platform)

    resnet = rows.get("resnet", {})
    gpt = rows.get("gpt", {})
    summary = {
        # Headline stays the ResNet north-star (comparable across rounds);
        # the other flagship numbers ride along on the same driver-parsed line.
        "metric": resnet.get("metric", "resnet50_train_mfu"),
        "value": resnet.get("value", 0.0),
        "unit": "percent_mfu",
        "vs_baseline": resnet.get("vs_baseline", 0.0),
        "images_per_sec_per_chip": resnet.get("images_per_sec_per_chip"),
        "gpt2_medium_mfu_pct": gpt.get("value"),
        "gpt2_medium_tokens_per_sec": gpt.get("tokens_per_sec_per_chip"),
        "serving_decode_tokens_per_sec_b8": rows.get("serving", {}).get("value"),
        "serving_bert_p50_ms_b8": rows.get("serving", {}).get("bert_http_p50_ms_b8"),
        "serving_ttft_p99_s": rows.get("serving", {}).get("ttft_p99"),
        "spec_accept_rate": rows.get("serving", {}).get("spec_accept_rate"),
        "decode_tok_s_heterogeneous": rows.get("serving", {}).get(
            "decode_tok_s_heterogeneous"),
        "kv_handoff_p99_s": rows.get("serving", {}).get("kv_handoff_p99_s"),
        "hpo_trials_per_hour": rows.get("hpo", {}).get("value"),
        "multichip_tokens_per_sec_per_chip": rows.get("multichip", {}).get("value"),
        "multichip_scaling_efficiency": rows.get("multichip", {}).get("scaling_efficiency"),
        "platform": platform,
        "errors": {k: v["error"] for k, v in rows.items() if v.get("error")} or None,
    }
    _emit(summary)
    return 0 if not summary["errors"] else 1


if __name__ == "__main__":
    sys.exit(main())
