"""Early stopping for StudyJobs: Katib's median stopping rule.

Reference context: the reference's Katib e2e (testing/katib_studyjob_test.py)
drives an external Katib whose early-stopping service implements
median-stop; round 3 shipped suggesters only (VERDICT r3 weak#5), so every
trial ran its full budget. This module adds the pruning half:

Median stopping rule (Google Vizier §3.2 semantics): stop trial T at step s
when T's best objective so far is strictly worse than the MEDIAN of the
running averages (up to step s) of the other trials' observation histories.
Mild and model-free — a trial is only cut when half the field was already
better on average at the same depth.

Wiring (the decision flows through the Trial CR so both execution paths
share it):

- trials report intermediate observations -> ``status.observations``
  (in-process runner) or the ``observations`` annotation (pod reporter),
- StudyJobReconciler applies :func:`should_stop` on every reconcile and
  marks losers with the ``early-stop`` annotation,
- the trial side checks that annotation at its next report and exits with
  its last metrics; the runner records phase ``Pruned``.
"""

from __future__ import annotations

import statistics
from typing import Any, Dict, List, Optional, Sequence, Tuple

EARLY_STOP_ANNOTATION = "early-stop"
OBSERVATIONS_ANNOTATION = "observations"

Observation = Tuple[float, float]  # (step, value)


def parse_early_stopping(spec: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """``spec.earlyStopping`` -> settings dict or None (disabled).

    Shape (Katib's earlyStopping block):
        earlyStopping:
          algorithmName: medianstop
          settings: {minTrials: 3, minStep: 1}
    """
    es = spec.get("earlyStopping") or {}
    algo = es.get("algorithmName")
    if not algo:
        return None
    if algo != "medianstop":
        raise ValueError(f"unknown earlyStopping algorithm {algo!r} (have: medianstop)")
    settings = es.get("settings") or {}
    return {
        "min_trials": int(settings.get("minTrials", 3)),
        "min_step": float(settings.get("minStep", 1)),
    }


def running_average_at(history: Sequence[Observation], step: float) -> Optional[float]:
    vals = [v for s, v in history if s <= step]
    return sum(vals) / len(vals) if vals else None


def should_stop(
    current: Sequence[Observation],
    others: Sequence[Sequence[Observation]],
    *,
    maximize: bool,
    min_trials: int = 3,
    min_step: float = 1,
) -> bool:
    """Median rule: prune when current's best-so-far is worse than the
    median of the other trials' running averages at the same step."""
    if not current:
        return False
    step = current[-1][0]
    if step < min_step:
        return False
    avgs = [a for a in (running_average_at(h, step) for h in others) if a is not None]
    if len(avgs) < min_trials:
        return False
    med = statistics.median(avgs)
    best = max(v for _, v in current) if maximize else min(v for _, v in current)
    return best < med if maximize else best > med


def observations_of(trial: Dict[str, Any]) -> List[Observation]:
    """status.observations -> [(step, value)] (tolerates missing/garbage)."""
    out: List[Observation] = []
    for o in (trial.get("status") or {}).get("observations") or []:
        try:
            out.append((float(o["step"]), float(o["value"])))
        except (KeyError, TypeError, ValueError):
            continue
    return out
