"""Suggestion algorithms: random, grid, and Gaussian-process Bayesian.

Katib's algorithm services are external gRPC processes; here they are
in-process numpy (control-plane side — no accelerator needed; trial
*training* is the TPU part). The Bayesian suggester is a standard GP with
RBF kernel + expected-improvement acquisition over unit-cube-normalized
parameters — enough to beat random search on smooth objectives at the
trial counts the BASELINE configs use (16 parallel trials).
"""

from __future__ import annotations

import itertools
import math
import random as pyrandom
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


@dataclass(frozen=True)
class ParamSpec:
    """One search dimension (StudyJob spec.parameters[] entry)."""

    name: str
    type: str  # "double" | "int" | "categorical" | "discrete"
    min: Optional[float] = None
    max: Optional[float] = None
    step: Optional[float] = None
    values: Sequence[Any] = ()
    log_scale: bool = False

    def validate(self) -> None:
        if self.type in ("double", "int"):
            if self.min is None or self.max is None or self.min > self.max:
                raise ValueError(f"param {self.name}: need min <= max")
            if self.log_scale and self.min <= 0:
                raise ValueError(f"param {self.name}: log scale needs min > 0")
        elif self.type in ("categorical", "discrete"):
            if not self.values:
                raise ValueError(f"param {self.name}: values required")
        else:
            raise ValueError(f"param {self.name}: unknown type {self.type!r}")

    # -- unit-cube encoding (for the GP) ------------------------------------
    def to_unit(self, value: Any) -> float:
        if self.type in ("double", "int"):
            lo, hi = float(self.min), float(self.max)
            if self.log_scale:
                return (math.log(float(value)) - math.log(lo)) / max(
                    math.log(hi) - math.log(lo), 1e-12
                )
            return (float(value) - lo) / max(hi - lo, 1e-12)
        return self.values.index(value) / max(len(self.values) - 1, 1)

    def from_unit(self, u: float) -> Any:
        u = min(max(u, 0.0), 1.0)
        if self.type in ("double", "int"):
            lo, hi = float(self.min), float(self.max)
            if self.log_scale:
                value = math.exp(math.log(lo) + u * (math.log(hi) - math.log(lo)))
            else:
                value = lo + u * (hi - lo)
            if self.type == "int":
                return int(round(value))
            return value
        idx = int(round(u * (len(self.values) - 1)))
        return self.values[idx]

    def grid_points(self, resolution: int = 4) -> List[Any]:
        if self.type in ("categorical", "discrete"):
            return list(self.values)
        if self.type == "int":
            lo, hi = int(self.min), int(self.max)
            if hi - lo + 1 <= resolution:
                return list(range(lo, hi + 1))
        return [self.from_unit(i / (resolution - 1)) for i in range(resolution)]


@dataclass
class Observation:
    params: Dict[str, Any]
    objective: float


class Suggester:
    """Stateful: tell() observations, ask() the next parameter sets."""

    def __init__(self, specs: Sequence[ParamSpec], maximize: bool = True, seed: int = 0):
        for s in specs:
            s.validate()
        self.specs = list(specs)
        self.maximize = maximize
        self.observations: List[Observation] = []
        self._rng = pyrandom.Random(seed)

    def tell(self, params: Dict[str, Any], objective: float) -> None:
        self.observations.append(Observation(params, objective))

    def ask(self, count: int = 1) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def best(self) -> Optional[Observation]:
        if not self.observations:
            return None
        key = (lambda o: o.objective) if self.maximize else (lambda o: -o.objective)
        return max(self.observations, key=key)

    def _random_params(self) -> Dict[str, Any]:
        return {s.name: s.from_unit(self._rng.random()) for s in self.specs}


class RandomSuggester(Suggester):
    def ask(self, count: int = 1) -> List[Dict[str, Any]]:
        return [self._random_params() for _ in range(count)]


class GridSuggester(Suggester):
    def __init__(self, specs, maximize=True, seed=0, resolution: int = 4):
        super().__init__(specs, maximize, seed)
        self._grid = [
            dict(zip([s.name for s in self.specs], combo))
            for combo in itertools.product(*(s.grid_points(resolution) for s in self.specs))
        ]
        self._cursor = 0

    def ask(self, count: int = 1) -> List[Dict[str, Any]]:
        out = self._grid[self._cursor : self._cursor + count]
        self._cursor += len(out)
        return out

    @property
    def exhausted(self) -> bool:
        return self._cursor >= len(self._grid)


class BayesianSuggester(Suggester):
    """GP(RBF) + expected improvement, candidates by random sampling.

    Dimensions are the unit-cube encodings; categorical dims ride along as
    ordinal codes (coarse but standard for small search spaces).
    """

    def __init__(self, specs, maximize=True, seed=0, n_candidates: int = 256,
                 length_scale: float = 0.25, noise: float = 1e-6, n_startup: int = 4):
        super().__init__(specs, maximize, seed)
        self.n_candidates = n_candidates
        self.length_scale = length_scale
        self.noise = noise
        self.n_startup = n_startup

    def _encode(self, params: Dict[str, Any]) -> np.ndarray:
        return np.array([s.to_unit(params[s.name]) for s in self.specs])

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / self.length_scale**2)

    def ask(self, count: int = 1) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        pending: List[np.ndarray] = []
        for _ in range(count):
            if len(self.observations) < self.n_startup:
                params = self._random_params()
                out.append(params)
                pending.append(self._encode(params))
                continue
            X = np.stack(
                [self._encode(o.params) for o in self.observations]
                + pending  # liar strategy: pending points repel new ones
            )
            y = np.array(
                [o.objective for o in self.observations]
                + [self._pessimistic_value()] * len(pending),
                dtype=np.float64,
            )
            if not self.maximize:
                y = -y
            y_mean, y_std = y.mean(), max(y.std(), 1e-9)
            yn = (y - y_mean) / y_std

            K = self._kernel(X, X) + self.noise * np.eye(len(X))
            L = np.linalg.cholesky(K)
            alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))

            cands = np.array(
                [[self._rng.random() for _ in self.specs] for _ in range(self.n_candidates)]
            )
            Ks = self._kernel(cands, X)
            mu = Ks @ alpha
            v = np.linalg.solve(L, Ks.T)
            var = np.clip(1.0 - (v**2).sum(0), 1e-12, None)
            sigma = np.sqrt(var)
            best = yn.max()
            z = (mu - best) / sigma
            ei = sigma * (z * _norm_cdf(z) + _norm_pdf(z))
            pick = cands[int(np.argmax(ei))]
            params = {s.name: s.from_unit(u) for s, u in zip(self.specs, pick)}
            out.append(params)
            pending.append(self._encode(params))
        return out

    def _pessimistic_value(self) -> float:
        vals = [o.objective for o in self.observations]
        return min(vals) if self.maximize else max(vals)


def _norm_pdf(z: np.ndarray) -> np.ndarray:
    return np.exp(-0.5 * z**2) / math.sqrt(2 * math.pi)


def _norm_cdf(z: np.ndarray) -> np.ndarray:
    from math import erf

    return np.vectorize(lambda t: 0.5 * (1 + erf(t / math.sqrt(2))))(z)


ALGORITHMS = {
    "random": RandomSuggester,
    "grid": GridSuggester,
    "bayesianoptimization": BayesianSuggester,
    "bayesian": BayesianSuggester,
}


def make_suggester(algorithm: str, specs: Sequence[ParamSpec], maximize: bool, seed: int = 0) -> Suggester:
    try:
        cls = ALGORITHMS[algorithm.lower()]
    except KeyError:
        raise ValueError(f"unknown algorithm {algorithm!r}; known: {sorted(ALGORITHMS)}") from None
    return cls(specs, maximize=maximize, seed=seed)
