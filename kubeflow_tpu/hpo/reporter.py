"""Trial-side metrics reporter: the process that runs INSIDE a trial pod.

Closes the production reporting loop the round-1 ``TrialPodRunner``
hand-waved ("written by a status updater sidecar in production" — no such
sidecar existed): the trial container entrypoint runs the objective and
PATCHes the result back onto its Trial CR as the ``results`` annotation via
the apiserver REST client, where ``TrialPodRunner`` picks it up and
completes the trial. The reference delegated this entirely to out-of-tree
Katib metrics collectors (testing/katib_studyjob_test.py only ever asserts
the StudyJob reaches Running); here the loop is in-tree and tested
end-to-end on the pod substrate.

Contract (env, injected by TrialPodRunner into the pod spec):
- ``TRIAL_NAME`` / ``TRIAL_NAMESPACE`` — which Trial CR to report to.
- ``TRIAL_PARAMETERS`` — JSON dict of parameter assignments.
- ``TRIAL_OBJECTIVE`` — objective to run: a registered name from
  ``kubeflow_tpu.hpo.trials`` (``mnist``, ``quadratic``) or a
  ``module:function`` path.
- ``APISERVER_URL`` — where to PATCH.

Exit code is the pod-phase signal: 0 → kubelet marks the pod Succeeded,
non-zero → Failed; the annotation carries the numbers.
"""

from __future__ import annotations

import importlib
import json
import logging
import os
import sys
from typing import Any, Callable, Dict, Mapping, Optional

log = logging.getLogger("kubeflow_tpu.hpo.reporter")

RESULTS_ANNOTATION = "results"

#: Registered objective shortcuts (images/trial-jax-tpu runs these on-slice).
OBJECTIVES = {
    "mnist": "kubeflow_tpu.hpo.trials:mnist_objective",
    "quadratic": "kubeflow_tpu.hpo.trials:quadratic_objective",
}


def resolve_objective(name: str) -> Callable[[Dict[str, Any]], Dict[str, float]]:
    """``mnist`` | ``module.path:function`` → callable."""
    path = OBJECTIVES.get(name, name)
    mod_name, sep, fn_name = path.partition(":")
    if not sep:
        raise ValueError(
            f"objective {name!r}: expected a registered name "
            f"({', '.join(sorted(OBJECTIVES))}) or 'module:function'"
        )
    fn = getattr(importlib.import_module(mod_name), fn_name, None)
    if not callable(fn):
        raise ValueError(f"objective {path!r} does not resolve to a callable")
    return fn


def report(
    metrics: Dict[str, float],
    name: str,
    namespace: str,
    url: Optional[str] = None,
) -> None:
    """PATCH ``{metric: value}`` onto the Trial's results annotation."""
    from ..apiserver.client import Client
    from ..runtime.bootstrap import connect

    client = Client(connect(url))
    client.patch(
        "katib.kubeflow.org/v1alpha1",
        "Trial",
        name,
        {"metadata": {"annotations": {RESULTS_ANNOTATION: json.dumps(metrics, sort_keys=True)}}},
        namespace,
    )


def report_intermediate(
    step: float,
    metrics: Dict[str, float],
    name: str,
    namespace: str,
    url: Optional[str] = None,
    client=None,
) -> bool:
    """Append (step, metrics) to the Trial's observations annotation and
    return whether to CONTINUE — False once the StudyJob controller marked
    this trial with the early-stop annotation (median stopping,
    hpo/earlystop.py). The trial then exits 0 with its last metrics."""
    from ..api import meta as apimeta
    from ..apiserver.client import Client
    from ..hpo.earlystop import EARLY_STOP_ANNOTATION, OBSERVATIONS_ANNOTATION
    from ..runtime.bootstrap import connect

    api = "katib.kubeflow.org/v1alpha1"
    client = client or Client(connect(url))
    trial = client.get(api, "Trial", name, namespace)
    annotations = apimeta.annotations_of(trial)
    try:
        obs = json.loads(annotations.get(OBSERVATIONS_ANNOTATION) or "[]")
    except ValueError:
        obs = []
    # the OBJECTIVE metric, not whichever dict entry comes first — median
    # stopping on the wrong metric would prune the best trials of a
    # minimize study
    metric_name = trial.get("spec", {}).get("objectiveMetricName", "objective")
    value = metrics.get(metric_name)
    if not isinstance(value, (int, float)):
        value = next((v for v in metrics.values() if isinstance(v, (int, float))), None)
    obs.append({"step": float(step), "value": value, "metrics": metrics})
    client.patch(
        api, "Trial", name,
        {"metadata": {"annotations": {OBSERVATIONS_ANNOTATION: json.dumps(obs)}}},
        namespace,
    )
    fresh = client.get(api, "Trial", name, namespace)
    return EARLY_STOP_ANNOTATION not in apimeta.annotations_of(fresh)


def main(env: Optional[Mapping[str, str]] = None) -> int:
    """Run the objective named by the environment and report the metrics.

    ``env`` is injectable so the pod-substrate e2e can execute trial pods
    in-process with the pod's own env (the fake kubelet has no containers).
    """
    env = env or os.environ
    name = env.get("TRIAL_NAME", "")
    namespace = env.get("TRIAL_NAMESPACE", "")
    if not name or not namespace:
        log.error("TRIAL_NAME / TRIAL_NAMESPACE not set; not running under a trial pod")
        return 2
    try:
        import inspect

        params = json.loads(env.get("TRIAL_PARAMETERS") or "{}")
        objective = resolve_objective(env.get("TRIAL_OBJECTIVE", "mnist"))
        kwargs = {}
        try:
            accepts_report = "report_fn" in inspect.signature(objective).parameters
        except (TypeError, ValueError):
            accepts_report = False
        if accepts_report:
            url = env.get("APISERVER_URL")

            def report_fn(step, metrics):
                try:
                    return report_intermediate(step, metrics, name, namespace, url=url)
                except Exception:
                    log.exception("intermediate report failed; continuing")
                    return True

            kwargs["report_fn"] = report_fn
        metrics = objective(params, **kwargs)
        if not isinstance(metrics, dict) or not metrics:
            raise ValueError(f"objective returned {metrics!r}, expected a non-empty dict")
    except Exception:
        log.exception("trial %s/%s: objective failed", namespace, name)
        return 1
    try:
        report(metrics, name, namespace, url=env.get("APISERVER_URL"))
    except Exception:
        log.exception("trial %s/%s: reporting failed", namespace, name)
        return 1
    log.info("trial %s/%s reported %s", namespace, name, metrics)
    return 0


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    sys.exit(main())
