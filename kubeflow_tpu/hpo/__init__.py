"""Hyperparameter optimization: the platform's Katib-class subsystem.

The reference only *drives* Katib from e2e tests (testing/
katib_studyjob_test.py launches an external StudyJob controller and waits
for Running). Here the StudyJob subsystem is in-tree: suggestion algorithms
(random/grid/bayesian), a StudyJob controller materializing trial pods on
TPU slices, and an in-process trial executor for CPU CI.
"""

from kubeflow_tpu.hpo.suggest import (  # noqa: F401
    BayesianSuggester,
    GridSuggester,
    ParamSpec,
    RandomSuggester,
    make_suggester,
)
from kubeflow_tpu.hpo.earlystop import should_stop as median_should_stop  # noqa: F401
