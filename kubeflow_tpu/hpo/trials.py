"""Trial workloads: the objective functions StudyJobs optimize.

Each is a short real JAX training run returning {metric: value}. On TPU
pods these run under the injected slice env (the trial pod path); in CPU CI
the InProcessTrialRunner calls them directly — mirroring how the
reference's katib e2e uses an MNIST job it only ever runs on CPU
(katib_studyjob_test.py).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import optax


def mnist_objective(
    params: Dict[str, Any],
    steps: int = 30,
    batch: int = 64,
    report_fn=None,
    report_every: int = 5,
) -> Dict[str, float]:
    """Train MnistCNN briefly on synthetic data; returns final accuracy/loss.

    Tunable params: lr (double), dropout (double), width (int).
    Synthetic labels are a deterministic function of the input so the task
    is learnable and hyperparameters matter.

    ``report_fn(step, {metric: value}) -> bool`` (optional) receives
    intermediate metrics every ``report_every`` steps; returning False stops
    the run early (median-stopping — hpo/earlystop.py) and the last metrics
    become the trial's (censored) result.
    """
    from kubeflow_tpu.models import MnistCNN
    from kubeflow_tpu.training import ClassifierTask

    lr = float(params.get("lr", 1e-3))
    dropout = float(params.get("dropout", 0.1))
    width = int(params.get("width", 16))
    steps = int(params.get("steps", steps))

    rng = jax.random.PRNGKey(0)
    model = MnistCNN(width=width, dropout_rate=dropout, dtype=jnp.float32)
    task = ClassifierTask(model=model, optimizer=optax.adam(lr))

    imgs = jax.random.normal(rng, (batch, 28, 28, 1))
    labels = (jnp.abs(imgs).sum((1, 2, 3)) * 7).astype(jnp.int32) % 10
    state = task.init(rng, imgs)
    step = task.make_train_step()
    metrics = {}
    for i in range(steps):
        state, metrics = step(state, imgs, labels)
        if report_fn is not None and (i + 1) % report_every == 0 and i + 1 < steps:
            cont = report_fn(i + 1, {"accuracy": float(metrics["accuracy"]),
                                     "loss": float(metrics["loss"])})
            if cont is False:
                break
    return {
        "accuracy": float(metrics["accuracy"]),
        "loss": float(metrics["loss"]),
    }


def quadratic_objective(params: Dict[str, Any]) -> Dict[str, float]:
    """Cheap analytic objective for suggester/controller tests:
    max at lr=0.1, width=32."""
    import math

    lr = float(params.get("lr", 0.0))
    width = float(params.get("width", 0))
    score = math.exp(-((math.log10(max(lr, 1e-9)) + 1) ** 2)) * math.exp(
        -(((width - 32) / 32) ** 2)
    )
    return {"accuracy": score}
