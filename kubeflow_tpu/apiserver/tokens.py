"""python -m kubeflow_tpu.apiserver.tokens — generate a role token table.

Prints a fresh static-token CSV (the kube ``--token-auth-file`` format the
apiserver consumes via ``APISERVER_TOKEN_FILE``) plus the per-role secrets,
ready to paste into the ``kubeflow-tpu-tokens`` Secret
(manifests/apiserver/base/resources.yaml). Roles all join the
``system:kubeflow-tpu`` group, which the seeded bootstrap RBAC binds to
full resource access (auth.py seed_rbac).
"""

from __future__ import annotations

import secrets

from .auth import SERVICE_GROUP

#: Secret key -> service-account user suffix (must match the identities the
#: manifest template ships, manifests/apiserver/base/resources.yaml).
ROLES = {"controllers": "controllers", "webhook": "admission-webhook",
         "webapps": "webapps"}


def main() -> None:
    toks = {role: secrets.token_urlsafe(24) for role in ROLES}
    print("# token-table.csv (APISERVER_TOKEN_FILE)")
    for i, (role, tok) in enumerate(toks.items(), 1):
        print(f'{tok},system:serviceaccount:kubeflow:{ROLES[role]},u{i},"{SERVICE_GROUP}"')
    print("\n# per-role Secret keys (injected as APISERVER_TOKEN)")
    for role, tok in toks.items():
        print(f"{role}: {tok}")


if __name__ == "__main__":
    main()
