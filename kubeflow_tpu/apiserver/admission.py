"""Dynamic admission: stored MutatingWebhookConfiguration objects drive
which external webhooks intercept which writes (VERDICT r4 #5).

The reference registers its PodDefault webhook through a
MutatingWebhookConfiguration with rules + namespaceSelector + failurePolicy
(admission-webhook/manifests/base/mutating-webhook-configuration.yaml:1-23);
the real API server consults those objects on every admission-eligible
request. Round 4 wired the webhook by a ``WEBHOOK_URL`` env instead — static,
no failure semantics, no selectors. This module is the API-server side:

- :func:`dynamic_admission_hook` — a Store admission hook that, per CREATE,
  lists the stored configurations and calls every matching webhook
  (rules: apiGroups/apiVersions/operations/resources; namespaceSelector:
  matchLabels + the four matchExpressions operators against the target
  namespace's labels), applying returned JSONPatches in order.
- failurePolicy per webhook (the seam VERDICT r4 #4 flags): ``Fail``
  rejects the write when the webhook is unreachable — a TPU PodDefault
  whose env injection silently didn't happen boots a wedged multi-host
  gang, so TPU-critical webhooks register with Fail. ``Ignore`` (default,
  matching the manifest) admits but ANNOTATES the object
  (``admission.kubeflow.org/skipped-webhook``) so the skip is observable.
- ``clientConfig.url`` or ``clientConfig.service`` (resolved to cluster
  service DNS); ``caBundle`` (base64 PEM) verifies TLS webhooks.
"""

from __future__ import annotations

import base64
import json
import logging
from typing import Any, Dict, List, Optional

from ..api import meta as apimeta
from ..api.meta import REGISTRY, Resource
from .store import ApiError, Forbidden

log = logging.getLogger("kubeflow_tpu.apiserver.admission")

SKIPPED_ANNOTATION = "admission.kubeflow.org/skipped-webhook"

_MWC = REGISTRY.for_plural("admissionregistration.k8s.io/v1", "mutatingwebhookconfigurations")


def webhook_configuration(
    name: str,
    url: str,
    failure_policy: str = "Fail",
    webhook_name: str = "poddefault.admission.kubeflow.org",
    rules: Optional[List[Dict[str, Any]]] = None,
    namespace_selector: Optional[Dict[str, Any]] = None,
    ca_bundle_b64: Optional[str] = None,
) -> Dict[str, Any]:
    """The standard pod-CREATE MutatingWebhookConfiguration object — one
    builder shared by the env seed, the e2e drivers, and tests so the
    registration schema has a single source."""
    wh: Dict[str, Any] = {
        "name": webhook_name,
        "clientConfig": {"url": url},
        "rules": rules or [{"apiGroups": [""], "apiVersions": ["v1"],
                            "operations": ["CREATE"], "resources": ["pods"]}],
        "failurePolicy": failure_policy,
    }
    if namespace_selector:
        wh["namespaceSelector"] = namespace_selector
    if ca_bundle_b64:
        wh["clientConfig"]["caBundle"] = ca_bundle_b64
    return {
        "apiVersion": "admissionregistration.k8s.io/v1",
        "kind": "MutatingWebhookConfiguration",
        "metadata": {"name": name},
        "webhooks": [wh],
    }


class WebhookCallFailed(ApiError):
    """The API server's 'failed calling webhook' rejection (failurePolicy:
    Fail) — a 500, matching Kubernetes semantics for admission dial errors."""

    def __init__(self, message: str):
        super().__init__(message)
        self.code = 500
        self.reason = "InternalError"


def _rule_matches(rule: Dict[str, Any], op: str, res: Resource) -> bool:
    groups = rule.get("apiGroups", ["*"])
    versions = rule.get("apiVersions", ["*"])
    ops = rule.get("operations", ["*"])
    resources = rule.get("resources", ["*"])
    return (
        ("*" in groups or res.group in groups)
        and ("*" in versions or res.version in versions)
        and ("*" in ops or op in ops)
        and ("*" in resources or res.plural in resources)
    )


def _selector_matches(selector: Optional[Dict[str, Any]], labels: Dict[str, str]) -> bool:
    """LabelSelector (matchLabels + matchExpressions In/NotIn/Exists/
    DoesNotExist) against a label map; empty/absent selector matches all."""
    if not selector:
        return True
    for k, v in (selector.get("matchLabels") or {}).items():
        if labels.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or []:
        key, op, values = expr.get("key", ""), expr.get("operator", ""), expr.get("values") or []
        if op == "In" and labels.get(key) not in values:
            return False
        if op == "NotIn" and labels.get(key) in values:
            return False
        if op == "Exists" and key not in labels:
            return False
        if op == "DoesNotExist" and key in labels:
            return False
    return True


def _webhook_url(client_config: Dict[str, Any]) -> Optional[str]:
    if client_config.get("url"):
        return client_config["url"]
    svc = client_config.get("service")
    if svc:
        # service-based webhooks are always https (K8s semantics); caBundle
        # verifies a private CA, otherwise the system bundle applies
        port = svc.get("port", 443)
        path = svc.get("path", "/")
        return f"https://{svc['name']}.{svc.get('namespace', 'default')}.svc:{port}{path}"
    return None


def call_webhook(url: str, review: Dict[str, Any], timeout: float,
                 ca_bundle_b64: Optional[str] = None) -> Dict[str, Any]:
    """POST an AdmissionReview; returns the response body. Raises OSError/
    URLError/ValueError on transport or decode failure (caller maps to
    failurePolicy)."""
    import urllib.request

    ctx = None
    if url.startswith("https"):
        from ..web.tls import client_context

        ca_data = base64.b64decode(ca_bundle_b64).decode() if ca_bundle_b64 else None
        ctx = client_context(ca_data=ca_data)
    req = urllib.request.Request(
        url, json.dumps(review).encode(), {"content-type": "application/json"}
    )
    with urllib.request.urlopen(req, timeout=timeout, context=ctx) as resp:
        return json.loads(resp.read())


def _apply_response(obj: Dict[str, Any], response: Dict[str, Any]) -> Dict[str, Any]:
    if not response.get("allowed", True):
        # 403, as the Kubernetes API server returns for admission denial —
        # a 5xx would make clients retry a request that can't succeed.
        raise Forbidden(response.get("status", {}).get("message", "admission denied"))
    patch_b64 = response.get("patch")
    if patch_b64:
        from .server import apply_json_patch

        ops = json.loads(base64.b64decode(patch_b64))
        obj = apply_json_patch(obj, ops)
    return obj


def _mark_skipped(obj: Dict[str, Any], webhook_name: str) -> Dict[str, Any]:
    """failurePolicy Ignore: admit, but record the skipped webhook on the
    object — an unmutated pod must be observable, not silent."""
    obj = apimeta.deepcopy(obj)
    ann = obj.setdefault("metadata", {}).setdefault("annotations", {})
    prior = ann.get(SKIPPED_ANNOTATION)
    ann[SKIPPED_ANNOTATION] = f"{prior},{webhook_name}" if prior else webhook_name
    return obj


def dynamic_admission_hook(store, timeout: float = 5.0):
    """Store admission hook driven by stored MutatingWebhookConfigurations.

    Reads the configurations per CREATE (store reads are in-process and
    cheap; no cache invalidation seam needed), so registering/deregistering
    a webhook is just writing the object — no apiserver restart.
    """
    ns_res = REGISTRY.for_plural("v1", "namespaces")

    def hook(op: str, res: Resource, obj: Dict[str, Any]) -> Dict[str, Any]:
        if op != "CREATE":
            return obj
        try:
            configs = store.list(_MWC)
        except ApiError:
            return obj
        if not configs:
            return obj
        ns_labels: Optional[Dict[str, str]] = None
        namespace = apimeta.namespace_of(obj)
        for config in sorted(configs, key=apimeta.name_of):
            for wh in config.get("webhooks") or []:
                rules = wh.get("rules") or []
                if not any(_rule_matches(r, op, res) for r in rules):
                    continue
                selector = wh.get("namespaceSelector")
                if selector and namespace:
                    if ns_labels is None:
                        try:
                            ns_labels = apimeta.labels_of(store.get(ns_res, namespace))
                        except ApiError:
                            ns_labels = {}
                    if not _selector_matches(selector, ns_labels):
                        continue
                url = _webhook_url(wh.get("clientConfig") or {})
                if not url:
                    continue
                name = wh.get("name", apimeta.name_of(config))
                review = {
                    "apiVersion": "admission.k8s.io/v1",
                    "kind": "AdmissionReview",
                    "request": {
                        "uid": "admit-" + (apimeta.name_of(obj) or "unnamed"),
                        "operation": op,
                        "namespace": namespace,
                        "object": obj,
                    },
                }
                wh_timeout = float(wh.get("timeoutSeconds", timeout))
                try:
                    body = call_webhook(
                        url, review, wh_timeout, (wh.get("clientConfig") or {}).get("caBundle"))
                    # patch decode/apply failures are failurePolicy-governed
                    # too (K8s semantics), hence inside this try
                    obj = _apply_response(obj, body.get("response") or {})
                except Forbidden:
                    raise  # explicit denial is an answer, not a failure
                except Exception as e:  # transport/TLS/decode/patch failure
                    # K8s defaults failurePolicy to Fail — a config written
                    # without the field must not silently admit unmutated
                    # pods (the wedged-gang failure mode, VERDICT r4 #4)
                    if wh.get("failurePolicy", "Fail") != "Ignore":
                        raise WebhookCallFailed(
                            f"failed calling webhook {name!r}: {e}") from e
                    log.warning("webhook %s failed (%s); failurePolicy=Ignore", name, e)
                    obj = _mark_skipped(obj, name)
        return obj

    return hook
