"""Remote store: the Store surface over the REST apiserver.

Per-role services (controllers, webhook, web apps) run in their own
processes and talk to ``python -m kubeflow_tpu.apiserver`` through this
client — the analog of the reference's Go binaries using client-go against
the Kubernetes API server. It implements exactly the Store methods that
``Client`` and ``Manager`` consume, so the entire controller runtime works
unchanged against a remote control plane: watches are streamed NDJSON over
chunked HTTP, errors map back to the same ApiError taxonomy, and
``collect_garbage`` is a no-op because the apiserver process owns the GC
sweep (apiserver/server.py run_gc_loop).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Iterator, List, Optional

from ..api import meta as apimeta
from ..api.meta import Resource
from ..runtime.tracing import TRACER, format_traceparent
from .store import (
    ApiError,
    Conflict,
    Expired,
    Forbidden,
    Invalid,
    NotFound,
    ServiceUnavailable,
    TooManyRequests,
    WatchEvent,
)

_ERRORS = {404: NotFound, 409: Conflict, 422: Invalid, 403: Forbidden, 410: Expired,
           429: TooManyRequests, 503: ServiceUnavailable}


def _raise_for(status_body: Dict[str, Any], code: int,
               headers: Optional[Any] = None) -> None:
    cls = _ERRORS.get(code, ApiError)
    err = cls(status_body.get("message", f"HTTP {code}"))
    # Codes without a dedicated class (e.g. server-side 400s) must keep their
    # original status, not inherit ApiError's class-level 500 — a client
    # error reported as InternalError misleads retry/alerting logic.
    if cls is ApiError:
        err.code = code
        err.reason = status_body.get("reason", err.reason)
    # Retryable shedding (429/503) carries the server's Retry-After through
    # to the typed error so backoff honors it instead of guessing — callers
    # (fleet watcher, informers, elastic trainer) distinguish these from
    # fatal 4xx by catching TooManyRequests/ServiceUnavailable.
    if headers is not None and hasattr(err, "retry_after_s"):
        raw = headers.get("Retry-After") if hasattr(headers, "get") else None
        if raw:
            try:
                err.retry_after_s = float(raw)
            except ValueError:
                pass
    raise err


class RemoteWatch:
    """Iterator of WatchEvents over one streaming HTTP response."""

    def __init__(self, resp):
        self._resp = resp
        self.closed = False

    def close(self) -> None:
        self.closed = True
        # Shut the raw socket down FIRST: a reader thread blocked in
        # readinto holds the response's buffer lock, and HTTPResponse.close()
        # would deadlock waiting for it. SHUT_RDWR makes the blocked read
        # return EOF, the reader releases the lock, and close() proceeds.
        try:
            sock = getattr(getattr(self._resp, "fp", None), "raw", None)
            sock = getattr(sock, "_sock", None)
            if sock is not None:
                import socket as _socket

                sock.shutdown(_socket.SHUT_RDWR)
        except OSError:
            pass
        except Exception:
            pass
        try:
            self._resp.close()
        except Exception:
            pass

    def __iter__(self) -> Iterator[WatchEvent]:
        from http.client import HTTPException

        try:
            for line in self._resp:
                if not line.strip():
                    continue
                rec = json.loads(line)
                if rec["type"] == "BOOKMARK":  # server liveness heartbeat
                    continue
                yield WatchEvent(rec["type"], rec["object"])
        except (OSError, ValueError, HTTPException):
            # torn-down connection (incl. IncompleteRead mid-chunk) — the
            # stream just ends; the consumer re-watches/relists
            return
        finally:
            self.close()


class RemoteStore:
    def __init__(self, base_url: str, timeout: float = 30.0, token: Optional[str] = None,
                 ca_file: Optional[str] = None, flow: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        # Flow identity for the apiserver's priority-and-fairness gate
        # (fairness.py): sent as X-Flow-Client on every request. Env default
        # (APISERVER_FLOW) so per-role processes declare their flow without
        # call-site changes; None = classified from the auth identity.
        import os as _os

        self.flow = flow if flow is not None else _os.environ.get("APISERVER_FLOW") or None
        # Role identity for the apiserver's token/RBAC gate (auth.py). Env
        # default so every role picks up its manifest-mounted token without
        # call-site changes; None = anonymous (open/dev apiserver).
        import os

        self.token = token if token is not None else os.environ.get("APISERVER_TOKEN") or None
        # https apiservers are verified against APISERVER_CA_FILE (a path)
        # or APISERVER_CA_DATA (inline PEM from a Secret key) — web/tls.py
        # contract; never unverified. A client with neither falls back to
        # the system bundle (real-CA deployments).
        self._ssl_context = None
        if self.base_url.startswith("https"):
            from ..web.tls import client_context

            self._ssl_context = client_context(
                ca_file if ca_file is not None else os.environ.get("APISERVER_CA_FILE") or None,
                os.environ.get("APISERVER_CA_DATA") or None,
            )

    # -- wire helpers --------------------------------------------------------
    @staticmethod
    def now() -> str:
        return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())

    def _path(self, res: Resource, namespace: Optional[str], name: Optional[str] = None,
              subresource: Optional[str] = None) -> str:
        prefix = f"/api/{res.version}" if not res.group else f"/apis/{res.group}/{res.version}"
        parts = [prefix]
        if res.namespaced and namespace:
            parts.append(f"namespaces/{namespace}")
        parts.append(res.plural)
        if name:
            parts.append(name)
        if subresource:
            parts.append(subresource)
        return "/".join(parts)

    def _request(self, method: str, path: str, body: Optional[Dict] = None,
                 query: str = "", timeout: Optional[float] = None):
        url = self.base_url + path + (f"?{query}" if query else "")
        data = json.dumps(body).encode() if body is not None else None
        headers = {"content-type": "application/json"}
        if self.token:
            headers["authorization"] = f"Bearer {self.token}"
        if self.flow:
            headers["x-flow-client"] = self.flow
        # Propagate the caller's trace across the hop: the apiserver's
        # dispatch span continues this header, so a reconcile's writes show
        # up inside the reconcile trace instead of dying at the process
        # boundary.
        cur = TRACER.current_span()
        if cur is not None:
            headers["traceparent"] = format_traceparent(cur)
        req = urllib.request.Request(url, data=data, method=method, headers=headers)
        try:
            return urllib.request.urlopen(
                req, timeout=timeout or self.timeout, context=self._ssl_context)
        except urllib.error.HTTPError as e:
            payload = e.read()
            try:
                status = json.loads(payload)
            except ValueError:
                status = {"message": payload.decode(errors="replace")}
            _raise_for(status, e.code, headers=e.headers)

    def _json(self, method: str, path: str, body: Optional[Dict] = None, query: str = "") -> Any:
        with self._request(method, path, body, query) as resp:
            payload = resp.read()
        return json.loads(payload) if payload else None

    # -- Store surface -------------------------------------------------------
    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        res = apimeta.REGISTRY.for_object(obj)
        return self._json("POST", self._path(res, apimeta.namespace_of(obj)), obj)

    def get(self, res: Resource, name: str, namespace: Optional[str] = None) -> Dict[str, Any]:
        return self._json("GET", self._path(res, namespace, name))

    def list(
        self,
        res: Resource,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        field_selector: Optional[Dict[str, str]] = None,
    ) -> List[Dict[str, Any]]:
        query = ""
        if label_selector:
            sel = ",".join(f"{k}={v}" for k, v in sorted(label_selector.items()))
            query = "labelSelector=" + urllib.request.quote(sel)
        items = self._json("GET", self._path(res, namespace), query=query)["items"]
        if field_selector:
            from .store import _match_fields

            items = [o for o in items if _match_fields(o, field_selector)]
        return items

    def list_page(
        self,
        res: Resource,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        field_selector: Optional[Dict[str, str]] = None,
        limit: Optional[int] = None,
        continue_token: Optional[str] = None,
    ):
        """One page of a paginated LIST — the Store.list_page surface over
        the wire (``limit``/``continue`` query params). Returns
        (items, rv, next_token); a stale token surfaces as Expired (410)."""
        params = []
        if limit is not None:
            params.append(f"limit={int(limit)}")
        if continue_token:
            params.append("continue=" + urllib.request.quote(continue_token))
        if label_selector:
            sel = ",".join(f"{k}={v}" for k, v in sorted(label_selector.items()))
            params.append("labelSelector=" + urllib.request.quote(sel))
        doc = self._json("GET", self._path(res, namespace), query="&".join(params))
        items = doc["items"]
        if field_selector:
            from .store import _match_fields

            items = [o for o in items if _match_fields(o, field_selector)]
        md = doc.get("metadata") or {}
        try:
            rv = int(md.get("resourceVersion") or 0)
        except ValueError:
            rv = 0
        return items, rv, md.get("continue") or None

    def update(self, obj: Dict[str, Any], subresource: Optional[str] = None) -> Dict[str, Any]:
        res = apimeta.REGISTRY.for_object(obj)
        path = self._path(res, apimeta.namespace_of(obj), apimeta.name_of(obj), subresource)
        return self._json("PUT", path, obj)

    def update_status(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        return self.update(obj, subresource="status")

    def patch(self, res: Resource, name: str, patch: Dict[str, Any],
              namespace: Optional[str] = None) -> Dict[str, Any]:
        return self._json("PATCH", self._path(res, namespace, name), patch)

    def delete(self, res: Resource, name: str, namespace: Optional[str] = None) -> Dict[str, Any]:
        return self._json("DELETE", self._path(res, namespace, name))

    def delete_collection(
        self, res: Resource, namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> int:
        n = 0
        for obj in self.list(res, namespace=namespace, label_selector=label_selector):
            try:
                self.delete(res, apimeta.name_of(obj), apimeta.namespace_of(obj))
                n += 1
            except NotFound:
                pass
        return n

    def watch(
        self,
        res: Optional[Resource] = None,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        send_initial: bool = False,
        since_rv: Optional[int] = None,
        sync_marker: bool = False,
    ) -> RemoteWatch:
        if res is None:
            raise Invalid("remote watch requires a resource (no cross-kind wildcard on the wire)")
        params = ["watch=true"]
        if send_initial:
            params.append("sendInitial=true")
        if sync_marker:
            params.append("syncMarker=true")
        if since_rv is not None:
            params.append(f"resourceVersion={since_rv}")
        if label_selector:
            sel = ",".join(f"{k}={v}" for k, v in sorted(label_selector.items()))
            params.append("labelSelector=" + urllib.request.quote(sel))
        resp = self._request(
            "GET", self._path(res, namespace), query="&".join(params), timeout=3600.0
        )
        return RemoteWatch(resp)

    def collect_garbage(self) -> int:
        return 0  # the apiserver process runs the sweep

    def register_admission(self, hook) -> None:
        raise RuntimeError(
            "admission runs server-side; deploy the webhook and register it "
            "by creating a MutatingWebhookConfiguration object"
        )

    def wait_ready(self, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        last: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                with self._request("GET", "/healthz", timeout=2.0) as resp:
                    resp.read()
                return
            except Exception as e:
                last = e
                time.sleep(0.2)
        raise TimeoutError(f"apiserver at {self.base_url} not ready: {last}")
