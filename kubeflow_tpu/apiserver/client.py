"""Typed-ish client over the Store.

Controllers and web backends use this interface; it is shaped so an HTTP
implementation against a real Kubernetes API server is a drop-in (same verbs,
same addressing). Mirrors the role of controller-runtime's ``client.Client``
in the reference controllers.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..api import meta as apimeta
from ..api.meta import REGISTRY, Resource
from .store import NotFound, Store


class Client:
    def __init__(self, store: Store):
        self.store = store

    def _res(self, api_version: str, kind: str) -> Resource:
        return REGISTRY.for_kind(api_version, kind)

    # -- verbs --------------------------------------------------------------
    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        return self.store.create(obj)

    def get(self, api_version: str, kind: str, name: str, namespace: Optional[str] = None) -> Dict[str, Any]:
        return self.store.get(self._res(api_version, kind), name, namespace)

    def get_opt(
        self, api_version: str, kind: str, name: str, namespace: Optional[str] = None
    ) -> Optional[Dict[str, Any]]:
        try:
            return self.get(api_version, kind, name, namespace)
        except NotFound:
            return None

    def list(
        self,
        api_version: str,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        field_selector: Optional[Dict[str, str]] = None,
    ) -> List[Dict[str, Any]]:
        return self.store.list(
            self._res(api_version, kind),
            namespace=namespace,
            label_selector=label_selector,
            field_selector=field_selector,
        )

    def update(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        return self.store.update(obj)

    def update_status(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        return self.store.update_status(obj)

    def patch(
        self, api_version: str, kind: str, name: str, patch: Dict[str, Any], namespace: Optional[str] = None
    ) -> Dict[str, Any]:
        return self.store.patch(self._res(api_version, kind), name, patch, namespace)

    def delete(self, api_version: str, kind: str, name: str, namespace: Optional[str] = None) -> Dict[str, Any]:
        return self.store.delete(self._res(api_version, kind), name, namespace)

    def delete_opt(self, api_version: str, kind: str, name: str, namespace: Optional[str] = None) -> None:
        try:
            self.delete(api_version, kind, name, namespace)
        except NotFound:
            pass

    def watch(self, api_version: str, kind: str, namespace: Optional[str] = None, **kw):
        return self.store.watch(self._res(api_version, kind), namespace=namespace, **kw)

    # -- helpers ------------------------------------------------------------
    def create_or_get(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        try:
            return self.create(obj)
        except Exception:
            return self.get(
                apimeta.api_version_of(obj), obj["kind"], apimeta.name_of(obj), apimeta.namespace_of(obj)
            )

    def emit_event(
        self,
        involved: Dict[str, Any],
        reason: str,
        message: str,
        type_: str = "Normal",
        component: str = "kubeflow-tpu",
    ) -> Dict[str, Any]:
        """Record a v1 Event against an object (reference mirrors pod events
        onto Notebook CRs — notebook_controller.go:90-109)."""
        ns = apimeta.namespace_of(involved) or "default"
        ev = apimeta.new_object(
            "v1",
            "Event",
            name="",
            namespace=ns,
        )
        ev["metadata"]["generateName"] = f"{apimeta.name_of(involved)}."
        ev.update(
            {
                "involvedObject": {
                    "apiVersion": apimeta.api_version_of(involved),
                    "kind": involved.get("kind"),
                    "name": apimeta.name_of(involved),
                    "namespace": ns,
                    "uid": apimeta.uid_of(involved),
                },
                "reason": reason,
                "message": message,
                "type": type_,
                "source": {"component": component},
                "firstTimestamp": Store.now(),
                "lastTimestamp": Store.now(),
                "count": 1,
            }
        )
        return self.create(ev)
