"""Typed-ish client over the Store.

Controllers and web backends use this interface; it is shaped so an HTTP
implementation against a real Kubernetes API server is a drop-in (same verbs,
same addressing). Mirrors the role of controller-runtime's ``client.Client``
in the reference controllers.

Retry discipline: every verb retries shed/overloaded responses (429
TooManyRequests / 503 ServiceUnavailable — the retryable pair, never the
fatal 4xx family) with capped exponential backoff and FULL jitter
(delay ~ U(0, min(cap, base·2^attempt)), the AWS-architecture-blog variant
that de-synchronizes a thundering herd), honoring a server-sent
``Retry-After`` as the floor. Transient *connection* failures — refused or
reset while the apiserver restarts, surfaced by RemoteStore as raw
URLError/ConnectionResetError rather than the ApiError taxonomy — ride the
same jittered schedule, so controllers and informers span a restart window
instead of surfacing handler failures. Timeouts are NOT retried (a hung
server is not a restarting one; stacking full client timeouts would park a
reconciler far past the leader-election deadline). The in-process Store
never sheds, so the wrapper only bites against a remote apiserver.
"""

from __future__ import annotations

import http.client
import random
import time
import urllib.error
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..api import meta as apimeta
from ..api.meta import REGISTRY, Resource
from .store import NotFound, ServiceUnavailable, Store, TooManyRequests

#: retry policy defaults — bounded so a dead apiserver fails a verb in
#: seconds, not minutes; informers/reconcilers have their own outer loops
RETRY_MAX_ATTEMPTS = 4
RETRY_BASE_S = 0.1
RETRY_CAP_S = 5.0
#: a malicious/buggy Retry-After must not park a controller for an hour
RETRY_AFTER_CLAMP_S = 30.0


def is_transient_conn_error(exc: BaseException) -> bool:
    """True for connection-refused/reset/aborted-mid-response failures — the
    apiserver-restart window. HTTPError (a real server response) and
    timeouts (a hung, not restarting, server) are excluded on purpose."""
    if isinstance(exc, urllib.error.HTTPError):
        return False
    if isinstance(exc, urllib.error.URLError):
        exc = exc.reason if isinstance(exc.reason, BaseException) else exc
    if isinstance(exc, TimeoutError):  # socket.timeout is an alias
        return False
    return isinstance(exc, (ConnectionRefusedError, ConnectionResetError,
                            BrokenPipeError, ConnectionAbortedError,
                            http.client.RemoteDisconnected,
                            http.client.BadStatusLine))


class Client:
    def __init__(self, store: Store, event_retention: Optional[int] = None,
                 max_retries: int = RETRY_MAX_ATTEMPTS,
                 backoff_base_s: float = RETRY_BASE_S,
                 backoff_cap_s: float = RETRY_CAP_S,
                 retry_sleep: Callable[[float], None] = time.sleep,
                 retry_rng: Optional[random.Random] = None):
        self.store = store
        self._events: Optional["EventRecorder"] = None
        #: overrides EventRecorder's max_events GC cap when set — scale
        #: harnesses raise it so thousands of live gangs keep aggregating
        #: instead of churning the retention GC (see runtime/events.py)
        self.event_retention = event_retention
        self.max_retries = max_retries
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        #: injectable for fake-clock tests; defaults are the real thing
        self._retry_sleep = retry_sleep
        self._retry_rng = retry_rng if retry_rng is not None else random.Random()

    def _res(self, api_version: str, kind: str) -> Resource:
        return REGISTRY.for_kind(api_version, kind)

    def backoff_delay(self, attempt: int, retry_after_s: Optional[float]) -> float:
        """Full-jitter delay for the given (0-based) attempt; a server
        Retry-After is the floor, clamped so it can't park us forever."""
        delay = self._retry_rng.uniform(
            0.0, min(self.backoff_cap_s, self.backoff_base_s * (2.0 ** attempt)))
        if retry_after_s:
            delay = max(delay, min(float(retry_after_s), RETRY_AFTER_CLAMP_S))
        return delay

    def _retrying(self, fn: Callable[[], Any]) -> Any:
        attempt = 0
        while True:
            try:
                return fn()
            except (TooManyRequests, ServiceUnavailable) as e:
                if attempt >= self.max_retries:
                    raise
                from ..runtime.metrics import METRICS  # lazy: import-cycle guard

                METRICS.counter("apiserver_client_retries_total",
                                code=str(e.code)).inc()
                self._retry_sleep(self.backoff_delay(
                    attempt, getattr(e, "retry_after_s", None)))
                attempt += 1
            except (urllib.error.URLError, http.client.BadStatusLine, OSError) as e:
                # connection refused/reset while the apiserver restarts:
                # same jittered schedule, no Retry-After to honor
                if attempt >= self.max_retries or not is_transient_conn_error(e):
                    raise
                from ..runtime.metrics import METRICS  # lazy: import-cycle guard

                METRICS.counter("apiserver_client_retries_total",
                                code="conn").inc()
                self._retry_sleep(self.backoff_delay(attempt, None))
                attempt += 1

    # -- verbs --------------------------------------------------------------
    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        return self._retrying(lambda: self.store.create(obj))

    def get(self, api_version: str, kind: str, name: str, namespace: Optional[str] = None) -> Dict[str, Any]:
        return self._retrying(
            lambda: self.store.get(self._res(api_version, kind), name, namespace))

    def get_opt(
        self, api_version: str, kind: str, name: str, namespace: Optional[str] = None
    ) -> Optional[Dict[str, Any]]:
        try:
            return self.get(api_version, kind, name, namespace)
        except NotFound:
            return None

    def list(
        self,
        api_version: str,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        field_selector: Optional[Dict[str, str]] = None,
    ) -> List[Dict[str, Any]]:
        return self._retrying(lambda: self.store.list(
            self._res(api_version, kind),
            namespace=namespace,
            label_selector=label_selector,
            field_selector=field_selector,
        ))

    def list_paged(
        self,
        api_version: str,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        limit: int = 500,
    ) -> Tuple[List[Dict[str, Any]], int]:
        """Full collection via the paginated LIST path (limit/continue
        tokens, every page pinned to one consistent snapshot). Returns
        (items, snapshot rv) — the informer relist primitive. A stale
        continue token surfaces as Expired (410): restart from page one."""
        res = self._res(api_version, kind)
        items: List[Dict[str, Any]] = []
        token: Optional[str] = None
        rv = 0
        while True:
            page, rv, token = self._retrying(lambda tok=token: self.store.list_page(
                res, namespace=namespace, label_selector=label_selector,
                limit=limit, continue_token=tok))
            items.extend(page)
            if not token:
                return items, rv

    def update(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        return self._retrying(lambda: self.store.update(obj))

    def update_status(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        return self._retrying(lambda: self.store.update_status(obj))

    def patch(
        self, api_version: str, kind: str, name: str, patch: Dict[str, Any], namespace: Optional[str] = None
    ) -> Dict[str, Any]:
        return self._retrying(
            lambda: self.store.patch(self._res(api_version, kind), name, patch, namespace))

    def delete(self, api_version: str, kind: str, name: str, namespace: Optional[str] = None) -> Dict[str, Any]:
        return self._retrying(
            lambda: self.store.delete(self._res(api_version, kind), name, namespace))

    def delete_opt(self, api_version: str, kind: str, name: str, namespace: Optional[str] = None) -> None:
        try:
            self.delete(api_version, kind, name, namespace)
        except NotFound:
            pass

    def watch(self, api_version: str, kind: str, namespace: Optional[str] = None, **kw):
        return self.store.watch(self._res(api_version, kind), namespace=namespace, **kw)

    # -- helpers ------------------------------------------------------------
    def create_or_get(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        try:
            return self.create(obj)
        except Exception:
            return self.get(
                apimeta.api_version_of(obj), obj["kind"], apimeta.name_of(obj), apimeta.namespace_of(obj)
            )

    @property
    def events(self) -> "EventRecorder":
        """The client's EventRecorder (lazy — most clients never emit; the
        import is deferred because runtime/__init__ imports this module)."""
        if self._events is None:
            from ..runtime.events import EventRecorder

            if self.event_retention is not None:
                self._events = EventRecorder(self, max_events=self.event_retention)
            else:
                self._events = EventRecorder(self)
        return self._events

    def emit_event(
        self,
        involved: Dict[str, Any],
        reason: str,
        message: str,
        type_: str = "Normal",
        component: str = "kubeflow-tpu",
    ) -> Optional[Dict[str, Any]]:
        """Record a v1 Event against an object (reference mirrors pod events
        onto Notebook CRs — notebook_controller.go:90-109). Routed through
        the correlating :class:`EventRecorder`: a duplicate (same involved
        object, reason, component, type) bumps ``count``/``lastTimestamp``
        on the existing Event instead of minting a new object. Returns the
        stored Event, or None when the spam filter dropped it."""
        return self.events.emit(involved, reason, message, type_=type_, component=component)
