"""Typed-ish client over the Store.

Controllers and web backends use this interface; it is shaped so an HTTP
implementation against a real Kubernetes API server is a drop-in (same verbs,
same addressing). Mirrors the role of controller-runtime's ``client.Client``
in the reference controllers.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..api import meta as apimeta
from ..api.meta import REGISTRY, Resource
from .store import NotFound, Store


class Client:
    def __init__(self, store: Store, event_retention: Optional[int] = None):
        self.store = store
        self._events: Optional["EventRecorder"] = None
        #: overrides EventRecorder's max_events GC cap when set — scale
        #: harnesses raise it so thousands of live gangs keep aggregating
        #: instead of churning the retention GC (see runtime/events.py)
        self.event_retention = event_retention

    def _res(self, api_version: str, kind: str) -> Resource:
        return REGISTRY.for_kind(api_version, kind)

    # -- verbs --------------------------------------------------------------
    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        return self.store.create(obj)

    def get(self, api_version: str, kind: str, name: str, namespace: Optional[str] = None) -> Dict[str, Any]:
        return self.store.get(self._res(api_version, kind), name, namespace)

    def get_opt(
        self, api_version: str, kind: str, name: str, namespace: Optional[str] = None
    ) -> Optional[Dict[str, Any]]:
        try:
            return self.get(api_version, kind, name, namespace)
        except NotFound:
            return None

    def list(
        self,
        api_version: str,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        field_selector: Optional[Dict[str, str]] = None,
    ) -> List[Dict[str, Any]]:
        return self.store.list(
            self._res(api_version, kind),
            namespace=namespace,
            label_selector=label_selector,
            field_selector=field_selector,
        )

    def update(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        return self.store.update(obj)

    def update_status(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        return self.store.update_status(obj)

    def patch(
        self, api_version: str, kind: str, name: str, patch: Dict[str, Any], namespace: Optional[str] = None
    ) -> Dict[str, Any]:
        return self.store.patch(self._res(api_version, kind), name, patch, namespace)

    def delete(self, api_version: str, kind: str, name: str, namespace: Optional[str] = None) -> Dict[str, Any]:
        return self.store.delete(self._res(api_version, kind), name, namespace)

    def delete_opt(self, api_version: str, kind: str, name: str, namespace: Optional[str] = None) -> None:
        try:
            self.delete(api_version, kind, name, namespace)
        except NotFound:
            pass

    def watch(self, api_version: str, kind: str, namespace: Optional[str] = None, **kw):
        return self.store.watch(self._res(api_version, kind), namespace=namespace, **kw)

    # -- helpers ------------------------------------------------------------
    def create_or_get(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        try:
            return self.create(obj)
        except Exception:
            return self.get(
                apimeta.api_version_of(obj), obj["kind"], apimeta.name_of(obj), apimeta.namespace_of(obj)
            )

    @property
    def events(self) -> "EventRecorder":
        """The client's EventRecorder (lazy — most clients never emit; the
        import is deferred because runtime/__init__ imports this module)."""
        if self._events is None:
            from ..runtime.events import EventRecorder

            if self.event_retention is not None:
                self._events = EventRecorder(self, max_events=self.event_retention)
            else:
                self._events = EventRecorder(self)
        return self._events

    def emit_event(
        self,
        involved: Dict[str, Any],
        reason: str,
        message: str,
        type_: str = "Normal",
        component: str = "kubeflow-tpu",
    ) -> Optional[Dict[str, Any]]:
        """Record a v1 Event against an object (reference mirrors pod events
        onto Notebook CRs — notebook_controller.go:90-109). Routed through
        the correlating :class:`EventRecorder`: a duplicate (same involved
        object, reason, component, type) bumps ``count``/``lastTimestamp``
        on the existing Event instead of minting a new object. Returns the
        stored Event, or None when the spam filter dropped it."""
        return self.events.emit(involved, reason, message, type_=type_, component=component)
