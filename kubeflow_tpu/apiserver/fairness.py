"""API priority and fairness: per-flow admission for the apiserver.

The round-11 scale harness proved the control plane *fast*; this module
makes it *fair*. Without it the apiserver admits requests first-come-
first-served, so one misbehaving tenant (NotebookOS-style interactive
notebook churn hammering LIST/watch) starves scheduler binds and every
other well-behaved client. The design is a compact version of Kubernetes
API Priority and Fairness (KEP-1040):

- **Flow classification** — every request belongs to a *flow* (who) which
  maps to a *priority level* (how important). The flow comes from the
  ``X-Flow-Client`` header when the client states one, else from the
  authenticated identity, else ``anonymous``. ``system:*`` identities
  (scheduler, controllers, podlets) classify into the ``system`` level;
  ``bulk:*`` / ``interactive:*`` / ``notebook:*`` flows into ``low``;
  everything else is ``normal`` workload traffic.
- **Concurrency shares** — each level owns a fixed number of *seats*
  (max concurrently executing requests). Seats are not shared across
  levels, so a flooded ``low`` level can never occupy ``system`` capacity.
- **Shuffle-sharded bounded queues** — a level's waiting requests spread
  over N FIFO queues; each flow hashes to a small *hand* of queues and
  enqueues onto the shortest. A noisy flow fills only its hand while a
  quiet flow in the same level almost surely owns a queue the noisy one
  doesn't touch (the shuffle-sharding isolation argument from the KEP).
- **Overflow rejection** — a full queue rejects with 429 + ``Retry-After``
  (:class:`FlowRejected`); the estimate scales with queue pressure so
  honest clients back off harder as the level saturates.

Metrics: ``apiserver_flowcontrol_dispatched_total`` /
``apiserver_flowcontrol_rejected_total`` /
``apiserver_flowcontrol_queued_total`` (labels ``priority_level``,
``flow``) and ``apiserver_flowcontrol_queue_wait_seconds``
(label ``priority_level``).
"""

from __future__ import annotations

import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..runtime.metrics import METRICS

#: flow-name prefixes that classify into the ``system`` priority level.
#: ``system:anonymous`` / ``system:unauthenticated`` are explicitly NOT
#: system components — an unauthenticated client must not self-promote.
SYSTEM_PREFIX = "system:"
_NOT_SYSTEM = ("system:anonymous", "system:unauthenticated")

#: flow-name prefixes that classify into the ``low`` (bulk/interactive
#: churn) priority level — the NotebookOS-style tenants.
LOW_PREFIXES = ("bulk:", "interactive:", "notebook:", "batch:")

LEVEL_SYSTEM = "system"
LEVEL_NORMAL = "normal"
LEVEL_LOW = "low"


def classify_flow(flow: str) -> str:
    """Flow name -> priority level name (pure function; unit-testable)."""
    if flow.startswith(SYSTEM_PREFIX) and flow not in _NOT_SYSTEM:
        return LEVEL_SYSTEM
    if any(flow.startswith(p) for p in LOW_PREFIXES):
        return LEVEL_LOW
    return LEVEL_NORMAL


@dataclass(frozen=True)
class LevelConfig:
    """Static configuration of one priority level.

    ``seats``: max concurrently executing requests.
    ``queues`` × ``queue_length``: the bounded waiting room.
    ``hand_size``: how many queues one flow may use (shuffle shard).
    """

    name: str
    seats: int
    queues: int = 8
    queue_length: int = 64
    hand_size: int = 2


#: Seat split for the default three-level config. ``system`` gets the
#: largest share (scheduler + podlet + controller fan-out must never wait
#: behind tenants); ``low`` gets a sliver — enough to make progress, small
#: enough that a flood saturates it without touching anyone else.
DEFAULT_LEVELS: Tuple[LevelConfig, ...] = (
    LevelConfig(LEVEL_SYSTEM, seats=16, queues=8, queue_length=128, hand_size=2),
    LevelConfig(LEVEL_NORMAL, seats=12, queues=16, queue_length=64, hand_size=2),
    LevelConfig(LEVEL_LOW, seats=4, queues=16, queue_length=32, hand_size=2),
)


class FlowRejected(Exception):
    """Queue overflow / wait timeout -> shed with 429 + Retry-After."""

    def __init__(self, flow: str, level: str, retry_after_s: float, why: str):
        super().__init__(
            f"flow {flow!r} rejected at priority level {level!r}: {why} "
            f"(retry after {retry_after_s:.0f}s)")
        self.flow = flow
        self.level = level
        self.retry_after_s = retry_after_s


class _Waiter:
    __slots__ = ("event", "granted", "abandoned", "flow", "enqueued_at")

    def __init__(self, flow: str, enqueued_at: float):
        self.event = threading.Event()
        self.granted = False
        self.abandoned = False
        self.flow = flow
        self.enqueued_at = enqueued_at


@dataclass
class Ticket:
    """Proof of an occupied seat; pass back to :meth:`FlowController.release`."""

    flow: str
    level: str
    queued_s: float = 0.0


@dataclass
class _Level:
    cfg: LevelConfig
    executing: int = 0
    waiting: int = 0
    queues: List["object"] = field(default_factory=list)  # List[deque]
    rr: int = 0  # round-robin dispatch cursor across queues


class FlowController:
    """Admission gate the apiserver calls around every resource request.

    ``acquire`` blocks (bounded) until a seat frees up or rejects with
    :class:`FlowRejected`; ``release`` returns the seat and dispatches the
    next queued request round-robin across the level's queues, so no single
    queue (= no single flow hand) monopolizes the dispatch order.
    """

    def __init__(self, levels: Sequence[LevelConfig] = DEFAULT_LEVELS,
                 max_wait_s: float = 15.0, clock=time.monotonic):
        import collections

        self._lock = threading.Lock()
        self._clock = clock
        self.max_wait_s = max_wait_s
        self._levels: Dict[str, _Level] = {}
        for cfg in levels:
            lvl = _Level(cfg=cfg)
            lvl.queues = [collections.deque() for _ in range(max(1, cfg.queues))]
            self._levels[cfg.name] = lvl

    # -- classification ------------------------------------------------------
    def resolve_flow(self, header: Optional[str], user: Optional[str]) -> str:
        return header or user or "anonymous"

    def hand_of(self, level: str, flow: str) -> List[int]:
        """The queue indices this flow may use (deterministic shuffle shard:
        ``hand_size`` independent hashes over the queue count)."""
        lvl = self._levels[level]
        n = len(lvl.queues)
        hand = []
        for i in range(lvl.cfg.hand_size):
            h = zlib.crc32(f"{flow}/{i}".encode()) % n
            if h not in hand:
                hand.append(h)
        return hand

    # -- admission -----------------------------------------------------------
    def admit(self, header: Optional[str], user: Optional[str],
              timeout: Optional[float] = None) -> Ticket:
        flow = self.resolve_flow(header, user)
        return self.acquire(flow, classify_flow(flow), timeout=timeout)

    def acquire(self, flow: str, level_name: str,
                timeout: Optional[float] = None) -> Ticket:
        lvl = self._levels[level_name]
        with self._lock:
            if lvl.executing < lvl.cfg.seats and lvl.waiting == 0:
                lvl.executing += 1
                METRICS.counter("apiserver_flowcontrol_dispatched_total",
                                priority_level=level_name, flow=flow).inc()
                return Ticket(flow, level_name)
            # Shuffle shard: shortest queue in this flow's hand.
            hand = self.hand_of(level_name, flow)
            qi = min(hand, key=lambda i: len(lvl.queues[i]))
            q = lvl.queues[qi]
            if len(q) >= lvl.cfg.queue_length:
                retry = self._retry_after_locked(lvl)
                METRICS.counter("apiserver_flowcontrol_rejected_total",
                                priority_level=level_name, flow=flow).inc()
                raise FlowRejected(flow, level_name, retry, "queue full")
            waiter = _Waiter(flow, self._clock())
            q.append(waiter)
            lvl.waiting += 1
            METRICS.counter("apiserver_flowcontrol_queued_total",
                            priority_level=level_name, flow=flow).inc()
        granted = waiter.event.wait(self.max_wait_s if timeout is None else timeout)
        waited = self._clock() - waiter.enqueued_at
        with self._lock:
            METRICS.histogram("apiserver_flowcontrol_queue_wait_seconds",
                              priority_level=level_name).observe(waited)
            if waiter.granted:
                # (covers the race where the grant landed between the wait
                # timing out and us re-taking the lock: the seat is ours)
                METRICS.counter("apiserver_flowcontrol_dispatched_total",
                                priority_level=level_name, flow=flow).inc()
                return Ticket(flow, level_name, queued_s=waited)
            waiter.abandoned = True  # dispatcher skips us; lazily dropped
            lvl.waiting -= 1
            retry = self._retry_after_locked(lvl)
            METRICS.counter("apiserver_flowcontrol_rejected_total",
                            priority_level=level_name, flow=flow).inc()
        if not granted:
            raise FlowRejected(flow, level_name, retry, "timed out in queue")
        raise FlowRejected(flow, level_name, retry, "not dispatched")

    def release(self, ticket: Ticket) -> None:
        lvl = self._levels[ticket.level]
        with self._lock:
            lvl.executing -= 1
            self._dispatch_locked(lvl)

    def _dispatch_locked(self, lvl: _Level) -> None:
        """Hand freed seats to queued waiters, round-robin across queues so
        every hand gets dispatch turns regardless of per-queue depth."""
        n = len(lvl.queues)
        while lvl.executing < lvl.cfg.seats and lvl.waiting > 0:
            dispatched = False
            for step in range(n):
                q = lvl.queues[(lvl.rr + step) % n]
                while q:
                    waiter = q.popleft()
                    if waiter.abandoned:
                        continue
                    waiter.granted = True
                    lvl.waiting -= 1
                    lvl.executing += 1
                    waiter.event.set()
                    lvl.rr = (lvl.rr + step + 1) % n
                    dispatched = True
                    break
                if dispatched:
                    break
            if not dispatched:
                # every remaining entry was an abandoned husk
                lvl.waiting = 0
                return

    def _retry_after_locked(self, lvl: _Level) -> float:
        """Honest backoff hint: one second per saturated seat-round of
        waiters ahead, clamped to [1, 30] (RFC 7231 delta-seconds)."""
        rounds = lvl.waiting / max(1, lvl.cfg.seats)
        return min(30.0, max(1.0, round(rounds)))

    # -- introspection -------------------------------------------------------
    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """State for ``/debug/fairness``-style surfaces and tests."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for name, lvl in self._levels.items():
                out[name] = {
                    "seats": lvl.cfg.seats,
                    "executing": lvl.executing,
                    "waiting": lvl.waiting,
                    "queues": len(lvl.queues),
                    "queue_length": lvl.cfg.queue_length,
                }
        return out
