"""Write-ahead log + snapshots: durable storage under the apiserver Store.

The reference control plane gets durability from etcd: every mutation is
fsynced to a raft log before the revision is exposed, compaction folds the
log into snapshots, and a restarted member replays the tail to recover both
state and the revision counter. This module rebuilds that bottom layer for
the in-process Store — stdlib-only, one directory on local disk:

- ``WriteAheadLog`` — length-prefixed, crc32-framed journal records,
  fsynced on append; periodic snapshots written with the checkpointer's
  disk discipline (``training/checkpoint.py``): tmp file → fsync → rename
  → fsync parent, newest-complete-wins on recovery, torn tails truncated.
- ``DurableBackend`` — wraps any storage backend (``DictBackend`` by
  default) with the Store's backend protocol. Every ``put``/``delete``
  appends a WAL record and fsyncs **before** the mutation reaches the
  inner backend, so a resourceVersion is never observable (watch event,
  list, /healthz) unless it is already durable. On open it recovers
  bucket state and the monotonic RV counter from the newest complete
  snapshot plus segment replay, and serves ``journal_since`` from the
  replayed + live record window so watches and informers resume from
  their durable RVs across a restart.

Layout of a WAL directory::

    snapshot_<rv>.bin   one framed record: full bucket state as of <rv>
    wal_<rv>.log        framed mutation records with rv > <rv>
    _tmp.*              in-flight snapshot droppings, reclaimed on open

A snapshot at rv S is written (tmp+rename) *before* the segment rolls to
``wal_<S>.log``, so recovery is always "newest complete snapshot + its own
segment" — a crash between the two leaves the previous pair intact and
loses nothing. GC keeps the newest ``keep_snapshots`` complete snapshots
(never fewer than the newest one) and deletes older snapshot/segment pairs.

Frame format (all integers big-endian)::

    [4 bytes payload length][4 bytes crc32(payload)][payload JSON]

A short read or crc mismatch marks the torn tail: everything before it is
the durable prefix, everything from it on is truncated on open (etcd's
WAL does the same for a partially-synced final record).
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import uuid
import zlib
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..api import meta as apimeta
from .backend import DictBackend, JournalExpired, JournalRecord

_FRAME = struct.Struct(">II")  # payload length, crc32(payload)
_SNAP_PREFIX = "snapshot_"
_SEG_PREFIX = "wal_"
_TMP_PREFIX = "_tmp."

#: records appended between snapshots (APISERVER_WAL_SNAPSHOT_EVERY)
SNAPSHOT_EVERY_DEFAULT = 4096
#: in-memory watch-resume window, records (matches the native journal cap)
JOURNAL_CAP_DEFAULT = 8192
#: complete snapshots retained by GC (the newest is never deleted)
KEEP_SNAPSHOTS = 2

#: fsync-dominated: the default 1ms-floor ladder can't resolve an append
_APPEND_BUCKETS = (0.0002, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.5, 1.0)


def _fsync_dir(path: str) -> None:
    """fsync a directory so renames/creates inside it are durable."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def encode_frame(payload: bytes) -> bytes:
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def scan_frames(data: bytes) -> Tuple[List[bytes], int]:
    """(payloads, durable_prefix_length) — stops at the first torn or
    corrupt frame; bytes past the returned offset are the torn tail."""
    payloads: List[bytes] = []
    off = 0
    while off + _FRAME.size <= len(data):
        length, crc = _FRAME.unpack_from(data, off)
        start = off + _FRAME.size
        end = start + length
        if end > len(data):
            break  # short final record: the crash interrupted the write
        payload = data[start:end]
        if zlib.crc32(payload) != crc:
            break  # torn or bit-rotted: nothing past here is trustworthy
        payloads.append(payload)
        off = end
    return payloads, off


class WriteAheadLog:
    """Framed journal segments + snapshots in one directory.

    Opening performs recovery: tmp droppings are reclaimed, the newest
    *complete* snapshot is chosen (crc-validated, incomplete ones are
    skipped, not trusted), its segment's torn tail is truncated in place,
    and the surviving records are exposed as ``base_rv`` / ``state`` /
    ``tail`` for the caller to rebuild from.
    """

    def __init__(
        self,
        directory: str,
        snapshot_every: int = SNAPSHOT_EVERY_DEFAULT,
        keep_snapshots: int = KEEP_SNAPSHOTS,
    ) -> None:
        self.dir = os.path.abspath(directory)
        self.snapshot_every = max(1, int(snapshot_every))
        self.keep_snapshots = max(1, int(keep_snapshots))
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        self._fh = None  # active segment file handle
        self._since_snapshot = 0
        #: recovery surface, consumed by DurableBackend
        self.base_rv = 0
        self.state: Optional[Dict[str, Any]] = None
        self.tail: List[Dict[str, Any]] = []
        self._recover()

    # -- recovery -------------------------------------------------------------

    def _snapshot_rvs(self) -> List[int]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith(_SNAP_PREFIX) and name.endswith(".bin"):
                try:
                    out.append(int(name[len(_SNAP_PREFIX):-len(".bin")]))
                except ValueError:
                    continue
        return sorted(out)

    def _snap_path(self, rv: int) -> str:
        return os.path.join(self.dir, f"{_SNAP_PREFIX}{rv}.bin")

    def _seg_path(self, rv: int) -> str:
        return os.path.join(self.dir, f"{_SEG_PREFIX}{rv}.log")

    def _read_snapshot(self, rv: int) -> Optional[Dict[str, Any]]:
        """Parse a snapshot file; None unless it is one complete frame."""
        try:
            with open(self._snap_path(rv), "rb") as f:
                data = f.read()
        except OSError:
            return None
        payloads, good = scan_frames(data)
        if len(payloads) != 1 or good != len(data):
            return None  # torn/corrupt: fall through to an older snapshot
        try:
            snap = json.loads(payloads[0])
        except ValueError:
            return None
        return snap if snap.get("rv") == rv else None

    def _recover(self) -> None:
        # reclaim in-flight snapshot droppings from a crashed writer
        for name in os.listdir(self.dir):
            if name.startswith(_TMP_PREFIX):
                try:
                    os.remove(os.path.join(self.dir, name))
                except OSError:
                    pass
        # newest complete snapshot wins; incomplete ones are skipped
        for rv in reversed(self._snapshot_rvs()):
            snap = self._read_snapshot(rv)
            if snap is not None:
                self.base_rv, self.state = rv, snap
                break
        # replay the chosen base's segment, truncating any torn tail
        seg = self._seg_path(self.base_rv)
        if os.path.exists(seg):
            with open(seg, "rb") as f:
                data = f.read()
            payloads, good = scan_frames(data)
            if good < len(data):
                with open(seg, "r+b") as f:
                    f.truncate(good)
                    f.flush()
                    os.fsync(f.fileno())
            for payload in payloads:
                try:
                    rec = json.loads(payload)
                except ValueError:
                    continue
                if rec.get("rv", 0) > self.base_rv:  # dup/stale replay guard
                    self.tail.append(rec)
        with self._lock:
            self._fh = open(seg, "ab")
        _fsync_dir(self.dir)

    def drop_recovery_state(self) -> None:
        """Free the recovery surface once the caller has consumed it."""
        self.state, self.tail = None, []

    # -- append / snapshot ----------------------------------------------------

    def append(self, record: Dict[str, Any]) -> None:
        """Frame, write, and fsync one record; durable when this returns."""
        from ..runtime.metrics import METRICS  # lazy: mirrors store.py

        payload = json.dumps(record, separators=(",", ":")).encode()
        frame = encode_frame(payload)
        hist = METRICS.histogram("wal_append_seconds", buckets=_APPEND_BUCKETS)
        start = time.perf_counter()
        with self._lock:
            self._fh.write(frame)
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._since_snapshot += 1
        hist.observe(time.perf_counter() - start)

    def should_snapshot(self) -> bool:
        with self._lock:
            return self._since_snapshot >= self.snapshot_every

    def snapshot(self, rv: int, objects: List[Tuple[str, str, str, Dict[str, Any]]]) -> None:
        """Write a snapshot at ``rv``, roll the segment, GC old pairs.

        ``objects`` is the full bucket state: (bucket, ns, name, obj).
        The snapshot must be durable before the segment rolls — a crash
        between the two recovers from the *new* snapshot with an empty
        segment; a crash before the rename recovers from the old pair.
        """
        from ..runtime.metrics import METRICS  # lazy: mirrors store.py

        payload = json.dumps(
            {"rv": rv, "objects": [[b, ns, n, o] for b, ns, n, o in objects]},
            separators=(",", ":"),
        ).encode()
        with self._lock:
            tmp = os.path.join(self.dir, f"{_TMP_PREFIX}{rv}.{uuid.uuid4().hex}")
            with open(tmp, "wb") as f:
                f.write(encode_frame(payload))
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, self._snap_path(rv))
            _fsync_dir(self.dir)
            # roll the segment only after the snapshot is durable
            self._fh.close()
            self._fh = open(self._seg_path(rv), "ab")
            _fsync_dir(self.dir)
            self.base_rv = rv
            self._since_snapshot = 0
            self._gc_locked()
        METRICS.counter("wal_snapshots_total").inc()

    def _gc_locked(self) -> None:
        """Delete snapshot/segment pairs older than the newest
        ``keep_snapshots`` *complete* snapshots. The newest complete
        snapshot is never a deletion candidate — without it the log
        cannot bound replay."""
        complete = [rv for rv in self._snapshot_rvs()
                    if self._read_snapshot(rv) is not None]
        keep = set(complete[-self.keep_snapshots:])
        keep.add(self.base_rv)  # the active segment's base stays
        floor = min(keep)
        for rv in complete:
            if rv in keep:
                continue
            for path in (self._snap_path(rv), self._seg_path(rv)):
                try:
                    os.remove(path)
                except OSError:
                    pass
        # stray segments below the retention floor with no snapshot pair
        # (e.g. wal_0.log from before the first snapshot)
        for name in os.listdir(self.dir):
            if not (name.startswith(_SEG_PREFIX) and name.endswith(".log")):
                continue
            try:
                rv = int(name[len(_SEG_PREFIX):-len(".log")])
            except ValueError:
                continue
            if rv < floor and rv not in keep:
                try:
                    os.remove(os.path.join(self.dir, name))
                except OSError:
                    pass

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


class DurableBackend:
    """WAL-backed storage backend: fsync-before-RV-exposure, snapshot
    compaction, and restart recovery of state + the monotonic RV counter.

    Wraps an ``inner`` backend (``DictBackend`` unless given) for the
    in-memory representation; this class owns the RV counter and the
    watch-resume journal so durability semantics never depend on which
    inner backend is active.
    """

    journal_capable = True

    def __init__(
        self,
        directory: str,
        inner=None,
        snapshot_every: int = SNAPSHOT_EVERY_DEFAULT,
        journal_cap: int = JOURNAL_CAP_DEFAULT,
        keep_snapshots: int = KEEP_SNAPSHOTS,
    ) -> None:
        from ..runtime.metrics import METRICS  # lazy: mirrors store.py

        self._inner = inner if inner is not None else DictBackend()
        self._wal = WriteAheadLog(
            directory, snapshot_every=snapshot_every, keep_snapshots=keep_snapshots
        )
        self._lock = threading.Lock()
        self._journal: deque = deque()
        self._journal_cap = max(1, int(journal_cap))
        # --- recover: snapshot state, then replay the segment tail ---
        rv = self._wal.base_rv
        self._journal_floor = rv  # resume covers everything after the base
        if self._wal.state is not None:
            for bucket, ns, name, obj in self._wal.state.get("objects", []):
                self._inner.put(bucket, ns, name, obj, 0, "ADDED")
        replayed = 0
        for rec in self._wal.tail:
            rec_rv = int(rec["rv"])
            if rec["op"] == "DELETED":
                self._inner.delete(rec["bucket"], rec["ns"], rec["name"],
                                   rec["obj"], rec_rv)
            else:
                self._inner.put(rec["bucket"], rec["ns"], rec["name"],
                                rec["obj"], rec_rv, rec["op"])
            self._journal.append(JournalRecord(
                rec_rv, rec["op"], rec["bucket"], rec["ns"], rec["name"],
                rec["obj"]))
            rv = max(rv, rec_rv)
            replayed += 1
        self._rv = rv
        self._wal.drop_recovery_state()
        if replayed:
            METRICS.counter("wal_replayed_records_total").inc(replayed)

    # -- rv counter -----------------------------------------------------------

    def next_rv(self) -> int:
        with self._lock:
            self._rv += 1
            return self._rv

    def current_rv(self) -> int:
        with self._lock:
            return self._rv

    # -- mutations: WAL first, then the inner backend -------------------------

    def _record(self, rv: int, op: str, bucket: str, ns: str, name: str,
                obj: Dict[str, Any]) -> None:
        # fsync happens inside append(): the record is durable before the
        # inner backend (and so any watcher or reader) can observe the RV
        self._wal.append(
            {"rv": rv, "op": op, "bucket": bucket, "ns": ns, "name": name, "obj": obj}
        )
        with self._lock:
            self._rv = max(self._rv, rv)
            self._journal.append(
                JournalRecord(rv, op, bucket, ns, name, apimeta.deepcopy(obj)))
            while len(self._journal) > self._journal_cap:
                self._journal_floor = self._journal.popleft().rv

    def put(self, bucket: str, ns: str, name: str, obj: Dict[str, Any],
            rv: int, op: str) -> None:
        self._record(rv, op, bucket, ns, name, obj)
        self._inner.put(bucket, ns, name, obj, rv, op)
        self._maybe_snapshot()

    def delete(self, bucket: str, ns: str, name: str,
               final_obj: Dict[str, Any], rv: int) -> None:
        self._record(rv, "DELETED", bucket, ns, name, final_obj)
        self._inner.delete(bucket, ns, name, final_obj, rv)
        self._maybe_snapshot()

    def _maybe_snapshot(self) -> None:
        if not self._wal.should_snapshot():
            return
        self.snapshot()

    def snapshot(self) -> None:
        """Fold current state into a snapshot and truncate the tail."""
        objects = [(bucket, apimeta.namespace_of(obj), apimeta.name_of(obj), obj)
                   for bucket, obj in self._inner.list_all()]
        self._wal.snapshot(self.current_rv(), objects)

    # -- reads delegate to the inner backend ----------------------------------

    def contains(self, bucket: str, ns: str, name: str) -> bool:
        return self._inner.contains(bucket, ns, name)

    def get(self, bucket: str, ns: str, name: str) -> Optional[Dict[str, Any]]:
        return self._inner.get(bucket, ns, name)

    def list(
        self, bucket: str, ns: Optional[str] = None,
        selector: Optional[Dict[str, str]] = None,
    ) -> List[Dict[str, Any]]:
        return self._inner.list(bucket, ns, selector)

    def list_all(self) -> List[Tuple[str, Dict[str, Any]]]:
        return self._inner.list_all()

    def count(self, bucket: str) -> int:
        return self._inner.count(bucket)

    # -- watch resume ---------------------------------------------------------

    def journal_since(
        self, since_rv: int, max_records: int = 0, bucket: Optional[str] = None
    ) -> List[JournalRecord]:
        with self._lock:
            if since_rv < self._journal_floor:
                raise JournalExpired(
                    f"journal window expired before rv {since_rv} "
                    f"(floor: {self._journal_floor})")
            out = []
            for rec in self._journal:
                if rec.rv <= since_rv:
                    continue
                if bucket is not None and rec.bucket != bucket:
                    continue
                out.append(JournalRecord(
                    rec.rv, rec.type, rec.bucket, rec.namespace, rec.name,
                    apimeta.deepcopy(rec.object)))
                if max_records and len(out) >= max_records:
                    break
            return out

    def close(self) -> None:
        self._wal.close()
