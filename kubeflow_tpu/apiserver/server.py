"""REST API server: the Kubernetes-wire HTTP surface over the Store.

Exposes every registered resource at the standard paths —
``/api/v1/...`` for core, ``/apis/<group>/<version>/...`` for groups,
with ``namespaces/<ns>`` scoping, ``/status`` subresources, label
selectors, JSON merge-patch, and ``?watch=true`` streaming (NDJSON watch
events with resourceVersion resume via the native journal). This is what
makes the per-role service entrypoints real: controllers, webapps, and the
webhook connect to this server from separate processes exactly as the
reference's Go binaries connect to the Kubernetes API server.

Auth model (VERDICT r3 #3): pass an :class:`~.auth.ApiAuth` to gate every
verb — bearer-token identity + RBAC over the store's Role/Binding objects,
deny-by-default, the K8s-API-server half of the reference's two-gate model
(user-facing SAR stays in the web apps, crud_backend model, SURVEY §2.7).
``auth=None`` keeps the open in-process/all-in-one behavior. Admission is
driven by stored MutatingWebhookConfiguration objects (admission.py —
rules, namespaceSelector, failurePolicy); ``webhook_url`` is legacy sugar
that seeds one such object for pod CREATEs.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from ..api import meta as apimeta
from ..api.conversion import convert, convert_fragment, hub_resource
from ..api.meta import REGISTRY, Resource
from ..runtime.metrics import METRICS
from ..runtime.tracing import TRACEPARENT_ANNOTATION, TRACER, format_traceparent
from ..web.http import App, HttpError, JsonResponse, Request, StreamingResponse
from .auth import ApiAuth, Identity, Unauthenticated
from .fairness import FlowController, FlowRejected
from .store import ApiError, Store


def _selector_of(req: Request) -> Optional[Dict[str, str]]:
    raw = req.query1("labelSelector")
    if not raw:
        return None
    return apimeta.parse_selector_string(raw)


def apply_json_patch(obj: Dict[str, Any], ops: List[Dict[str, Any]]) -> Dict[str, Any]:
    """RFC 6902 subset: add/replace/remove with object/array paths."""
    out = apimeta.deepcopy(obj)
    for op in ops:
        path = [p.replace("~1", "/").replace("~0", "~") for p in op["path"].split("/")[1:]]
        parent: Any = out
        for seg in path[:-1]:
            parent = parent[int(seg)] if isinstance(parent, list) else parent.setdefault(seg, {})
        leaf = path[-1]
        kind = op["op"]
        if isinstance(parent, list):
            idx = len(parent) if leaf == "-" else int(leaf)
            if kind == "add":
                parent.insert(idx, op["value"])
            elif kind == "replace":
                parent[idx] = op["value"]
            elif kind == "remove":
                del parent[idx]
            else:
                raise ValueError(f"unsupported patch op {kind!r}")
        else:
            if kind in ("add", "replace"):
                parent[leaf] = op["value"]
            elif kind == "remove":
                parent.pop(leaf, None)
            else:
                raise ValueError(f"unsupported patch op {kind!r}")
    return out


_MWC_RES = REGISTRY.for_plural("admissionregistration.k8s.io/v1",
                               "mutatingwebhookconfigurations")


def seed_webhook_config(store: Store, url: str, failure_policy: str = "Ignore",
                        name: str = "env-registered-webhook") -> None:
    """Materialize the legacy ``WEBHOOK_URL`` env wiring as a stored
    MutatingWebhookConfiguration, so there is exactly one admission-
    registration mechanism — the object (apiserver/admission.py). Ignore
    policy preserves the env path's historical fail-open behavior; native
    registrations should write their own object with Fail.

    Upsert: the env always reflects the CURRENT url — re-wiring an
    all-in-one with a new WEBHOOK_URL must not leave a stale endpoint."""
    from .admission import webhook_configuration
    from .store import Conflict

    desired = webhook_configuration(name, url, failure_policy)
    try:
        store.create(desired)
    except Conflict:
        existing = store.get(_MWC_RES, name)
        if existing.get("webhooks") != desired["webhooks"]:
            existing["webhooks"] = desired["webhooks"]
            store.update(existing)


def make_apiserver_app(
    store: Store,
    webhook_url: Optional[str] = None,
    auth: Optional[ApiAuth] = None,
    fairness: Optional[FlowController] = None,
) -> App:
    """``fairness`` gates every resource verb through API priority-and-
    fairness (apiserver/fairness.py): requests are classified into a flow
    (``X-Flow-Client`` header, else authenticated identity), queued behind
    per-priority-level concurrency shares, and shed with 429 + Retry-After
    on overflow. ``None`` (default) keeps the open admit-everything
    behavior — in-process test stores don't need flow control."""
    from .admission import dynamic_admission_hook

    app = App("apiserver")
    # once per store: building two apps over one store (tests, all-in-one)
    # must not double-invoke every matching webhook
    if not getattr(store, "_dynamic_admission_registered", False):
        store.register_admission(dynamic_admission_hook(store))
        store._dynamic_admission_registered = True
    if webhook_url:
        seed_webhook_config(store, webhook_url)

    if auth is not None:
        @app.middleware
        def authenticate(req: Request) -> Optional[JsonResponse]:
            if req.path == "/healthz":  # kubelet probes stay anonymous
                return None
            header = req.header("authorization")
            bearer = header[7:] if header.lower().startswith("bearer ") else None
            try:
                req.context["identity"] = auth.authenticate(bearer)
            except Unauthenticated as e:
                if auth.anonymous_read and req.method == "GET":
                    req.context["identity"] = Identity(
                        "system:anonymous", ("system:unauthenticated",))
                    return None
                return JsonResponse(
                    {"kind": "Status", "status": "Failure", "code": 401,
                     "reason": "Unauthorized", "message": str(e)},
                    status=401, headers={"WWW-Authenticate": "Bearer"},
                )
            return None

    def authorize(req: Request, verb: str, res: Resource) -> None:
        """RBAC gate per verb (no-op when the server runs open)."""
        if auth is None:
            return
        ident = req.context["identity"]
        ns = req.params.get("ns")
        if not auth.ensure(ident, verb, res.group, res.plural, ns):
            raise HttpError(
                403,
                f"user {ident.user!r} cannot {verb} {res.plural}.{res.group or 'core'}"
                + (f" in namespace {ns!r}" if ns else " at cluster scope"),
            )

    def res_of(req: Request) -> Resource:
        """Resource addressed by the URL. May be a SPOKE version — handlers
        store/watch via ``hub_resource(res)`` and convert responses back to
        the requested version (hub-and-spoke, conversion.py)."""
        group = req.params.get("group", "")
        version = req.params["version"]
        api_version = f"{group}/{version}" if group else version
        try:
            return REGISTRY.for_plural(api_version, req.params["plural"])
        except KeyError as e:
            raise HttpError(404, str(e)) from None

    def outbound(obj: Dict[str, Any], res: Resource) -> Dict[str, Any]:
        return convert(obj, res.group, res.kind, res.version)

    def inbound(obj: Dict[str, Any], res: Resource) -> Dict[str, Any]:
        # The body must name the version the endpoint serves — blind
        # restamping would accept bogus versions and skip the registered
        # (endpoint-version → hub) field mappers.
        body_version = obj.get("apiVersion", "")
        if body_version != res.api_version:
            raise HttpError(
                400,
                f"body apiVersion {body_version!r} does not match endpoint {res.api_version!r}",
            )
        return convert(obj, res.group, res.kind, hub_resource(res).version)

    def error(e: ApiError) -> JsonResponse:
        return JsonResponse(e.to_status(), status=e.code)

    # -- handlers (shared by core + group paths) -----------------------------
    def list_or_watch(req: Request):
        res = res_of(req)
        ns = req.params.get("ns")
        selector = _selector_of(req)
        if req.query1("watch") in ("true", "1"):
            authorize(req, "watch", res)
            return _watch_stream(store, res, ns, selector, req)
        authorize(req, "list", res)
        limit_param = req.query1("limit")
        cont = req.query1("continue") or None
        try:
            if limit_param or cont:
                try:
                    limit = int(limit_param) if limit_param else None
                except ValueError:
                    raise HttpError(400, f"invalid limit {limit_param!r}") from None
                items, rv, next_token = store.list_page(
                    hub_resource(res), namespace=ns, label_selector=selector,
                    limit=limit, continue_token=cont)
            else:
                items, rv = store.list_with_rv(
                    hub_resource(res), namespace=ns, label_selector=selector)
                next_token = None
        except ApiError as e:
            return error(e)
        # RV captured atomically with the snapshot (store.list_with_rv /
        # the page's pinned snapshot) so list+watch-from-RV never misses
        # interleaved writes — and every page of one list reports the SAME RV.
        metadata: Dict[str, Any] = {"resourceVersion": str(rv)}
        if next_token:
            metadata["continue"] = next_token
        return {
            "apiVersion": res.api_version,
            "kind": res.list_kind or f"{res.kind}List",
            "metadata": metadata,
            "items": [outbound(o, res) for o in items],
        }

    def create(req: Request):
        res = res_of(req)
        authorize(req, "create", res)
        obj = req.json or {}
        obj.setdefault("apiVersion", res.api_version)
        obj.setdefault("kind", res.kind)
        if req.params.get("ns"):
            obj.setdefault("metadata", {}).setdefault("namespace", req.params["ns"])
        # Stamp the creating request's trace context on the object: the hop
        # from a client's POST to the watch-driven reconcile it causes has
        # no header to carry, so the object itself carries it (a client's
        # own traceparent survives verbatim via the dispatch span).
        cur = TRACER.current_span()
        if cur is not None:
            md = obj.setdefault("metadata", {})
            ann = dict(md.get("annotations") or {})
            ann.setdefault(TRACEPARENT_ANNOTATION, format_traceparent(cur))
            md["annotations"] = ann
        try:
            return JsonResponse(outbound(store.create(inbound(obj, res)), res), status=201)
        except ApiError as e:
            return error(e)

    def get_item(req: Request):
        res = res_of(req)
        authorize(req, "get", res)
        try:
            return outbound(store.get(hub_resource(res), req.params["name"], req.params.get("ns")), res)
        except ApiError as e:
            return error(e)

    def _check_body_matches_path(req: Request, obj: Dict[str, Any]) -> None:
        """The body must name the object the URL addresses — a mismatched
        client write must 400, not silently update a different object."""
        md = obj.get("metadata") or {}
        if md.get("name") != req.params["name"]:
            raise HttpError(400, f"body names {md.get('name')!r}, path names {req.params['name']!r}")
        path_ns = req.params.get("ns")
        if path_ns is not None and md.get("namespace") not in (None, path_ns):
            raise HttpError(
                400, f"body namespace {md.get('namespace')!r} != path namespace {path_ns!r}"
            )

    def put_item(req: Request):
        res = res_of(req)
        authorize(req, "update", res)
        obj = req.json or {}
        _check_body_matches_path(req, obj)
        try:
            return outbound(store.update(inbound(obj, res)), res)
        except ApiError as e:
            return error(e)

    def put_status(req: Request):
        res = res_of(req)
        authorize(req, "update", res)
        obj = req.json or {}
        _check_body_matches_path(req, obj)
        try:
            return outbound(store.update_status(inbound(obj, res)), res)
        except ApiError as e:
            return error(e)

    def patch_item(req: Request):
        res = res_of(req)
        authorize(req, "patch", res)
        patch = dict(req.json or {})
        # apiVersion/kind are endpoint-determined; merging a spoke version
        # into the stored hub object would corrupt its storage key.
        patch.pop("apiVersion", None)
        patch.pop("kind", None)
        # spoke→hub field mappers apply to the fragment before the merge
        patch = convert_fragment(
            patch, res.group, res.kind, res.version, hub_resource(res).version
        )
        try:
            return outbound(
                store.patch(hub_resource(res), req.params["name"], patch, req.params.get("ns")),
                res,
            )
        except ApiError as e:
            return error(e)

    def delete_item(req: Request):
        res = res_of(req)
        authorize(req, "delete", res)
        try:
            return outbound(store.delete(hub_resource(res), req.params["name"], req.params.get("ns")), res)
        except ApiError as e:
            return error(e)

    def flow_reject(e: FlowRejected) -> JsonResponse:
        retry_after = max(1, int(round(e.retry_after_s)))
        return JsonResponse(
            {"apiVersion": "v1", "kind": "Status", "status": "Failure",
             "code": 429, "reason": "TooManyRequests", "message": str(e)},
            status=429, headers={"Retry-After": str(retry_after)},
        )

    def instrumented(verb: str, handler):
        """kube-apiserver's request SLI surface: one histogram + in-flight
        gauge per (verb, resource), plus a child span under the dispatch
        span (which already continues any inbound ``traceparent``, so a
        controller's write shows up inside its reconcile trace).

        When fairness is configured, the flow-control gate sits here —
        around every resource verb, before any store work. The seat is held
        for the handler dispatch only: a watch's streaming phase runs
        seatless (served from the watch cache, it no longer amplifies store
        load), matching APF's treatment of long-running requests."""

        def wrapped(req: Request):
            v = verb
            if v == "list" and req.query1("watch") in ("true", "1"):
                v = "watch"
            resource = req.params.get("plural", "")
            ticket = None
            if fairness is not None:
                ident = req.context.get("identity")
                try:
                    ticket = fairness.admit(
                        req.header("x-flow-client") or None,
                        getattr(ident, "user", None))
                except FlowRejected as e:
                    return flow_reject(e)
            gauge = METRICS.gauge("apiserver_inflight_requests", verb=v)
            gauge.inc()
            start = time.monotonic()
            dec_on_exit = True
            try:
                with TRACER.span(f"apiserver.{v}", resource=resource, verb=v):
                    resp = handler(req)
                if v == "watch" and isinstance(resp, StreamingResponse):
                    # a watch is long-running: it stays in-flight until the
                    # stream closes, and its "duration" is the stream
                    # lifetime — ~0s dispatch samples would pollute the
                    # latency ladder, so the histogram skips watches
                    dec_on_exit = False
                    prev_close = resp.on_close

                    def close() -> None:
                        gauge.dec()
                        if prev_close is not None:
                            prev_close()

                    resp.on_close = close
                return resp
            finally:
                if ticket is not None:
                    fairness.release(ticket)
                if dec_on_exit:
                    gauge.dec()
                if v != "watch":
                    METRICS.histogram(
                        "apiserver_request_seconds", verb=v, resource=resource
                    ).observe(time.monotonic() - start)

        wrapped.__name__ = getattr(handler, "__name__", verb)
        return wrapped

    # -- route table ---------------------------------------------------------
    # /api/v1/... (core) and /apis/<group>/<version>/... share handlers; the
    # core prefix hard-pins version into the pattern params via defaults.
    prefixes = [
        "/api/<version>",
        "/apis/<group>/<version>",
    ]
    for prefix in prefixes:
        for scope in (f"{prefix}/namespaces/<ns>", prefix):
            app.route(f"{scope}/<plural>", methods=("GET",))(instrumented("list", list_or_watch))
            app.route(f"{scope}/<plural>", methods=("POST",))(instrumented("create", create))
            app.route(f"{scope}/<plural>/<name>", methods=("GET",))(instrumented("get", get_item))
            app.route(f"{scope}/<plural>/<name>", methods=("PUT",))(instrumented("update", put_item))
            app.route(f"{scope}/<plural>/<name>/status", methods=("PUT",))(
                instrumented("update_status", put_status))
            app.route(f"{scope}/<plural>/<name>", methods=("PATCH",))(instrumented("patch", patch_item))
            app.route(f"{scope}/<plural>/<name>", methods=("DELETE",))(instrumented("delete", delete_item))

    @app.route("/healthz")
    def healthz(req: Request):
        return {"status": "ok", "resourceVersion": str(store.backend.current_rv())}

    if fairness is not None:
        @app.route("/debug/fairness")
        def debug_fairness(req: Request):
            return fairness.snapshot()

    @app.route("/apis")
    def discovery(req: Request):
        groups: Dict[str, List[str]] = {}
        for res in REGISTRY.all():
            groups.setdefault(res.group or "core", []).append(f"{res.plural}.{res.version}")
        return {"groups": {g: sorted(v) for g, v in groups.items()}}

    # /metrics + /debug/* on the API port (kube-apiserver serves /metrics
    # and /debug/pprof the same way); the auth middleware above still gates
    # them when the server runs authenticated
    from ..runtime.obs import mount_observability

    mount_observability(app)

    return app


def _watch_stream(
    store: Store, res: Resource, ns: Optional[str], selector: Optional[Dict[str, str]], req: Request
):
    since_rv: Optional[int] = None
    rv_param = req.query1("resourceVersion")
    if rv_param:
        try:
            since_rv = int(rv_param)
        except ValueError:
            raise HttpError(400, f"invalid resourceVersion {rv_param!r}") from None
    send_initial = req.query1("sendInitial") in ("true", "1")
    sync_marker = req.query1("syncMarker") in ("true", "1")
    try:
        watcher = store.watch(
            hub_resource(res),
            namespace=ns,
            label_selector=selector,
            send_initial=send_initial,
            since_rv=since_rv,
            sync_marker=sync_marker,
        )
    except ApiError as e:
        return JsonResponse(e.to_status(), status=e.code)

    def chunks() -> Iterator[bytes]:
        # Heartbeat BOOKMARKs on idle streams: a broken socket is only
        # detected on write, so without periodic writes a watcher whose
        # client vanished (controller rollout) would leak its handler
        # thread + Store registration forever on a quiet resource.
        import queue as _queue

        # per-event fanout counter: N watchers on a busy resource multiply
        # every write by N here — the storm SLI the scale harness reads
        sent = METRICS.counter("apiserver_watch_events_sent_total", resource=res.plural)
        while True:
            try:
                # next_event, never .queue: preloaded initial-list/RV-replay
                # events must reach remote clients too (round-2 regression).
                item = watcher.next_event(timeout=15.0)
            except _queue.Empty:
                yield json.dumps({"type": "BOOKMARK", "object": {}}).encode() + b"\n"
                continue
            if item is None:
                return
            if item.type == "SYNC":  # protocol marker, not an object — no conversion
                yield json.dumps({"type": "SYNC", "object": item.object}).encode() + b"\n"
                continue
            obj = convert(item.object, res.group, res.kind, res.version)
            sent.inc()
            yield json.dumps({"type": item.type, "object": obj}).encode() + b"\n"

    return StreamingResponse(
        chunks(),
        headers={"Content-Type": "application/json; stream=watch"},
        on_close=watcher.close,
    )


def run_gc_loop(store: Store, interval: float = 0.1) -> threading.Thread:
    """The kube-controller-manager GC role, hosted by the apiserver process
    (remote controllers must not each run their own sweep)."""

    def loop() -> None:
        while True:
            time.sleep(interval)
            try:
                store.collect_garbage()
            except Exception:
                pass

    t = threading.Thread(target=loop, name="apiserver-gc", daemon=True)
    t.start()
    return t
