"""Storage backends for the Store: native C++ core with a Python fallback.

The reference's control plane is compiled (five Go binaries — SURVEY.md
§2.9). Here the storage hot path — MVCC buckets, revision counter,
label-filtered listing, and the watch journal — lives in a C++ shared
library (kubeflow_tpu/native/store_core.cc) bound via ctypes; object
*semantics* (admission, finalizers, status merge, GC) stay in the Python
Store on top of either backend.

The native backend adds a capability the dict backend lacks: a bounded
write journal, so watches can resume from a resourceVersion (etcd watch
windows). Selection: KUBEFLOW_TPU_NATIVE=1 forces native (raises if the
toolchain is missing), =0 forces Python, unset tries native and falls back.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..api import meta as apimeta

_REC = "\x1e"
_UNIT = "\x1f"

#: journal op codes (shared with store_core.cc)
OPS = ("ADDED", "MODIFIED", "DELETED")
_OP_CODE = {name: i for i, name in enumerate(OPS)}


@dataclass
class JournalRecord:
    rv: int
    type: str  # ADDED | MODIFIED | DELETED
    bucket: str
    namespace: str
    name: str
    object: Dict[str, Any]


class JournalExpired(Exception):
    """since_rv fell out of the journal window — relist, like etcd 410 Gone."""


class DictBackend:
    """Pure-Python storage: plain dicts, no journal (the pre-native shape)."""

    journal_capable = False

    def __init__(self) -> None:
        self._rv = 0
        self._data: Dict[str, Dict[Tuple[str, str], Dict[str, Any]]] = {}

    def next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def current_rv(self) -> int:
        return self._rv

    def contains(self, bucket: str, ns: str, name: str) -> bool:
        return (ns, name) in self._data.get(bucket, {})

    def get(self, bucket: str, ns: str, name: str) -> Optional[Dict[str, Any]]:
        obj = self._data.get(bucket, {}).get((ns, name))
        return apimeta.deepcopy(obj) if obj is not None else None

    def put(self, bucket: str, ns: str, name: str, obj: Dict[str, Any], rv: int, op: str) -> None:
        # JSON round-trip instead of deepcopy: enforces the same wire-shape
        # contract as the native backend (tuples→lists, non-serializable
        # values rejected), so object semantics can never depend on which
        # backend is active — a real apiserver likewise serializes to etcd.
        self._data.setdefault(bucket, {})[(ns, name)] = json.loads(
            json.dumps(obj, separators=(",", ":"))
        )

    def delete(self, bucket: str, ns: str, name: str, final_obj: Dict[str, Any], rv: int) -> None:
        self._data.get(bucket, {}).pop((ns, name), None)

    def list(
        self, bucket: str, ns: Optional[str] = None, selector: Optional[Dict[str, str]] = None
    ) -> List[Dict[str, Any]]:
        out = []
        for (obj_ns, _), obj in self._data.get(bucket, {}).items():
            if ns is not None and obj_ns != ns:
                continue
            if selector:
                labels = apimeta.labels_of(obj)
                if any(labels.get(k) != v for k, v in selector.items()):
                    continue
            out.append(apimeta.deepcopy(obj))
        return out

    def list_all(self) -> List[Tuple[str, Dict[str, Any]]]:
        out = []
        for bucket, entries in self._data.items():
            for obj in entries.values():
                out.append((bucket, apimeta.deepcopy(obj)))
        return out

    def count(self, bucket: str) -> int:
        return len(self._data.get(bucket, {}))

    def journal_since(
        self, since_rv: int, max_records: int = 0, bucket: Optional[str] = None
    ) -> List[JournalRecord]:
        raise NotImplementedError("DictBackend keeps no journal")


# --- native backend ----------------------------------------------------------

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libstorecore.so")
_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


class NativeUnavailable(Exception):
    """The native core cannot be built/loaded here (toolchain missing)."""


def _build_native() -> str:
    """make the shared library if absent (idempotent, serialized)."""
    with _build_lock:
        src = os.path.join(_NATIVE_DIR, "store_core.cc")
        have_so = os.path.exists(_SO_PATH)
        if not os.path.exists(src):
            # Artifact-based install: source stripped, prebuilt .so shipped.
            if have_so:
                return _SO_PATH
            raise NativeUnavailable(f"neither {_SO_PATH} nor its source exists")
        if have_so and os.path.getmtime(_SO_PATH) >= os.path.getmtime(src):
            return _SO_PATH
        try:
            # Bounded: a wedged compiler (NFS stall, OOM-thrashing cc1plus)
            # would otherwise park every thread needing the native backend
            # behind _build_lock forever.
            proc = subprocess.run(
                ["make", "-C", _NATIVE_DIR], capture_output=True, text=True,
                timeout=300,
            )
        except FileNotFoundError as e:  # no make on PATH
            raise NativeUnavailable(f"native build toolchain missing: {e}") from None
        except subprocess.TimeoutExpired as e:
            raise NativeUnavailable(f"native core build timed out: {e}") from None
        if proc.returncode != 0:
            raise NativeUnavailable(
                f"native core build failed:\n{proc.stdout}\n{proc.stderr}"
            )
        return _SO_PATH


def load_native_lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    path = _build_native()
    lib = ctypes.CDLL(path)
    lib.store_new.restype = ctypes.c_void_p
    lib.store_destroy.argtypes = [ctypes.c_void_p]
    lib.store_next_rv.argtypes = [ctypes.c_void_p]
    lib.store_next_rv.restype = ctypes.c_uint64
    lib.store_current_rv.argtypes = [ctypes.c_void_p]
    lib.store_current_rv.restype = ctypes.c_uint64
    lib.store_put.argtypes = [ctypes.c_void_p] + [ctypes.c_char_p] * 5 + [ctypes.c_uint64, ctypes.c_int]
    lib.store_put.restype = ctypes.c_int
    lib.store_get.argtypes = [ctypes.c_void_p] + [ctypes.c_char_p] * 3
    lib.store_get.restype = ctypes.c_void_p  # manual free
    lib.store_contains.argtypes = [ctypes.c_void_p] + [ctypes.c_char_p] * 3
    lib.store_contains.restype = ctypes.c_int
    lib.store_delete.argtypes = (
        [ctypes.c_void_p] + [ctypes.c_char_p] * 4 + [ctypes.c_uint64, ctypes.c_int]
    )
    lib.store_delete.restype = ctypes.c_int
    lib.store_list.argtypes = (
        [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p]
    )
    lib.store_list.restype = ctypes.c_void_p
    lib.store_list_all.argtypes = [ctypes.c_void_p]
    lib.store_list_all.restype = ctypes.c_void_p
    lib.store_journal_since.argtypes = [
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.c_int,
        ctypes.c_char_p,
    ]
    lib.store_journal_since.restype = ctypes.c_void_p
    lib.store_count.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.store_count.restype = ctypes.c_uint64
    lib.store_set_journal_cap.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.store_free_str.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def _enc(s: Optional[str]) -> bytes:
    return (s or "").encode()


class NativeBackend:
    """ctypes binding over the C++ store core."""

    journal_capable = True

    def __init__(self) -> None:
        self._lib = load_native_lib()
        self._h = self._lib.store_new()

    def __del__(self) -> None:
        h, self._h = getattr(self, "_h", None), None
        if h and getattr(self, "_lib", None) is not None:
            self._lib.store_destroy(h)

    # -- string marshalling --------------------------------------------------
    def _take_str(self, ptr: Optional[int]) -> Optional[str]:
        if not ptr:
            return None
        try:
            return ctypes.string_at(ptr).decode()
        finally:
            self._lib.store_free_str(ptr)

    @staticmethod
    def _check_key(*parts: str) -> None:
        """Bucket/namespace/name flow raw into the journal wire format —
        separator bytes in them would misalign journal records for every
        future watch resume, so reject at the write boundary (real
        Kubernetes DNS-1123 names can't contain them either)."""
        for p in parts:
            if _UNIT in p or _REC in p:
                raise ValueError(f"object key not representable on the native wire: {p!r}")

    @staticmethod
    def _pairs_flat(pairs: Dict[str, str]) -> str:
        """Flatten k=v pairs for the C boundary, rejecting anything that
        would corrupt the wire format (keys with '=', separator bytes) —
        real Kubernetes label syntax forbids all of these anyway; failing
        loudly beats two backends silently disagreeing on a match."""
        for k, v in pairs.items():
            if "=" in k or _UNIT in k or _REC in k or _UNIT in str(v) or _REC in str(v):
                raise ValueError(f"label not representable on the native wire: {k!r}={v!r}")
        return _UNIT.join(f"{k}={v}" for k, v in sorted(pairs.items()))

    @classmethod
    def _labels_flat(cls, obj: Dict[str, Any]) -> str:
        return cls._pairs_flat(apimeta.labels_of(obj))

    @classmethod
    def _selector_flat(cls, selector: Optional[Dict[str, str]]) -> str:
        return cls._pairs_flat(selector) if selector else ""

    # -- backend interface ---------------------------------------------------
    def next_rv(self) -> int:
        return int(self._lib.store_next_rv(self._h))

    def current_rv(self) -> int:
        return int(self._lib.store_current_rv(self._h))

    def contains(self, bucket: str, ns: str, name: str) -> bool:
        return bool(self._lib.store_contains(self._h, _enc(bucket), _enc(ns), _enc(name)))

    def get(self, bucket: str, ns: str, name: str) -> Optional[Dict[str, Any]]:
        blob = self._take_str(self._lib.store_get(self._h, _enc(bucket), _enc(ns), _enc(name)))
        return None if blob is None else json.loads(blob)

    def put(self, bucket: str, ns: str, name: str, obj: Dict[str, Any], rv: int, op: str) -> None:
        self._check_key(bucket, ns, name)
        self._lib.store_put(
            self._h,
            _enc(bucket),
            _enc(ns),
            _enc(name),
            json.dumps(obj, separators=(",", ":")).encode(),
            self._labels_flat(obj).encode(),
            rv,
            _OP_CODE[op],
        )

    def delete(self, bucket: str, ns: str, name: str, final_obj: Dict[str, Any], rv: int) -> None:
        self._check_key(bucket, ns, name)
        self._lib.store_delete(
            self._h,
            _enc(bucket),
            _enc(ns),
            _enc(name),
            json.dumps(final_obj, separators=(",", ":")).encode(),
            rv,
            _OP_CODE["DELETED"],
        )

    def list(
        self, bucket: str, ns: Optional[str] = None, selector: Optional[Dict[str, str]] = None
    ) -> List[Dict[str, Any]]:
        blob = self._take_str(
            self._lib.store_list(
                self._h,
                _enc(bucket),
                _enc(ns),
                0 if ns is None else 1,  # "" filters the empty namespace; None = all
                _enc(self._selector_flat(selector)),
            )
        )
        if not blob:
            return []
        return [json.loads(r) for r in blob.split(_REC)]

    def list_all(self) -> List[Tuple[str, Dict[str, Any]]]:
        blob = self._take_str(self._lib.store_list_all(self._h))
        if not blob:
            return []
        out = []
        for rec in blob.split(_REC):
            bucket, _, obj_json = rec.partition(_UNIT)
            out.append((bucket, json.loads(obj_json)))
        return out

    def count(self, bucket: str) -> int:
        return int(self._lib.store_count(self._h, _enc(bucket)))

    def set_journal_cap(self, cap: int) -> None:
        self._lib.store_set_journal_cap(self._h, cap)

    def journal_since(
        self, since_rv: int, max_records: int = 0, bucket: Optional[str] = None
    ) -> List[JournalRecord]:
        ptr = self._lib.store_journal_since(self._h, since_rv, max_records, _enc(bucket))
        blob = self._take_str(ptr)
        if blob is None:
            raise JournalExpired(f"journal window expired before rv {since_rv}")
        if not blob:
            return []
        out = []
        for rec in blob.split(_REC):
            rv_s, op_s, bucket, ns, name, obj_json = rec.split(_UNIT, 5)
            out.append(
                JournalRecord(int(rv_s), OPS[int(op_s)], bucket, ns, name, json.loads(obj_json))
            )
        return out


def default_backend():
    """Backend selection: KUBEFLOW_TPU_NATIVE=1 forces native, =0 forces
    Python, unset prefers native and falls back ONLY when the toolchain is
    genuinely unavailable — a broken native core (bad signature, crash in
    store_new) must surface, not silently downgrade to the journal-less
    fallback."""
    mode = os.environ.get("KUBEFLOW_TPU_NATIVE", "").strip()
    if mode == "0":
        return DictBackend()
    if mode == "1":
        return NativeBackend()
    try:
        return NativeBackend()
    except NativeUnavailable as e:
        import logging

        logging.getLogger("kubeflow_tpu.apiserver").warning(
            "native store core unavailable, using Python fallback: %s", e
        )
        return DictBackend()
