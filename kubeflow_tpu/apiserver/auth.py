"""Apiserver authn/authz: bearer-token identity + RBAC (VERDICT r3 #3).

In the reference every API call is gated twice: the Kubernetes API server
authenticates and runs RBAC on each request, and the web backends add a
per-user SubjectAccessReview on top (crud_backend/authz.py:25-43). Round 3
shipped only the SAR half — the substrate's own REST boundary
(apiserver/server.py) accepted unauthenticated writes from anything that
could reach the port. This module is the cluster-API half:

- :class:`TokenAuthenticator` — static bearer tokens → (user, groups), the
  analog of ``kube-apiserver --token-auth-file``. Role tokens are
  provisioned by the manifests (Secret ``kubeflow-tpu-tokens``) and read
  from ``APISERVER_TOKENS`` / ``APISERVER_TOKEN_FILE``.
- :class:`RBACAuthorizer` — Role/ClusterRole ``rules`` evaluation
  ((apiGroups, resources, verbs) with ``*`` wildcards) over the store's
  RBAC objects, bound through RoleBinding/ClusterRoleBinding subjects
  (User and Group). ``system:masters`` bypasses, K8s semantics. RoleBindings
  whose roleRef names one of the platform roles (kubeflow-admin/edit/view)
  fall back to the web/auth.py verb model when no ClusterRole object is
  stored — so KFAM-managed namespaces authorize identically at both gates.
- :func:`seed_rbac` — bootstrap ClusterRole + ClusterRoleBinding for the
  platform service group (``system:kubeflow-tpu``), the analog of the K8s
  bootstrap RBAC reconciler: controllers/webhook/webapps authenticate with
  role tokens whose group grants full resource access; webapps still gate
  per-user SAR before acting on a user's behalf (crud_backend model).

Deny-by-default: with auth enabled, a request with no/unknown token is 401
and an authenticated request with no matching rule is 403. ``/healthz``
stays anonymous (kubelet probes).
"""

from __future__ import annotations

import csv
import os
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional

from ..api.meta import REGISTRY
from ..web.auth import ROLE_VERBS

MASTERS_GROUP = "system:masters"
SERVICE_GROUP = "system:kubeflow-tpu"

_RBAC = "rbac.authorization.k8s.io/v1"


@dataclass(frozen=True)
class Identity:
    user: str
    groups: tuple = ()


class Unauthenticated(Exception):
    pass


def _parse_expiry(raw: str) -> Optional[float]:
    """``exp=<RFC3339|unix-seconds>`` column → unix timestamp (None = never)."""
    value = raw.split("=", 1)[1].strip() if "=" in raw else raw.strip()
    if not value:
        return None
    try:
        return float(value)
    except ValueError:
        pass
    import datetime

    dt = datetime.datetime.fromisoformat(value.replace("Z", "+00:00"))
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt.timestamp()


class TokenAuthenticator:
    """Token table with lifecycle: ``Authorization: Bearer <token>`` →
    Identity, per-token expiry, and hot-reload of the token file so
    rotation needs no apiserver restart (VERDICT r4 weak #6 / next #3).

    Rotation protocol: rewrite ``APISERVER_TOKEN_FILE`` (a Secret remount
    in a real deploy); within ``reload_interval`` seconds new requests
    authenticate against the new table — removed tokens 401, added tokens
    work. During a graceful rotation the file carries both old (with a
    near ``exp=``) and new tokens, so in-flight roles never see a gap.
    """

    def __init__(self, tokens: Optional[Dict[str, Identity]] = None,
                 reload_interval: float = 1.0):
        # Single-attribute state (tokens, expiry): a reload swaps both maps
        # in one assignment, so concurrent request threads always see a
        # consistent pair (ThreadingHTTPServer serves requests in parallel).
        self._state: tuple = (dict(tokens or {}), {})
        self._file: Optional[str] = None
        self._file_mtime: float = -1.0
        self._inline: str = ""
        self._reload_interval = reload_interval
        self._next_check = 0.0

    @property
    def _tokens(self) -> Dict[str, Identity]:
        return self._state[0]

    @property
    def _expiry(self) -> Dict[str, float]:
        return self._state[1]

    def add(self, token: str, user: str, groups: Iterable[str] = (),
            not_after: Optional[float] = None) -> None:
        if "CHANGEME" in token:
            # The manifest Secret template ships CHANGEME placeholders; an
            # unedited deploy must fail CLOSED, not accept a well-known
            # bearer token bound to the full-access service group.
            import logging

            logging.getLogger("kubeflow_tpu.apiserver").error(
                "refusing placeholder token for %r — replace every CHANGEME "
                "in the kubeflow-tpu-tokens Secret (see "
                "python -m kubeflow_tpu.apiserver.tokens)", user)
            return
        self._tokens[token] = Identity(user, tuple(groups))
        if not_after is not None:
            self._expiry[token] = not_after

    def authenticate_token(self, token: Optional[str]) -> Identity:
        self._maybe_reload()
        tokens, expiry = self._state  # one read: stable across a concurrent swap
        if not token or token not in tokens:
            raise Unauthenticated("invalid or missing bearer token")
        import time

        exp = expiry.get(token)
        if exp is not None and time.time() >= exp:
            raise Unauthenticated("token expired")
        return tokens[token]

    def __len__(self) -> int:
        return len(self._tokens)

    # -- lifecycle -----------------------------------------------------------
    def _maybe_reload(self) -> None:
        """Reload the token file when its mtime moved (stat throttled to
        once per ``reload_interval`` — cheap enough for the request path)."""
        if not self._file:
            return
        import time

        now = time.monotonic()
        if now < self._next_check:
            return
        self._next_check = now + self._reload_interval
        try:
            mtime = os.stat(self._file).st_mtime
        except OSError:
            return  # missing file: keep the last good table (Secret remount gap)
        if mtime == self._file_mtime:
            return
        # Rebuild into fresh dicts, then swap — concurrent request threads
        # must never observe a half-empty table mid-rotation. Only a
        # successful load advances the recorded mtime: a transiently
        # unreadable file (kubelet's atomic Secret symlink swap) keeps the
        # last good table and retries on the next poll instead of 500ing
        # the request and pinning the stale table forever.
        fresh = TokenAuthenticator()
        fresh._load_inline(self._inline)
        try:
            fresh._load_file(self._file)
        except Exception:
            # unreadable OR unparsable (bad UTF-8, csv.Error): keep serving
            # the last good table and retry next poll — a broken rotation
            # must not 500 the API or pin a stale mtime
            return
        self._file_mtime = mtime
        self._state = fresh._state

    def _load_inline(self, inline: str) -> None:
        for entry in filter(None, inline.split(";")):
            # maxsplit=2: group names themselves contain colons
            # (system:masters, system:kubeflow-tpu) — only | separates groups.
            parts = entry.split(":", 2)
            if len(parts) < 2:
                continue
            groups = [g for g in (parts[2].split("|") if len(parts) > 2 else []) if g]
            self.add(parts[0], parts[1], groups)

    def _load_file(self, path: str) -> None:
        """Kube static-token CSV: ``token,user,uid,"group1,group2"`` with an
        optional 5th column ``exp=<RFC3339|unix>`` for per-token expiry."""
        with open(path, newline="") as f:
            for row in csv.reader(f):
                if len(row) < 2 or row[0].lstrip().startswith("#"):
                    continue
                groups = [g.strip() for g in row[3].split(",")] if len(row) > 3 else []
                not_after = None
                if len(row) > 4 and row[4].strip():
                    try:
                        not_after = _parse_expiry(row[4])
                    except ValueError:
                        continue  # malformed expiry: reject the row, not the file
                self.add(row[0].strip(), row[1].strip(),
                         [g for g in groups if g], not_after=not_after)

    @classmethod
    def from_env(cls) -> "TokenAuthenticator":
        """``APISERVER_TOKENS`` inline (``tok:user:grp1|grp2;tok2:u2:``) and/or
        ``APISERVER_TOKEN_FILE`` (kube static-token CSV + optional ``exp=``
        column). The file is watched for rotation."""
        auth = cls()
        auth._inline = os.environ.get("APISERVER_TOKENS", "")
        auth._load_inline(auth._inline)
        path = os.environ.get("APISERVER_TOKEN_FILE", "")
        if path:
            # Track the path even if absent at boot (slow volume mount):
            # _maybe_reload picks the file up when it appears instead of
            # 401ing until a restart.
            auth._file = path
            if os.path.exists(path):
                auth._file_mtime = os.stat(path).st_mtime
                auth._load_file(path)
        return auth


def _rule_matches(rule: Dict[str, Any], group: str, resource: str, verb: str) -> bool:
    api_groups = rule.get("apiGroups", [])
    resources = rule.get("resources", [])
    verbs = rule.get("verbs", [])
    return (
        ("*" in api_groups or group in api_groups)
        and ("*" in resources or resource in resources)
        and ("*" in verbs or verb in verbs)
    )


def _subject_matches(subjects: Optional[List[Dict[str, Any]]], ident: Identity) -> bool:
    for sub in subjects or []:
        kind = sub.get("kind", "User")
        if kind == "User" and sub.get("name") == ident.user:
            return True
        if kind == "Group" and sub.get("name") in ident.groups:
            return True
    return False


class RBACAuthorizer:
    """RBAC over the store's Role/ClusterRole/Binding objects (in-process —
    the authorizer runs inside the apiserver, it does not call back out)."""

    def __init__(self, store):
        self.store = store
        self._res = {
            "Role": REGISTRY.for_plural(_RBAC, "roles"),
            "RoleBinding": REGISTRY.for_plural(_RBAC, "rolebindings"),
            "ClusterRole": REGISTRY.for_plural(_RBAC, "clusterroles"),
            "ClusterRoleBinding": REGISTRY.for_plural(_RBAC, "clusterrolebindings"),
        }

    def _cluster_role_rules(self, name: str) -> Optional[List[Dict[str, Any]]]:
        try:
            return self.store.get(self._res["ClusterRole"], name).get("rules", [])
        except Exception:
            return None

    def _role_rules(self, name: str, namespace: str) -> Optional[List[Dict[str, Any]]]:
        try:
            return self.store.get(self._res["Role"], name, namespace).get("rules", [])
        except Exception:
            return None

    def _ref_rules(
        self, role_ref: Dict[str, Any], namespace: Optional[str]
    ) -> Optional[List[Dict[str, Any]]]:
        name = role_ref.get("name", "")
        if role_ref.get("kind", "ClusterRole") == "Role":
            return self._role_rules(name, namespace) if namespace else None
        rules = self._cluster_role_rules(name)
        if rules is None and name in ROLE_VERBS:
            # KFAM-managed namespaces bind the named platform roles without
            # materializing ClusterRole objects (web/auth.py model): treat
            # them as "all resources, the role's verb set".
            return [{"apiGroups": ["*"], "resources": ["*"],
                     "verbs": sorted(ROLE_VERBS[role_ref["name"]])}]
        return rules

    def allowed(self, ident: Identity, verb: str, group: str, resource: str,
                namespace: Optional[str]) -> bool:
        if MASTERS_GROUP in ident.groups:
            return True
        for crb in self.store.list(self._res["ClusterRoleBinding"]):
            if not _subject_matches(crb.get("subjects"), ident):
                continue
            rules = self._ref_rules(crb.get("roleRef") or {}, None) or []
            if any(_rule_matches(r, group, resource, verb) for r in rules):
                return True
        if namespace:
            for rb in self.store.list(self._res["RoleBinding"], namespace=namespace):
                if not _subject_matches(rb.get("subjects"), ident):
                    continue
                rules = self._ref_rules(rb.get("roleRef") or {}, namespace) or []
                if any(_rule_matches(r, group, resource, verb) for r in rules):
                    return True
        return False


@dataclass
class ApiAuth:
    """The apiserver's authn+authz gate. ``None`` (the default wiring) keeps
    the open behavior for in-process/all-in-one runs; the per-role server
    enables it from env (deny-by-default toggle in manifests/params.env)."""

    authenticator: TokenAuthenticator
    authorizer: RBACAuthorizer
    anonymous_read: bool = False  # allow unauthenticated get/list/watch (debug)

    def authenticate(self, bearer: Optional[str]) -> Identity:
        return self.authenticator.authenticate_token(bearer)

    def ensure(self, ident: Identity, verb: str, group: str, resource: str,
               namespace: Optional[str]) -> bool:
        if (self.anonymous_read and verb in ("get", "list", "watch")
                and "system:unauthenticated" in ident.groups):
            return True
        return self.authorizer.allowed(ident, verb, group, resource, namespace)


def seed_rbac(store) -> None:
    """Create-if-absent the bootstrap RBAC for platform service identities
    (the K8s bootstrap-RBAC-reconciler analog, run by the apiserver role at
    startup). Controllers/webhook/webapps carry tokens in group
    ``system:kubeflow-tpu``; users get namespace RoleBindings via KFAM."""
    cr = {
        "apiVersion": _RBAC, "kind": "ClusterRole",
        "metadata": {"name": "kubeflow-tpu-service"},
        "rules": [{"apiGroups": ["*"], "resources": ["*"], "verbs": ["*"]}],
    }
    crb = {
        "apiVersion": _RBAC, "kind": "ClusterRoleBinding",
        "metadata": {"name": "kubeflow-tpu-service"},
        "roleRef": {"kind": "ClusterRole", "name": "kubeflow-tpu-service",
                    "apiGroup": "rbac.authorization.k8s.io"},
        "subjects": [{"kind": "Group", "name": SERVICE_GROUP}],
    }
    from .store import Conflict

    for obj in (cr, crb):
        try:
            store.create(obj)
        except Conflict:
            pass  # already seeded; any other failure must surface — a
            # silently missing binding would 403 every platform role


def auth_from_env(store) -> Optional[ApiAuth]:
    """``APISERVER_AUTH=token`` enables the gate; anything else (default)
    leaves the boundary open (all-in-one/dev parity with round 3)."""
    from ..utils import env_flag

    if os.environ.get("APISERVER_AUTH", "").lower() not in ("token", "rbac", "on", "true", "1"):
        return None
    authn = TokenAuthenticator.from_env()
    gate = ApiAuth(
        authenticator=authn,
        authorizer=RBACAuthorizer(store),
        anonymous_read=env_flag("APISERVER_ANONYMOUS_READ"),
    )
    seed_rbac(store)
    return gate
