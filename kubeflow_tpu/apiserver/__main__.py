"""python -m kubeflow_tpu.apiserver — the REST control-plane server.

Env: API_PORT (default 8001), WEBHOOK_URL (legacy sugar: seeds a
MutatingWebhookConfiguration object for the external PodDefault webhook —
admission is ALWAYS driven by those stored objects, apiserver/admission.py;
unset + no objects = in-process admission, the all-in-one default),
KUBEFLOW_TPU_NATIVE
(storage backend selection), APISERVER_WAL_DIR to run on the durable
WAL+snapshot backend (wal.py; APISERVER_WAL_SNAPSHOT_EVERY tunes
compaction) so state and the RV counter survive a restart,
APISERVER_AUTH=token (+ APISERVER_TOKENS /
APISERVER_TOKEN_FILE) for the deny-by-default bearer/RBAC gate (auth.py),
APISERVER_TLS_CERT_FILE + APISERVER_TLS_KEY_FILE to serve HTTPS (the
reference substrate is TLS-only; clients verify via APISERVER_CA_FILE —
web/tls.py). Bearer tokens over plaintext HTTP are only acceptable for
loopback dev runs. APISERVER_FAIRNESS=1 (the deployment default in
manifests) turns on the priority-and-fairness gate (fairness.py): requests
are classified into priority levels by flow identity and shed with 429 +
Retry-After when a level's queues overflow; =0/unset keeps the open
admit-everything dev behavior.
"""

from __future__ import annotations

import logging
import os

from ..apiserver.client import Client
from ..runtime.bootstrap import block_forever
from ..webhook.poddefault import admission_hook
from .auth import auth_from_env
from .server import make_apiserver_app, run_gc_loop
from .store import Store


def main() -> None:
    logging.basicConfig(level=os.environ.get("LOG_LEVEL", "INFO"))
    from ..runtime.tracing import TRACER

    TRACER.service = "apiserver"  # federated spans name their process
    backend = None
    wal_dir = os.environ.get("APISERVER_WAL_DIR", "")
    if wal_dir:
        from .wal import SNAPSHOT_EVERY_DEFAULT, DurableBackend

        backend = DurableBackend(
            wal_dir,
            snapshot_every=int(os.environ.get(
                "APISERVER_WAL_SNAPSHOT_EVERY", str(SNAPSHOT_EVERY_DEFAULT))),
        )
    store = Store(backend=backend)
    webhook_url = os.environ.get("WEBHOOK_URL", "")
    auth = auth_from_env(store)
    fairness = None
    if os.environ.get("APISERVER_FAIRNESS", "") not in ("", "0", "false"):
        from .fairness import FlowController

        fairness = FlowController()
    app = make_apiserver_app(store, webhook_url=webhook_url or None, auth=auth,
                             fairness=fairness)
    if not webhook_url:
        store.register_admission(
            admission_hook(Client(store), cluster_domain=os.environ.get("CLUSTER_DOMAIN", "cluster.local"))
        )
    run_gc_loop(store)
    port = int(os.environ.get("API_PORT", "8001"))
    ctx = None
    cert = os.environ.get("APISERVER_TLS_CERT_FILE", "")
    key = os.environ.get("APISERVER_TLS_KEY_FILE", "")
    if cert or key:
        # Half-configured TLS must fail CLOSED at startup, not silently
        # serve the bearer-token boundary over plaintext.
        if not (cert and key):
            raise SystemExit(
                "APISERVER_TLS_CERT_FILE and APISERVER_TLS_KEY_FILE must "
                "both be set (or both unset for loopback dev)")
        from ..web.tls import server_context

        ctx = server_context(cert, key)
    server = app.serve(port, host="0.0.0.0", ssl_context=ctx)
    logging.getLogger("kubeflow_tpu.apiserver").info(
        "apiserver on :%d (%s, backend=%s, admission=%s, auth=%s, fairness=%s)",
        server.port,
        "TLS" if ctx else "plain HTTP",
        type(store.backend).__name__,
        webhook_url or "in-process",
        "token+rbac" if auth else "open",
        "on" if fairness else "off",
    )
    try:
        block_forever()
    finally:
        server.close()


if __name__ == "__main__":
    main()
