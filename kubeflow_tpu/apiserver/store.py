"""In-process API storage: MVCC object store with watch streams.

This is the platform's etcd+apiserver analog. Every object lives under a
``group/version/plural`` bucket keyed by ``(namespace, name)``; a global
monotonically increasing resourceVersion stamps each write; watchers receive
ADDED/MODIFIED/DELETED events through bounded queues. Deletion honors
finalizers the way Kubernetes does (set ``deletionTimestamp``, wait for
finalizer removal) — the profile-controller's teardown path depends on this
(reference: profile-controller/controllers/profile_controller.go:277-312).

Admission hooks run on pod writes before persistence — the seam where the
PodDefault mutating webhook attaches (reference: admission-webhook/main.go:443).

Persistence is delegated to a storage backend (kubeflow_tpu/apiserver/
backend.py): the native C++ core (kubeflow_tpu/native/store_core.cc) by
default — the analog of the reference's compiled control-plane binaries —
with a pure-Python fallback. On the native backend, watches can resume from
a resourceVersion via the write journal (etcd watch-window semantics).
"""

from __future__ import annotations

import base64
import collections
import fnmatch
import json
import queue
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..api import conversion
from ..api import meta as apimeta
from ..api.meta import REGISTRY, Resource
from .backend import DictBackend, JournalExpired, NativeBackend, default_backend  # noqa: F401


def _to_hub(obj: Dict[str, Any]) -> Tuple[Resource, Dict[str, Any]]:
    """Resolve an object's Resource, routing spoke versions to the storage
    hub — a spoke-stamped object must never land in a spoke bucket where hub
    controllers and the REST surface would not see it (split-brain)."""
    res = REGISTRY.for_object(obj)
    hub = conversion.hub_resource(res)
    if hub is not res:
        obj = conversion.convert(obj, res.group, res.kind, hub.version)
        res = hub
    return res, obj


class ApiError(Exception):
    code = 500
    reason = "InternalError"

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message

    def to_status(self) -> Dict[str, Any]:
        return {
            "apiVersion": "v1",
            "kind": "Status",
            "status": "Failure",
            "code": self.code,
            "reason": self.reason,
            "message": self.message,
        }


class NotFound(ApiError):
    code = 404
    reason = "NotFound"


class Conflict(ApiError):
    code = 409
    reason = "Conflict"


class Invalid(ApiError):
    code = 422
    reason = "Invalid"


class Forbidden(ApiError):
    code = 403
    reason = "Forbidden"


class Expired(ApiError):
    code = 410
    reason = "Expired"


class TooManyRequests(ApiError):
    """429: the fairness layer shed this request. ``retry_after_s`` carries
    the server's Retry-After so clients can honor it instead of guessing."""

    code = 429
    reason = "TooManyRequests"

    def __init__(self, message: str, retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class ServiceUnavailable(ApiError):
    """503: transient server-side overload/outage — retryable, unlike the
    fatal 4xx family."""

    code = 503
    reason = "ServiceUnavailable"

    def __init__(self, message: str, retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    object: Dict[str, Any]


# Admission hook signature: (operation, resource, obj) -> mutated obj (or raise
# ApiError to reject). operation in {"CREATE", "UPDATE", "DELETE"}.
AdmissionHook = Callable[[str, Resource, Dict[str, Any]], Dict[str, Any]]


class _Watcher:
    def __init__(self, key: str, namespace: Optional[str], selector: Optional[Dict[str, str]]):
        self.key = key
        self.namespace = namespace
        self.selector = selector
        # LIVE events only. Sized for a 1k-notebook churn wave (~2k pods ×
        # several writes each): overflow closes the watcher and forces a
        # full relist, so drops must be rare, not routine.
        self.queue: "queue.Queue[Optional[WatchEvent]]" = queue.Queue(maxsize=16384)
        # Initial-list / journal-replay events: unbounded, drained before the
        # live queue. These MUST NOT count against the slow-watcher drop
        # policy — a collection larger than the queue bound would otherwise
        # close every watcher mid-relist and informers could never sync.
        # Contract: replay/initial delivery is COMPLETE (etcd streams the
        # whole watch window; a K8s initial list is never truncated); only
        # live events are subject to the bounded-queue drop-close policy.
        # Consumers must read through next_event()/iteration, never
        # self.queue directly, or preloaded events are silently skipped.
        self._preload: "collections.deque[WatchEvent]" = collections.deque()
        self.closed = False

    def preload(self, event: WatchEvent) -> None:
        self._preload.append(event)

    def next_event(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        """Next event: preloaded (initial list / journal replay) first, then
        live. Returns None at end-of-stream; raises queue.Empty on timeout.
        This is the ONLY correct read path — the REST streaming handler and
        __iter__ both go through it (round-2 regression: reading .queue
        directly skipped every preloaded event, so remote informers synced
        empty caches and RV-resume watches hung)."""
        if self._preload:
            return self._preload.popleft()
        return self.queue.get(timeout=timeout)

    def matches(self, res_key: str, obj: Dict[str, Any]) -> bool:
        if not fnmatch.fnmatch(res_key, self.key):
            return False
        if self.namespace is not None and apimeta.namespace_of(obj) != self.namespace:
            return False
        if self.selector:
            labels = apimeta.labels_of(obj)
            if any(labels.get(k) != v for k, v in self.selector.items()):
                return False
        return True

    def send(self, event: WatchEvent) -> None:
        if self.closed:
            return
        try:
            self.queue.put_nowait(event)
        except queue.Full:
            # Slow watcher: drop it rather than block the write path; informers
            # relist on close, same as an expired etcd watch window.
            self.close()

    def close(self) -> None:
        self.closed = True
        # The end-of-stream sentinel must ALWAYS arrive, or `for e in w`
        # blocks forever after draining — evict events until it fits (the
        # consumer is relisting anyway once it sees the stream closed).
        while True:
            try:
                self.queue.put_nowait(None)
                return
            except queue.Full:
                try:
                    self.queue.get_nowait()
                except queue.Empty:
                    pass

    def __iter__(self):
        while True:
            item = self.next_event()
            if item is None:
                return
            yield item


#: default watch-cache ring length — sized to ride out a 1k-notebook churn
#: wave between informer reconnects; override per Store for tests.
WATCH_CACHE_SIZE = 4096

#: LIST continue-token snapshots: how many concurrent paginated LISTs may be
#: in flight, and how long an abandoned one is kept before its token expires.
PAGE_SNAPSHOT_CAP = 64
PAGE_SNAPSHOT_TTL_S = 60.0


class Store:
    def __init__(self, backend=None, watch_cache_size: int = WATCH_CACHE_SIZE) -> None:
        self._lock = threading.RLock()
        self.backend = backend if backend is not None else default_backend()
        self._watchers: List[_Watcher] = []
        self._admission: List[AdmissionHook] = []
        # Watch cache (etcd watch-window analog, backend-independent): a
        # bounded ring of (rv, res_key, type, obj) fed by _notify. A watch
        # with since_rv replays from the ring when it still covers that RV;
        # compaction past it surfaces 410 Expired so the client relists.
        # Size 0 disables the ring (journal-only semantics, see watch()).
        self._wc_size = max(0, int(watch_cache_size))
        self._wc_events: "collections.deque[Tuple[int, str, str, Dict[str, Any]]]" = (
            collections.deque())
        # Highest RV compacted out of the ring. Seeded with the backend's
        # current RV: a pre-populated persistent backend has history this
        # ring never saw, so those RVs must fall through to the journal.
        self._wc_trimmed_rv = self.backend.current_rv()
        # Per-bucket object mirror serving send_initial watches without a
        # backend read per client (the watch-storm amplification fix).
        # Lazily built on first use, then maintained inline by _notify.
        self._wc_mirror: Dict[str, Dict[Tuple[Optional[str], str], Dict[str, Any]]] = {}
        # LIST pagination snapshots: token id -> (expires_mono, rv, items).
        self._page_snaps: "collections.OrderedDict[str, Tuple[float, int, List[Dict[str, Any]]]]" = (
            collections.OrderedDict())
        # GC ownership index, maintained at write time so a sweep never has
        # to decode the whole store (the old full-scan sweep at 20Hz was the
        # top cost in the 500-notebook loadtest profile):
        #   uid -> (res_key, name, namespace) for every live object,
        #   uid -> [owner uids] only for objects that HAVE ownerReferences.
        # _gc_dirty gates sweeps: set on any delete (may orphan children)
        # and on creates/updates that carry ownerReferences.
        self._gc_uids: Dict[str, Tuple[str, str, Optional[str]]] = {}
        self._gc_owners: Dict[str, List[str]] = {}
        self._gc_dirty = True
        self._gc_index_built = False

    # -- GC index maintenance (caller holds the lock) ------------------------
    def _gc_track(self, res: Resource, obj: Dict[str, Any]) -> None:
        md = obj.get("metadata", {})
        uid = md.get("uid")
        if not uid:
            return
        self._gc_uids[uid] = (res.key, md.get("name", ""), md.get("namespace"))
        refs = [r.get("uid") for r in (md.get("ownerReferences") or []) if r.get("uid")]
        if refs:
            self._gc_owners[uid] = refs
            self._gc_dirty = True
        else:
            self._gc_owners.pop(uid, None)

    def _gc_untrack(self, obj: Dict[str, Any]) -> None:
        uid = obj.get("metadata", {}).get("uid")
        if uid:
            self._gc_uids.pop(uid, None)
            self._gc_owners.pop(uid, None)
        self._gc_dirty = True

    def _gc_rebuild(self) -> None:
        """One full decode at startup for pre-populated (persistent) backends."""
        self._gc_uids.clear()
        self._gc_owners.clear()
        for res_key, obj in self.backend.list_all():
            res = next((r for r in REGISTRY.all() if r.key == res_key), None)
            if res is not None:
                self._gc_track(res, obj)
        self._gc_index_built = True
        self._gc_dirty = True

    # -- admission ----------------------------------------------------------
    def register_admission(self, hook: AdmissionHook) -> None:
        self._admission.append(hook)

    def _admit(self, op: str, res: Resource, obj: Dict[str, Any]) -> Dict[str, Any]:
        for hook in self._admission:
            obj = hook(op, res, obj)
        return obj

    # -- internals ----------------------------------------------------------
    @staticmethod
    def _obj_key(res: Resource, namespace: Optional[str], name: str) -> Tuple[str, str]:
        return (namespace or "") if res.namespaced else "", name

    def _notify(self, res: Resource, event: WatchEvent) -> None:
        obj = event.object
        self._wc_record(res, event.type, obj)
        for w in list(self._watchers):
            if w.closed:
                self._watchers.remove(w)
                continue
            if w.matches(res.key, obj):
                w.send(WatchEvent(event.type, apimeta.deepcopy(obj)))

    # -- watch cache (caller holds the lock) ---------------------------------
    def _wc_record(self, res: Resource, type_: str, obj: Dict[str, Any]) -> None:
        snap = apimeta.deepcopy(obj)
        mirror = self._wc_mirror.get(res.key)
        if mirror is not None:
            mkey = (apimeta.namespace_of(snap), apimeta.name_of(snap))
            if type_ == "DELETED":
                mirror.pop(mkey, None)
            else:
                mirror[mkey] = snap
        if self._wc_size <= 0:
            return
        try:
            rv = int(snap.get("metadata", {}).get("resourceVersion"))
        except (TypeError, ValueError):
            return  # un-versioned event: not replayable, skip the ring
        self._wc_events.append((rv, res.key, type_, snap))
        while len(self._wc_events) > self._wc_size:
            self._wc_trimmed_rv = self._wc_events.popleft()[0]

    def _wc_initial(self, res: Resource) -> List[Dict[str, Any]]:
        """Current bucket contents from the mirror (built once per bucket via
        a single backend read, maintained by _notify thereafter) — a watch
        storm of send_initial clients costs zero backend list reads."""
        mirror = self._wc_mirror.get(res.key)
        if mirror is None:
            mirror = {}
            for obj in self.backend.list(res.key, None, None):
                mirror[(apimeta.namespace_of(obj), apimeta.name_of(obj))] = (
                    apimeta.deepcopy(obj))
            self._wc_mirror[res.key] = mirror
        return list(mirror.values())

    @staticmethod
    def now() -> str:
        return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())

    # -- CRUD ---------------------------------------------------------------
    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        res, obj = _to_hub(obj)
        obj = apimeta.deepcopy(obj)
        md = obj.setdefault("metadata", {})
        name = md.get("name") or ""
        if not name and md.get("generateName"):
            name = md["generateName"] + uuid.uuid4().hex[:6]
            md["name"] = name
        if not name:
            raise Invalid(f"{res.kind}: metadata.name required")
        if res.namespaced and not md.get("namespace"):
            raise Invalid(f"{res.kind} {name}: metadata.namespace required")
        obj = self._admit("CREATE", res, obj)
        md = obj.setdefault("metadata", {})  # hooks may return a fresh copy
        with self._lock:
            ns, name = self._obj_key(res, md.get("namespace"), name)
            if self.backend.contains(res.key, ns, name):
                where = f"{ns}/{name}" if ns else name
                raise Conflict(f"{res.kind} {where} already exists")
            md["uid"] = md.get("uid") or str(uuid.uuid4())
            md["creationTimestamp"] = self.now()
            rv = self.backend.next_rv()
            md["resourceVersion"] = str(rv)
            md.setdefault("generation", 1)
            self.backend.put(res.key, ns, name, obj, rv, "ADDED")
            self._gc_track(res, obj)
            self._notify(res, WatchEvent("ADDED", obj))
            return apimeta.deepcopy(obj)

    def get(self, res: Resource, name: str, namespace: Optional[str] = None) -> Dict[str, Any]:
        res = conversion.hub_resource(res)
        with self._lock:
            ns, name = self._obj_key(res, namespace, name)
            obj = self.backend.get(res.key, ns, name)
            if obj is None:
                where = f" in {namespace}" if res.namespaced else ""
                raise NotFound(f'{res.kind} "{name}" not found{where}')
            return obj

    def list(
        self,
        res: Resource,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        field_selector: Optional[Dict[str, str]] = None,
    ) -> List[Dict[str, Any]]:
        return self.list_with_rv(res, namespace, label_selector, field_selector)[0]

    def list_with_rv(
        self,
        res: Resource,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        field_selector: Optional[Dict[str, str]] = None,
    ) -> Tuple[List[Dict[str, Any]], int]:
        """Snapshot list plus the store resourceVersion AT the snapshot.

        The RV is read under the same lock as the list so a client doing
        list→watch(resourceVersion=<list RV>) observes every write that lands
        after the snapshot (etcd returns the revision atomically with a range
        read for the same reason). Reading ``backend.current_rv()`` after the
        lock is released would open a gap in which writes are permanently
        missed by the informer pattern.
        """
        res = conversion.hub_resource(res)
        from ..runtime.metrics import METRICS  # lazy: runtime imports this module

        with self._lock:
            # every read that reaches the backing store — the counter the
            # scale harness watches to prove watch storms stay in the cache
            METRICS.counter("apiserver_store_list_total", resource=res.plural).inc()
            ns = namespace if (res.namespaced and namespace is not None) else None
            out = self.backend.list(res.key, ns, label_selector)
            rv = self.backend.current_rv()
            if field_selector:
                out = [o for o in out if _match_fields(o, field_selector)]
            return out, rv

    def list_page(
        self,
        res: Resource,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        field_selector: Optional[Dict[str, str]] = None,
        limit: Optional[int] = None,
        continue_token: Optional[str] = None,
    ) -> Tuple[List[Dict[str, Any]], int, Optional[str]]:
        """Paginated LIST pinned to a consistent resourceVersion snapshot.

        Page 1 takes one store snapshot (items + RV atomically, like
        list_with_rv) and parks it under an opaque continue token;
        continuation pages serve slices of that parked snapshot, so every
        page reflects the SAME RV no matter how much the store moved in
        between. Tokens are bounded (PAGE_SNAPSHOT_CAP) and expire
        (PAGE_SNAPSHOT_TTL_S); a stale/garbled token raises Expired (410
        ``Expired`` — K8s "continue token is too old"), telling the client
        to restart the list from scratch.
        """
        with self._lock:
            now = time.monotonic()
            for tid in [t for t, (exp, _, _) in self._page_snaps.items() if exp < now]:
                del self._page_snaps[tid]
            if continue_token:
                try:
                    tok = json.loads(base64.urlsafe_b64decode(
                        continue_token.encode()).decode())
                    tid, off = str(tok["id"]), int(tok["off"])
                except (ValueError, KeyError, TypeError):
                    raise Expired("malformed continue token; restart the list") from None
                snap = self._page_snaps.get(tid)
                if snap is None:
                    raise Expired(
                        "the provided continue token has expired; restart the list")
                _, rv, items = snap
            else:
                items, rv = self.list_with_rv(
                    res, namespace, label_selector, field_selector)
                tid, off = uuid.uuid4().hex[:16], 0
            if limit is None or off + limit >= len(items):
                if continue_token:
                    self._page_snaps.pop(tid, None)  # fully consumed
                return [apimeta.deepcopy(o) for o in items[off:]], rv, None
            if not continue_token:
                self._page_snaps[tid] = (now + PAGE_SNAPSHOT_TTL_S, rv, items)
                while len(self._page_snaps) > PAGE_SNAPSHOT_CAP:
                    self._page_snaps.popitem(last=False)
            next_token = base64.urlsafe_b64encode(
                json.dumps({"id": tid, "off": off + limit}).encode()).decode()
            return [apimeta.deepcopy(o) for o in items[off:off + limit]], rv, next_token

    def update(self, obj: Dict[str, Any], subresource: Optional[str] = None) -> Dict[str, Any]:
        res, obj = _to_hub(obj)
        obj = apimeta.deepcopy(obj)
        md = obj.setdefault("metadata", {})
        with self._lock:
            ns, name = self._obj_key(res, md.get("namespace"), md.get("name", ""))
            current = self.backend.get(res.key, ns, name)
            if current is None:
                raise NotFound(f'{res.kind} "{md.get("name")}" not found')
            cur_md = current["metadata"]
            if md.get("resourceVersion") and md["resourceVersion"] != cur_md["resourceVersion"]:
                raise Conflict(
                    f"{res.kind} {md.get('name')}: resourceVersion mismatch "
                    f"({md['resourceVersion']} != {cur_md['resourceVersion']})"
                )
            if subresource == "status":
                # Status updates only replace .status.
                merged = apimeta.deepcopy(current)
                merged["status"] = obj.get("status", {})
                obj = merged
                md = obj["metadata"]
            else:
                obj = self._admit("UPDATE", res, obj)
                md = obj.setdefault("metadata", {})
                # Immutable fields survive.
                md["uid"] = cur_md["uid"]
                md["creationTimestamp"] = cur_md["creationTimestamp"]
                if cur_md.get("deletionTimestamp"):
                    md["deletionTimestamp"] = cur_md["deletionTimestamp"]
                if _spec_changed(current, obj):
                    md["generation"] = cur_md.get("generation", 1) + 1
                else:
                    md["generation"] = cur_md.get("generation", 1)
            # No-op writes neither bump resourceVersion nor notify — without
            # this, a controller that unconditionally writes status would
            # requeue itself forever (controllers in the reference rely on
            # apiserver-side semantic no-op detection the same way).
            if _equal_ignoring_rv(current, obj):
                return current
            rv = self.backend.next_rv()
            md["resourceVersion"] = str(rv)
            self.backend.put(res.key, ns, name, obj, rv, "MODIFIED")
            self._gc_track(res, obj)
            self._notify(res, WatchEvent("MODIFIED", obj))
            # Finalizer removal on a deleting object completes the delete.
            if md.get("deletionTimestamp") and not md.get("finalizers"):
                drv = self.backend.next_rv()
                # DELETED events carry the deletion RV (etcd tombstone mod
                # revision) so watch consumers can order them against the
                # global RV stream — informer read-your-writes depends on it.
                md["resourceVersion"] = str(drv)
                self.backend.delete(res.key, ns, name, obj, drv)
                self._gc_untrack(obj)
                self._notify(res, WatchEvent("DELETED", obj))
            return apimeta.deepcopy(obj)

    def update_status(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        return self.update(obj, subresource="status")

    def patch(
        self,
        res: Resource,
        name: str,
        patch: Dict[str, Any],
        namespace: Optional[str] = None,
    ) -> Dict[str, Any]:
        """RFC 7386 JSON merge patch (null deletes a key)."""
        with self._lock:
            current = self.get(res, name, namespace)
            merged = _merge_patch(current, patch)
            merged["metadata"]["resourceVersion"] = current["metadata"]["resourceVersion"]
            return self.update(merged)

    def delete(self, res: Resource, name: str, namespace: Optional[str] = None) -> Dict[str, Any]:
        res = conversion.hub_resource(res)
        with self._lock:
            ns, name = self._obj_key(res, namespace, name)
            obj = self.backend.get(res.key, ns, name)
            if obj is None:
                where = f" in {namespace}" if res.namespaced else ""
                raise NotFound(f'{res.kind} "{name}" not found{where}')
            md = obj["metadata"]
            if md.get("finalizers"):
                if not md.get("deletionTimestamp"):
                    md["deletionTimestamp"] = self.now()
                    rv = self.backend.next_rv()
                    md["resourceVersion"] = str(rv)
                    self.backend.put(res.key, ns, name, obj, rv, "MODIFIED")
                    self._notify(res, WatchEvent("MODIFIED", obj))
                return apimeta.deepcopy(obj)
            drv = self.backend.next_rv()
            md["resourceVersion"] = str(drv)  # tombstone RV, see update()
            self.backend.delete(res.key, ns, name, obj, drv)
            self._gc_untrack(obj)
            self._notify(res, WatchEvent("DELETED", obj))
            return apimeta.deepcopy(obj)

    def delete_collection(
        self, res: Resource, namespace: Optional[str] = None, label_selector: Optional[Dict[str, str]] = None
    ) -> int:
        n = 0
        for obj in self.list(res, namespace=namespace, label_selector=label_selector):
            try:
                self.delete(res, apimeta.name_of(obj), apimeta.namespace_of(obj))
                n += 1
            except NotFound:
                pass
        return n

    # -- watch --------------------------------------------------------------
    def watch(
        self,
        res: Optional[Resource] = None,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        send_initial: bool = False,
        since_rv: Optional[int] = None,
        sync_marker: bool = False,
    ) -> _Watcher:
        """Open a watch stream. ``since_rv`` replays history before going
        live — etcd watch-window semantics. The bounded in-memory event ring
        (watch cache) is the primary replay source regardless of backend;
        the native write journal is the fallback for RVs the ring has
        already compacted. When neither covers the RV, raises Expired (410
        "too old resource version") and the caller relists (informer
        resync). ``watch_cache_size=0`` disables the ring: then a
        journal-less backend refuses since_rv outright (Invalid), the
        pre-ring behavior.

        ``sync_marker`` appends a ``SYNC`` event (empty object) after the
        initial-list/replay burst and before any live event. Informers use
        the marker as the relist boundary: everything cached that was NOT
        re-sent before SYNC vanished while disconnected, so synthetic
        DELETED events can fire (client-go emits deletes on relist for the
        same reason — handler-maintained state must not go stale)."""
        if res is not None:
            res = conversion.hub_resource(res)
        key = res.key if res else "*"
        w = _Watcher(key, namespace, label_selector)
        with self._lock:
            if since_rv is not None:
                ring_covers = self._wc_size > 0 and since_rv >= self._wc_trimmed_rv
                if ring_covers:
                    for rv, res_key, type_, obj in self._wc_events:
                        if rv > since_rv and w.matches(res_key, obj):
                            w.preload(WatchEvent(type_, apimeta.deepcopy(obj)))
                elif getattr(self.backend, "journal_capable", False):
                    try:
                        # Single-bucket watches filter in the C core — a
                        # resume must not marshal the whole journal.
                        records = self.backend.journal_since(
                            since_rv, bucket=res.key if res else None
                        )
                    except JournalExpired as e:
                        raise Expired(str(e)) from None
                    for rec in records:
                        if w.matches(rec.bucket, rec.object):
                            w.preload(WatchEvent(rec.type, rec.object))
                elif self._wc_size > 0:
                    raise Expired(
                        f"too old resource version: {since_rv} "
                        f"(oldest retained: {self._wc_trimmed_rv})")
                else:
                    raise Invalid("this backend keeps no journal; watch without since_rv")
            elif send_initial and res is not None:
                for obj in self._wc_initial(res):
                    if w.matches(res.key, obj):
                        w.preload(WatchEvent("ADDED", apimeta.deepcopy(obj)))
            if sync_marker:
                # The marker carries the store RV at the snapshot: informers
                # use it to jump their seen-RV to "current" on (re)connect,
                # making min-RV read barriers resolve immediately after sync.
                w.preload(
                    WatchEvent("SYNC", {"resourceVersion": str(self.backend.current_rv())})
                )
            self._watchers.append(w)
        return w

    # -- garbage collection (ownerReference cascade) ------------------------
    def collect_garbage(self) -> int:
        """Delete objects whose controller owner is gone (one sweep).

        Kubernetes runs this in kube-controller-manager; here it is invoked by
        the manager loop so e2e deletes cascade (Notebook → StatefulSet → Pod).
        Sweeps read the write-time ownership index — no store decode — and
        no-op entirely unless a write since the last sweep could have
        orphaned something (``_gc_dirty``).
        """
        deleted = 0
        with self._lock:
            if not self._gc_index_built:
                self._gc_rebuild()
            if not self._gc_dirty:
                return 0
            self._gc_dirty = False
            doomed: List[Tuple[Resource, str, Optional[str]]] = []
            for uid, owners in self._gc_owners.items():
                if all(o not in self._gc_uids for o in owners):
                    res_key, name, ns = self._gc_uids[uid]
                    res = next(r for r in REGISTRY.all() if r.key == res_key)
                    doomed.append((res, name, ns))
        for res, name, ns in doomed:
            try:
                # Each delete re-marks dirty, so grandchildren cascade on the
                # next sweep.
                self.delete(res, name, ns)
                deleted += 1
            except NotFound:
                pass
        return deleted


def _equal_ignoring_rv(old: Dict[str, Any], new: Dict[str, Any]) -> bool:
    a = apimeta.deepcopy(old)
    b = apimeta.deepcopy(new)
    for o in (a, b):
        o.get("metadata", {}).pop("resourceVersion", None)
        o.get("metadata", {}).pop("generation", None)
    return a == b


def _match_fields(obj: Dict[str, Any], field_selector: Dict[str, str]) -> bool:
    for path, want in field_selector.items():
        cur: Any = obj
        for part in path.split("."):
            if not isinstance(cur, dict) or part not in cur:
                return False
            cur = cur[part]
        if str(cur) != want:
            return False
    return True


def _spec_changed(old: Dict[str, Any], new: Dict[str, Any]) -> bool:
    for section in ("spec", "data"):
        if old.get(section) != new.get(section):
            return True
    for field in ("labels", "annotations", "finalizers", "ownerReferences"):
        if old["metadata"].get(field) != new.get("metadata", {}).get(field):
            return True
    return False


def _merge_patch(target: Any, patch: Any) -> Any:
    if not isinstance(patch, dict):
        return apimeta.deepcopy(patch)
    if not isinstance(target, dict):
        target = {}
    out = apimeta.deepcopy(target)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = _merge_patch(out.get(k), v)
    return out
