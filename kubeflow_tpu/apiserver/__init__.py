from .store import ApiError, Conflict, Forbidden, Invalid, NotFound, Store, WatchEvent  # noqa: F401
from .client import Client  # noqa: F401
