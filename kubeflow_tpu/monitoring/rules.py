"""Recording rules and multi-window multi-burn-rate SLO alerts.

The alert shape is the SRE-workbook recipe: for an objective like "99% of
requests under 250ms", the error budget is 1% and the *burn rate* is
(bad fraction)/(budget). An alert pair fires when the burn rate exceeds a
factor in BOTH a short and a long window — the short window gives fast
detection and fast reset, the long window gives resistance to blips. The
default pairs are the workbook's page (5m/1h, 14.4×) and ticket (30m/6h,
6×) tiers.

Lifecycle per pair: inactive → pending (condition holds, ``for_s`` not yet
served) → firing → resolved. Firing alerts surface three ways: the
``alerts_firing{alertname,severity}`` gauge, ``/debug/alerts`` (via
``obs.register_debug_source``, wired by plane.py), and — when a client is
attached — K8s Warning Events through ``runtime/events.py``, whose
recorder deduplicates repeat emissions into ONE Event with a bumped count.

"No data" is never "no errors": the bad fraction is ``None`` when a window
saw no traffic, and a None on either window holds the alert's current
state rather than resolving it — a scrape gap must not silently close a
page.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from ..runtime.metrics import METRICS, MetricsRegistry
from .tsdb import TSDB, Matchers

log = logging.getLogger("kubeflow_tpu.monitoring")


@dataclass(frozen=True)
class BurnRateWindow:
    short_s: float
    long_s: float
    factor: float
    severity: str


#: SRE-workbook multi-window multi-burn-rate defaults: a 14.4× burn exhausts
#: a 30-day budget in ~2 days (page), a 6× burn in ~5 days (ticket)
DEFAULT_BURN_RATE_WINDOWS: Tuple[BurnRateWindow, ...] = (
    BurnRateWindow(short_s=300.0, long_s=3600.0, factor=14.4, severity="page"),
    BurnRateWindow(short_s=1800.0, long_s=21600.0, factor=6.0, severity="ticket"),
)


@dataclass
class RecordingRule:
    """Evaluate ``fn(tsdb, now) -> iterable of (labels, value)`` each tick
    and write the results back as gauge series named ``record`` — the
    precompute-once pattern for anything a dashboard polls."""

    record: str
    fn: Callable[[TSDB, float], Iterable[Tuple[Dict[str, str], float]]]


@dataclass
class _PairState:
    state: str = "inactive"  # inactive | pending | firing | resolved
    pending_since: Optional[float] = None
    firing_since: Optional[float] = None
    resolved_at: Optional[float] = None
    burn_short: Optional[float] = None
    burn_long: Optional[float] = None


class SLOBurnRateAlert:
    """Latency-SLO burn-rate alert over one histogram family.

    ``objective`` is the good fraction (0.99 → 1% budget); a request is bad
    when it lands above ``threshold_s`` — which should align with a bucket
    bound of the histogram, since bucket resolution is all the exposition
    gives us. ``matchers`` scope the series (e.g. ``{"job": "serving"}``).
    """

    def __init__(
        self,
        name: str,
        metric: str,
        threshold_s: float,
        objective: float = 0.99,
        windows: Sequence[BurnRateWindow] = DEFAULT_BURN_RATE_WINDOWS,
        matchers: Optional[Matchers] = None,
        for_s: float = 0.0,
        involved: Optional[dict] = None,
    ) -> None:
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective {objective} outside (0, 1)")
        self.name = name
        self.metric = metric
        self.threshold_s = float(threshold_s)
        self.objective = float(objective)
        self.budget = 1.0 - self.objective
        self.windows = tuple(windows)
        self.matchers = matchers
        self.for_s = float(for_s)
        self.involved = involved
        self._pairs: Dict[str, _PairState] = {
            w.severity: _PairState() for w in self.windows
        }

    def bad_fraction(self, tsdb: TSDB, window_s: float, now: float) -> Optional[float]:
        """Fraction of observations above the threshold in the window, or
        None when the window carried no traffic (no data ≠ no errors)."""
        snap = tsdb.windowed_bucket_counts(self.metric, window_s, now, self.matchers)
        if snap is None:
            return None
        buckets, counts, total = snap
        good = 0
        for bound, count in zip(buckets, counts):
            if bound <= self.threshold_s + 1e-12:
                good += count
        return max(0.0, (total - good) / total)

    def evaluate(self, tsdb: TSDB, now: float) -> List[dict]:
        """Advance every window pair's state machine; returns one status
        dict per pair, flagging ``fired``/``resolved`` transitions so the
        engine knows when to emit Events."""
        statuses: List[dict] = []
        for w in self.windows:
            st = self._pairs[w.severity]
            burn_short = self._burn(tsdb, w.short_s, now)
            burn_long = self._burn(tsdb, w.long_s, now)
            st.burn_short, st.burn_long = burn_short, burn_long
            fired = resolved = False
            if burn_short is None or burn_long is None:
                # scrape gap / no traffic: hold state, never auto-resolve
                pass
            elif burn_short > w.factor and burn_long > w.factor:
                if st.state in ("inactive", "resolved"):
                    st.state = "pending"
                    st.pending_since = now
                if st.state == "pending" and now - (st.pending_since or now) >= self.for_s:
                    st.state = "firing"
                    st.firing_since = now
                    fired = True
            else:
                if st.state == "firing":
                    st.state = "resolved"
                    st.resolved_at = now
                    resolved = True
                elif st.state == "pending":
                    st.state = "inactive"
                    st.pending_since = None
            statuses.append({
                "alertname": self.name,
                "severity": w.severity,
                "state": st.state,
                "metric": self.metric,
                "threshold_s": self.threshold_s,
                "objective": self.objective,
                "factor": w.factor,
                "windows_s": [w.short_s, w.long_s],
                "burn_short": burn_short,
                "burn_long": burn_long,
                "since": st.firing_since if st.state == "firing" else st.pending_since,
                "fired": fired,
                "resolved": resolved,
            })
        return statuses

    def _burn(self, tsdb: TSDB, window_s: float, now: float) -> Optional[float]:
        frac = self.bad_fraction(tsdb, window_s, now)
        return None if frac is None else frac / self.budget


class RuleEngine:
    """Evaluate recording rules then alerts against one TSDB, publishing
    eval latency (``monitoring_rule_eval_seconds``), the per-alert
    ``alerts_firing`` gauge, and — with a client — K8s Events. Re-emitting
    the same Warning while an alert stays firing is intentional: the
    EventRecorder's dedup turns the stream into one Event with a rising
    ``count``, which is exactly the operator-facing contract."""

    def __init__(self, tsdb: TSDB, client=None,
                 registry: MetricsRegistry = METRICS,
                 component: str = "slo-monitor",
                 repeat_s: float = 30.0) -> None:
        self.tsdb = tsdb
        self._client = client
        self._registry = registry
        self._component = component
        #: minimum seconds between repeated firing Events for one alert
        #: (Alertmanager's repeat_interval). Emitting on EVERY eval would
        #: drain the EventRecorder's spam-filter tokens and starve the
        #: resolve notification.
        self.repeat_s = repeat_s
        self._last_emit: Dict[Tuple[str, str], float] = {}
        self.recording_rules: List[RecordingRule] = []
        self.alerts: List[SLOBurnRateAlert] = []
        self.last_statuses: List[dict] = []
        self.evaluations = 0

    def add(self, rule) -> None:
        if isinstance(rule, RecordingRule):
            self.recording_rules.append(rule)
        elif isinstance(rule, SLOBurnRateAlert):
            self.alerts.append(rule)
        else:
            raise TypeError(f"not a rule: {rule!r}")

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        now = time.time() if now is None else now
        with self._registry.timer("monitoring_rule_eval_seconds"):
            for rule in self.recording_rules:
                try:
                    self.tsdb.set_kind(rule.record, "gauge")
                    for labels, value in rule.fn(self.tsdb, now):
                        self.tsdb.add_sample(rule.record, labels, now, value)
                except Exception:
                    log.exception("recording rule %s failed", rule.record)
                    self._registry.counter(
                        "monitoring_rule_failures_total", record=rule.record
                    ).inc()
            statuses: List[dict] = []
            for alert in self.alerts:
                statuses.extend(alert.evaluate(self.tsdb, now))
            for s in statuses:
                self._publish(s, now)
        self.last_statuses = statuses
        self.evaluations += 1
        return statuses

    def _publish(self, status: dict, now: float) -> None:
        firing = status["state"] == "firing"
        self._registry.gauge(
            "alerts_firing",
            alertname=status["alertname"],
            severity=status["severity"],
        ).set(1.0 if firing else 0.0)
        if self._client is None:
            return
        key = (status["alertname"], status["severity"])
        if firing:
            last = self._last_emit.get(key)
            if last is not None and now - last < self.repeat_s:
                return  # within the repeat interval: the Event already says it
            self._last_emit[key] = now
        elif status["resolved"]:
            self._last_emit.pop(key, None)
        involved = self._involved(status)
        recorder = self._client.events
        if firing:
            recorder.emit(
                involved,
                reason=status["alertname"],
                message=(
                    f"SLO burn-rate alert {status['alertname']} "
                    f"({status['severity']}) firing: burn "
                    f"{_fmt_burn(status['burn_short'])}x/"
                    f"{_fmt_burn(status['burn_long'])}x over "
                    f"{int(status['windows_s'][0])}s/{int(status['windows_s'][1])}s "
                    f"windows exceeds {status['factor']}x "
                    f"(objective {status['objective']}, "
                    f"threshold {status['threshold_s']}s on {status['metric']})"
                ),
                type_="Warning",
                component=self._component,
            )
        elif status["resolved"]:
            recorder.emit(
                involved,
                reason=f"{status['alertname']}Resolved",
                message=(
                    f"SLO burn-rate alert {status['alertname']} "
                    f"({status['severity']}) resolved"
                ),
                type_="Normal",
                component=self._component,
            )

    def _involved(self, status: dict) -> dict:
        for alert in self.alerts:
            if alert.name == status["alertname"] and alert.involved is not None:
                return alert.involved
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": status["alertname"].lower(),
                         "namespace": "kubeflow-system"},
        }

    def snapshot(self) -> dict:
        """The ``/debug/alerts`` payload."""
        return {
            "evaluations": self.evaluations,
            "alerts": [
                {k: v for k, v in s.items() if k not in ("fired", "resolved")}
                for s in self.last_statuses
            ],
            "recording_rules": [r.record for r in self.recording_rules],
        }


def _fmt_burn(burn: Optional[float]) -> str:
    return "?" if burn is None else f"{burn:.1f}"
