"""Goodput ledger: where did the workload's wallclock go? (ISSUE 19)

The platform can trace a slow bind (monitoring/traces.py) and attribute an
MFU gap inside a step (training/attribution.py), but neither measures what
fraction of a training workload's *wallclock* was productive — time lost to
scheduling waits, compiles, checkpoint saves/restores, preemption replay
and reshard is invisible, which is exactly the badput the cold-start and
preemptible-actor roadmap items must prove they removed.

:class:`GoodputLedger` decomposes an incarnation-spanning run into goodput
plus named badput buckets with the repo's honesty contract (the PR 8
attribution discipline, applied across process restarts instead of inside
a step):

- every bucket is MEASURED, never modeled; the unmeasured residual lands in
  ``other`` instead of inflating a named bucket,
- the emitted fractions sum to exactly 1.0,
- ``reconstructionError`` reports how much of the measured wallclock the
  named (non-``other``) parts reconstruct — the goodput e2e gates it ≤ 5%.

Producers (``ElasticTrainer``) feed the ledger through five calls:
``note(bucket, seconds)`` for directly-timed intervals, ``step(index,
seconds)`` for per-step wall time (replayed step indices — at or below the
high-water mark of a previous incarnation — are badput, bucket
``preemption_replay``), ``begin_incarnation``/``end_incarnation`` for the
per-incarnation metadata section, and an optional ``attach_step_clock``
(a ``tpu.profiling.StepClock``) whose separately-accumulated compile and
``data_wait`` phases are drained out of step wall time into their own
buckets.

Surfaces: ``training_badput_seconds_total{bucket}`` /
``training_goodput_seconds_total`` counters and the
``training_goodput_fraction{workload}`` gauge (collector-refreshed at every
scrape, so the monitoring plane's TSDB sees it end to end),
``GET /debug/goodput`` on every observability-mounted server, a
``platform:training_goodput_fraction`` recording rule recomputing the
measured share TSDB-side, and :class:`TenantChipMeter` /
``serving_goodput_view`` for the per-tenant accounting half
(``tenant_chip_seconds_total{namespace}`` from the scheduler ledger's
bind/unbind lifecycle, token goodput from the serving waste counters).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Hashable, Iterable, List, Optional, Tuple

from ..runtime.metrics import METRICS, MetricsRegistry
from ..runtime.obs import register_debug_source
from .rules import RecordingRule

#: badput buckets in display order; ``other`` is always the computed
#: residual (wallclock minus everything measured), never written directly
BADPUT_BUCKETS = (
    "scheduling_wait",
    "compile",
    "checkpoint_save",
    "checkpoint_restore",
    "preemption_replay",
    "reshard",
    "data_wait",
    "other",
)

MEASURED_BUCKETS = tuple(b for b in BADPUT_BUCKETS if b != "other")


class GoodputLedger:
    """Incarnation-spanning goodput/badput decomposition for one workload.

    Thread-safe; registry writes (counters, the fraction gauge) happen
    outside the internal lock so no lock order ties this to the metrics
    registry. A collector keyed ``goodput:<workload>`` refreshes the
    ``training_goodput_fraction`` gauge at every exposition render, so a
    mid-run scrape sees the live fraction, not the last ``finish()``.
    """

    def __init__(
        self,
        workload: str = "training",
        *,
        registry: MetricsRegistry = METRICS,
        clock: Callable[[], float] = time.perf_counter,
    ) -> None:
        self.workload = workload
        self._registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        self._started: Optional[float] = None
        self._ended: Optional[float] = None
        self._goodput = 0.0
        self._badput: Dict[str, float] = {b: 0.0 for b in MEASURED_BUCKETS}
        self._high_water = -1
        self._incarnations: List[Dict[str, Any]] = []
        self._open: Optional[Dict[str, Any]] = None
        self._step_clock: Optional[Any] = None
        self._compile_seen = 0.0
        self._clock_steps_seen = 0
        _register_ledger(self)
        registry.register_collector(f"goodput:{workload}", self._refresh_gauge)

    # -- producer API --------------------------------------------------------
    def start(self) -> None:
        """Anchor the workload wallclock (idempotent: first call wins)."""
        with self._lock:
            if self._started is None:
                self._started = self._clock()
            self._ended = None

    def attach_step_clock(self, step_clock: Any) -> None:
        """Adopt a StepClock-shaped source (``compile_s`` accumulator +
        ``steps`` phase records): compile and ``data_wait`` time recorded
        during a step is drained out of that step's wall time into the
        matching badput buckets."""
        with self._lock:
            self._step_clock = step_clock
            self._compile_seen = float(getattr(step_clock, "compile_s", 0.0))
            self._clock_steps_seen = len(getattr(step_clock, "steps", ()))

    def begin_incarnation(self, attempt: int) -> None:
        with self._lock:
            if self._started is None:
                self._started = self._clock()
            if self._open is not None:
                self._close_incarnation_locked("abandoned", None)
            self._open = {
                "attempt": int(attempt),
                "startedAt": self._clock(),
                "goodputSeconds": 0.0,
                "badputSeconds": {b: 0.0 for b in MEASURED_BUCKETS},
                "replaySteps": 0,
            }

    def note(self, bucket: str, seconds: float) -> None:
        """Account a directly-measured badput interval."""
        if bucket not in MEASURED_BUCKETS:
            raise ValueError(f"unknown badput bucket {bucket!r} "
                             f"(one of {MEASURED_BUCKETS})")
        seconds = max(0.0, float(seconds))
        with self._lock:
            self._note_locked(bucket, seconds)
        self._registry.counter(
            "training_badput_seconds_total", bucket=bucket).inc(seconds)

    def step(self, index: int, wall_seconds: float) -> None:
        """Account one training step's wall time. Compile/data_wait drained
        from the attached StepClock come off the top; the remainder is
        goodput for a first-time step index, ``preemption_replay`` for a
        step at or below a previous incarnation's high-water mark."""
        wall_seconds = max(0.0, float(wall_seconds))
        emit: List[Tuple[str, float]] = []
        with self._lock:
            compile_d, data_d = self._drain_clock_locked()
            productive = max(0.0, wall_seconds - compile_d - data_d)
            if compile_d > 0.0:
                self._note_locked("compile", compile_d)
                emit.append(("compile", compile_d))
            if data_d > 0.0:
                self._note_locked("data_wait", data_d)
                emit.append(("data_wait", data_d))
            if index <= self._high_water:
                self._note_locked("preemption_replay", productive)
                emit.append(("preemption_replay", productive))
                if self._open is not None:
                    self._open["replaySteps"] += 1
            else:
                self._high_water = index
                self._goodput += productive
                if self._open is not None:
                    self._open["goodputSeconds"] += productive
        for bucket, seconds in emit:
            self._registry.counter(
                "training_badput_seconds_total", bucket=bucket).inc(seconds)
        if not any(b == "preemption_replay" for b, _s in emit):
            self._registry.counter("training_goodput_seconds_total").inc(
                max(0.0, wall_seconds - sum(s for _b, s in emit)))

    def end_incarnation(self, outcome: str,
                        end_step: Optional[int] = None) -> Dict[str, Any]:
        """Close the open incarnation; returns its goodput section (the
        dict the trainer embeds in the incarnation metadata)."""
        with self._lock:
            section = self._close_incarnation_locked(outcome, end_step)
        return section if section is not None else {}

    def finish(self) -> Dict[str, Any]:
        """Stop the wallclock (idempotent) and return a final snapshot."""
        with self._lock:
            if self._open is not None:
                self._close_incarnation_locked("abandoned", None)
            if self._ended is None and self._started is not None:
                self._ended = self._clock()
            snap = self._snapshot_locked()
        self._set_gauge(snap)
        return snap

    # -- consumer API --------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """The decomposition right now: seconds per bucket, fractions that
        sum to exactly 1.0, and the honesty number (``reconstructionError``
        — the share of wallclock the named buckets fail to reconstruct)."""
        with self._lock:
            return self._snapshot_locked()

    # -- internals -----------------------------------------------------------
    def _note_locked(self, bucket: str, seconds: float) -> None:
        self._badput[bucket] += seconds
        if self._open is not None:
            self._open["badputSeconds"][bucket] += seconds

    def _drain_clock_locked(self) -> Tuple[float, float]:
        clock = self._step_clock
        if clock is None:
            return 0.0, 0.0
        compile_total = float(getattr(clock, "compile_s", 0.0))
        compile_d = max(0.0, compile_total - self._compile_seen)
        self._compile_seen = compile_total
        steps = getattr(clock, "steps", [])
        data_d = 0.0
        for rec in steps[self._clock_steps_seen:]:
            data_d += float(rec.get("data_wait", 0.0))
        self._clock_steps_seen = len(steps)
        return compile_d, data_d

    def _close_incarnation_locked(
            self, outcome: str, end_step: Optional[int]
    ) -> Optional[Dict[str, Any]]:
        section = self._open
        self._open = None
        if section is None:
            return None
        started_at = section.pop("startedAt")
        section["wallclockSeconds"] = max(0.0, self._clock() - started_at)
        section["outcome"] = outcome
        if end_step is not None:
            section["endStep"] = int(end_step)
        self._incarnations.append(section)
        return section

    def _snapshot_locked(self) -> Dict[str, Any]:
        if self._started is None:
            wall = 0.0
        else:
            wall = max(0.0, (self._ended or self._clock()) - self._started)
        measured = dict(self._badput)
        named = self._goodput + sum(measured.values())
        other = max(0.0, wall - named)
        parts = dict(measured)
        parts["other"] = other
        denom = self._goodput + sum(parts.values())
        if denom <= 0.0:
            fractions = {"goodput": 1.0}
            fractions.update({b: 0.0 for b in BADPUT_BUCKETS})
        else:
            fractions = {"goodput": self._goodput / denom}
            for b in BADPUT_BUCKETS:
                if b != "other":
                    fractions[b] = parts[b] / denom
            # the honesty contract is checked with ==, not ≈: the residual
            # bucket closes the plain left-to-right sum (the exact
            # computation consumers run) to 1.0. For p = that partial sum,
            # fl(p + fl(1 - p)) == 1.0 whenever p ∈ [0, 2] — Sterbenz makes
            # the subtraction exact for p ≥ 0.5, and below that the ≤2⁻⁵⁴
            # rounding error still rounds back onto 1.0 — so the ~1e-16
            # float slop of the per-bucket divisions lands in ``other``
            # alongside the unmeasured wallclock it already represents.
            partial = 0.0
            for value in fractions.values():
                partial += value
            fractions["other"] = 1.0 - partial
        return {
            "workload": self.workload,
            "wallclockSeconds": wall,
            "goodputSeconds": self._goodput,
            "badputSeconds": parts,
            "measuredSeconds": named,
            "reconstructionError": (abs(wall - named) / wall) if wall > 0 else 0.0,
            "goodputFraction": fractions["goodput"],
            "fractions": fractions,
            "incarnations": list(self._incarnations),
        }

    def _refresh_gauge(self) -> None:
        with self._lock:
            started = self._started is not None
            snap = self._snapshot_locked() if started else None
        if snap is not None:
            self._set_gauge(snap)

    def _set_gauge(self, snap: Dict[str, Any]) -> None:
        self._registry.gauge(
            "training_goodput_fraction", workload=self.workload
        ).set(round(snap["goodputFraction"], 6))


# -- per-tenant chip metering --------------------------------------------------


class TenantChipMeter:
    """``tenant_chip_seconds_total{namespace}`` from bind/unbind lifecycle.

    The scheduler's ChipLedger calls ``on_bind`` for every record it puts
    and ``on_unbind`` for every record it drops; an interval stays open
    while the pod is bound. Replay-idempotent: the informer echo of a bind
    the scheduler already assumed carries an identical (namespace, chips)
    record and must NOT restart the interval. ``flush`` (registered as a
    metrics collector, so it runs at every scrape) settles open intervals
    incrementally — the counter tracks live binds within one scrape
    interval instead of only materializing at unbind.

    Counter increments happen after the internal lock is released, so the
    meter imposes no lock order against the metrics registry (it is called
    under the ChipLedger's lock).
    """

    def __init__(self, *, registry: MetricsRegistry = METRICS,
                 clock: Callable[[], float] = time.monotonic,
                 collector_key: Optional[str] = "tenant-chip-meter") -> None:
        self._registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        #: key -> [namespace, chips, interval anchor (last settle)]
        self._open: Dict[Hashable, List[Any]] = {}
        if collector_key is not None:
            registry.register_collector(collector_key, self.flush)

    def on_bind(self, key: Hashable, namespace: Optional[str],
                chips: int) -> None:
        ns = namespace or "default"
        chips = int(chips)
        now = self._clock()
        settled: List[Tuple[str, float]] = []
        with self._lock:
            cur = self._open.get(key)
            if cur is not None:
                if cur[0] == ns and cur[1] == chips:
                    return  # informer echo of an assumed bind: same interval
                settled.append(self._settle_locked(cur, now))
            self._open[key] = [ns, chips, now]
        self._emit(settled)

    def on_unbind(self, key: Hashable) -> None:
        now = self._clock()
        settled: List[Tuple[str, float]] = []
        with self._lock:
            cur = self._open.pop(key, None)
            if cur is not None:
                settled.append(self._settle_locked(cur, now))
        self._emit(settled)

    def flush(self) -> None:
        """Settle every open interval up to now (scrape-time collector)."""
        now = self._clock()
        with self._lock:
            settled = [self._settle_locked(cur, now)
                       for cur in self._open.values()]
        self._emit(settled)

    def open_intervals(self) -> Dict[str, int]:
        """namespace -> currently-bound chips (for /debug/goodput)."""
        out: Dict[str, int] = {}
        with self._lock:
            for ns, chips, _anchor in self._open.values():
                out[ns] = out.get(ns, 0) + chips
        return out

    def _settle_locked(self, cur: List[Any], now: float) -> Tuple[str, float]:
        ns, chips, anchor = cur
        dt = max(0.0, now - anchor)
        cur[2] = now
        return ns, chips * dt

    def _emit(self, settled: Iterable[Tuple[str, float]]) -> None:
        for ns, chip_seconds in settled:
            if chip_seconds > 0.0:
                self._registry.counter(
                    "tenant_chip_seconds_total", namespace=ns
                ).inc(chip_seconds)


#: the scheduler ledger's process-wide meter (kubeflow_tpu/scheduler/ledger.py
#: calls it from _put/_drop under its own lock)
TENANT_METER = TenantChipMeter()


# -- serving goodput view ------------------------------------------------------


def serving_goodput_view(registry: MetricsRegistry = METRICS) -> Dict[str, Any]:
    """Token-level goodput for the serving plane, from the waste counters
    the continuous batcher already maintains: delivered tokens vs tokens
    computed for nobody (``serving_discarded_tail_tokens_total``, of which
    ``serving_wasted_decode_tokens_total`` is the deadline/abandonment
    subset — the ISSUE 9 goodput-loss counter), plus the request-level
    shed/expiry context."""
    delivered = registry.total("serving_tokens_out_total")
    discarded = registry.total("serving_discarded_tail_tokens_total")
    wasted = registry.total("serving_wasted_decode_tokens_total")
    generated = delivered + discarded
    return {
        "deliveredTokens": delivered,
        "discardedTailTokens": discarded,
        "wastedDecodeTokens": wasted,
        "shedRequests": registry.total("serving_shed_total"),
        "deadlineExpired": registry.total("serving_deadline_expired_total"),
        "tokenGoodputFraction":
            (delivered / generated) if generated > 0 else None,
    }


# -- surfacing: debug source + recording rule ---------------------------------

_LEDGERS_LOCK = threading.Lock()
_LEDGERS: Dict[str, GoodputLedger] = {}


def _register_ledger(ledger: GoodputLedger) -> None:
    with _LEDGERS_LOCK:
        _LEDGERS[ledger.workload] = ledger


def get_ledger(workload: str = "training") -> GoodputLedger:
    """The process-wide ledger for ``workload`` (created on first use)."""
    with _LEDGERS_LOCK:
        existing = _LEDGERS.get(workload)
    return existing if existing is not None else GoodputLedger(workload)


def debug_goodput(_req: Any = None) -> Dict[str, Any]:
    """``GET /debug/goodput``: every workload ledger's decomposition, the
    serving token-goodput view, and the live per-tenant bound-chip set."""
    with _LEDGERS_LOCK:
        ledgers = list(_LEDGERS.values())
    return {
        "workloads": {led.workload: led.snapshot() for led in ledgers},
        "serving": serving_goodput_view(),
        "tenants": {"boundChips": TENANT_METER.open_intervals()},
    }


register_debug_source("goodput", debug_goodput)


def goodput_recording_rules() -> List[RecordingRule]:
    """Recording rules for the monitoring plane's RuleEngine.

    ``platform:training_goodput_fraction`` recomputes the measured goodput
    share TSDB-side from the scraped second counters — the federation-level
    cross-check of the in-process ``training_goodput_fraction`` gauge. (The
    counters carry only MEASURED seconds, so this is the measured share;
    the unmeasured ``other`` residual is visible in /debug/goodput and the
    gauge, which divide by true wallclock.)"""

    def _measured_fraction(tsdb: Any, _now: float):
        good = sum(v for _l, _t, v in
                   tsdb.latest("training_goodput_seconds_total"))
        bad = sum(v for _l, _t, v in
                  tsdb.latest("training_badput_seconds_total"))
        if good + bad > 0.0:
            yield {}, good / (good + bad)

    return [RecordingRule(record="platform:training_goodput_fraction",
                          fn=_measured_fraction)]
