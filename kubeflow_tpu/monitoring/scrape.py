"""Strict OpenMetrics parser + the scrape loop feeding the TSDB.

The parser accepts exactly the dialect ``MetricsRegistry.render()`` emits —
``# TYPE`` families, counter/gauge/histogram samples, OpenMetrics exemplar
suffixes on bucket lines, a terminating ``# EOF`` — and rejects everything
else with a line-numbered ``ParseError``. Being strict about our own format
is the point: a platform that silently tolerates a corrupt exposition ships
corrupt SLO math. Parsed samples keep their raw value/label/exemplar tokens
so ``render_exposition`` round-trips the input byte-faithfully (the
compliance test in tests/test_monitoring.py).

The ``Scraper`` pulls ``/metrics`` from a target set — a static list plus
live discovery of Pods carrying the ``monitoring.kubeflow.org/scrape``
annotations (fleet replicas annotate themselves via
``EngineFleet(metrics_url=...)``; ops servers are annotated by whoever runs
them) — writes every sample into the TSDB with ``instance``/``job`` target
labels, publishes per-target ``up`` and ``scrape_duration_seconds``, and
marks a target's series stale after ``stale_after`` consecutive misses.
"""

from __future__ import annotations

import logging
import re
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple
from urllib.parse import urlparse

from ..runtime.metrics import METRICS, MetricsRegistry
from .tsdb import TSDB

log = logging.getLogger("kubeflow_tpu.monitoring")

#: Pod annotations driving scrape discovery (the prometheus.io/scrape idiom)
SCRAPE_ANNOTATION = "monitoring.kubeflow.org/scrape"
SCRAPE_URL_ANNOTATION = "monitoring.kubeflow.org/url"
SCRAPE_JOB_ANNOTATION = "monitoring.kubeflow.org/job"

_METRIC_KINDS = ("counter", "gauge", "histogram", "untyped")

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) ([a-z]+)$"
)
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_EXEMPLAR_RE = re.compile(r"^\{(.*)\} (\S+) (\S+)$")


class ParseError(ValueError):
    def __init__(self, lineno: int, message: str):
        super().__init__(f"line {lineno}: {message}")
        self.lineno = lineno


def _unescape(value: str) -> str:
    return value.replace(r"\n", "\n").replace(r"\"", '"').replace("\\\\", "\\")


def _parse_labels(raw: str, lineno: int) -> Dict[str, str]:
    """Parse the inner text of ``{...}``; strict — the matched pairs joined
    by commas must reconstruct the raw text exactly, so stray tokens between
    pairs are an error rather than silently dropped."""
    if not raw:
        return {}
    pairs = list(_LABEL_PAIR_RE.finditer(raw))
    rebuilt = ",".join(m.group(0) for m in pairs)
    if rebuilt != raw.rstrip(","):
        raise ParseError(lineno, f"malformed label set {{{raw}}}")
    out: Dict[str, str] = {}
    for m in pairs:
        out[m.group(1)] = _unescape(m.group(2))
    return out


@dataclass
class Sample:
    """One exposition line, parsed and raw at once: ``labels``/``value`` are
    the semantic view; the ``raw_*`` tokens reproduce the input byte-for-byte
    (exemplar suffixes ride through ``raw_exemplar`` untouched)."""

    name: str
    labels: Dict[str, str]
    value: float
    raw_labels: str = ""
    raw_value: str = ""
    raw_exemplar: str = ""

    def render(self) -> str:
        labels = f"{{{self.raw_labels}}}" if self.raw_labels else ""
        value = self.raw_value or _format_value(self.value)
        return f"{self.name}{labels} {value}{self.raw_exemplar}"


@dataclass
class Family:
    name: str
    kind: str
    samples: List[Sample] = field(default_factory=list)

    def sample_names(self) -> Tuple[str, ...]:
        if self.kind == "histogram":
            return (f"{self.name}_bucket", f"{self.name}_sum",
                    f"{self.name}_count")
        return (self.name,)


def _format_value(v: float) -> str:
    return str(int(v)) if float(v).is_integer() and abs(v) < 1e15 else str(float(v))


def _scan_label_block(line: str, start: int, lineno: int) -> int:
    """Index just past the ``}`` closing the label block opened at ``start``
    (which must point at ``{``). Quote- and escape-aware."""
    i = start + 1
    in_quotes = False
    while i < len(line):
        c = line[i]
        if in_quotes:
            if c == "\\":
                i += 1
            elif c == '"':
                in_quotes = False
        elif c == '"':
            in_quotes = True
        elif c == "}":
            return i + 1
        i += 1
    raise ParseError(lineno, "unterminated label set")


def _parse_sample_line(line: str, lineno: int) -> Sample:
    m = _NAME_RE.match(line)
    if not m:
        raise ParseError(lineno, f"expected metric name: {line!r}")
    name = m.group(0)
    idx = m.end()
    raw_labels = ""
    if idx < len(line) and line[idx] == "{":
        end = _scan_label_block(line, idx, lineno)
        raw_labels = line[idx + 1:end - 1]
        idx = end
    rest = line[idx:]
    if not rest.startswith(" "):
        raise ParseError(lineno, f"expected value after name/labels: {line!r}")
    rest = rest[1:]
    raw_exemplar = ""
    if " # " in rest:
        value_tok, exemplar = rest.split(" # ", 1)
        if not _EXEMPLAR_RE.match(exemplar):
            raise ParseError(lineno, f"malformed exemplar: {exemplar!r}")
        raw_exemplar = f" # {exemplar}"
    else:
        value_tok = rest
    value_tok = value_tok.strip()
    if not value_tok or " " in value_tok:
        raise ParseError(lineno, f"expected a single value token: {rest!r}")
    try:
        value = float(value_tok)
    except ValueError:
        raise ParseError(lineno, f"bad value {value_tok!r}") from None
    return Sample(
        name=name,
        labels=_parse_labels(raw_labels, lineno),
        value=value,
        raw_labels=raw_labels,
        raw_value=value_tok,
        raw_exemplar=raw_exemplar,
    )


def parse_exposition(text: str, require_eof: bool = True) -> List[Family]:
    """Parse one exposition document into ordered families. Strict: every
    sample must belong to the most recently declared ``# TYPE`` family,
    ``# EOF`` must terminate the document (and nothing may follow it), and
    any line that is neither a comment nor a well-formed sample raises."""
    if text and not text.endswith("\n"):
        raise ParseError(text.count("\n") + 1, "exposition must end with a newline")
    families: List[Family] = []
    by_name: Dict[str, Family] = {}
    current: Optional[Family] = None
    saw_eof = False
    for lineno, line in enumerate(text.split("\n")[:-1], start=1):
        if saw_eof:
            raise ParseError(lineno, "content after # EOF")
        if not line.strip():
            continue
        if line.startswith("#"):
            if line == "# EOF":
                saw_eof = True
                continue
            if line.startswith("# HELP "):
                continue
            tm = _TYPE_RE.match(line)
            if not tm:
                raise ParseError(lineno, f"malformed comment line: {line!r}")
            name, kind = tm.group(1), tm.group(2)
            if kind not in _METRIC_KINDS:
                raise ParseError(lineno, f"unknown metric kind {kind!r}")
            if name in by_name:
                raise ParseError(lineno, f"duplicate # TYPE for {name}")
            current = Family(name=name, kind=kind)
            by_name[name] = current
            families.append(current)
            continue
        sample = _parse_sample_line(line, lineno)
        if current is None:
            raise ParseError(lineno, f"sample {sample.name} before any # TYPE")
        if sample.name not in current.sample_names():
            raise ParseError(
                lineno,
                f"sample {sample.name} does not belong to family "
                f"{current.name} ({current.kind})",
            )
        if sample.raw_exemplar and current.kind not in ("histogram", "counter"):
            raise ParseError(lineno, f"exemplar on a {current.kind} sample")
        current.samples.append(sample)
    if require_eof and not saw_eof:
        raise ParseError(text.count("\n") + 1, "missing # EOF terminator")
    return families


def render_exposition(families: Iterable[Family]) -> str:
    """Re-expose parsed families; with untouched ``raw_*`` tokens the output
    is byte-identical to the parsed input (the round-trip contract)."""
    lines: List[str] = []
    for fam in families:
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for s in fam.samples:
            lines.append(s.render())
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


# -- targets + scraper --------------------------------------------------------


@dataclass(frozen=True)
class Target:
    job: str
    url: str

    @property
    def instance(self) -> str:
        return urlparse(self.url).netloc or self.url


class Scraper:
    """Pull-based collection: static targets + annotated-Pod discovery,
    deduplicated by instance (two Pods advertising one URL — e.g. fleet
    replicas sharing a ModelServer — federate as ONE instance, not a
    double-counted pair)."""

    def __init__(
        self,
        tsdb: TSDB,
        targets: Sequence[Target] = (),
        client=None,
        timeout_s: float = 5.0,
        stale_after: int = 3,
        registry: MetricsRegistry = METRICS,
    ) -> None:
        self.tsdb = tsdb
        self._static = list(targets)
        self._client = client
        self._timeout_s = timeout_s
        self.stale_after = int(stale_after)
        self._registry = registry
        self._misses: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def add_target(self, target: Target) -> None:
        with self._lock:
            self._static.append(target)

    def discover(self) -> List[Target]:
        """Static targets plus every Pod annotated for scraping, first
        writer per instance wins (static list outranks discovery)."""
        with self._lock:
            targets: Dict[str, Target] = {t.instance: t for t in self._static}
        if self._client is not None:
            from ..api.meta import annotations_of, name_of

            try:
                pods = self._client.list("v1", "Pod")
            except Exception:
                log.exception("scrape discovery: Pod list failed")
                pods = []
            for pod in pods:
                ann = annotations_of(pod)
                if ann.get(SCRAPE_ANNOTATION) != "true":
                    continue
                url = ann.get(SCRAPE_URL_ANNOTATION)
                if not url:
                    continue
                t = Target(job=ann.get(SCRAPE_JOB_ANNOTATION) or name_of(pod),
                           url=url)
                targets.setdefault(t.instance, t)
        return list(targets.values())

    def fetch(self, target: Target) -> str:
        with urllib.request.urlopen(target.url, timeout=self._timeout_s) as resp:
            if resp.status != 200:
                raise IOError(f"{target.url}: HTTP {resp.status}")
            return resp.read().decode("utf-8")

    def scrape_once(self, now: Optional[float] = None) -> Dict[str, bool]:
        """One pass over the discovered target set; returns instance → up.
        Every attempt — success or not — lands ``up`` and
        ``scrape_duration_seconds`` in the TSDB so rules can alert on
        absence, not just on badness."""
        now = time.time() if now is None else now
        targets = self.discover()
        self._registry.gauge("monitoring_scrape_targets").set(float(len(targets)))
        results: Dict[str, bool] = {}
        for target in targets:
            results[target.instance] = self._scrape_target(target, now)
        return results

    def _scrape_target(self, target: Target, now: float) -> bool:
        start = time.perf_counter()
        try:
            families = parse_exposition(self.fetch(target))
        except Exception as e:
            duration = time.perf_counter() - start
            misses = self._misses.get(target.instance, 0) + 1
            self._misses[target.instance] = misses
            if misses >= self.stale_after:
                flipped = self.tsdb.mark_stale(instance=target.instance)
                if flipped:
                    log.warning("target %s stale after %d misses (%d series): %s",
                                target.instance, misses, flipped, e)
            self._registry.counter("monitoring_scrapes_total", result="error").inc()
            self._write_target_health(target, up=0.0, duration=duration, now=now)
            return False
        duration = time.perf_counter() - start
        self._misses[target.instance] = 0
        self._ingest(target, families, now)
        self._registry.counter("monitoring_scrapes_total", result="ok").inc()
        self._write_target_health(target, up=1.0, duration=duration, now=now)
        return True

    def _write_target_health(self, target: Target, up: float, duration: float,
                             now: float) -> None:
        labels = {"instance": target.instance, "job": target.job}
        self.tsdb.set_kind("up", "gauge")
        self.tsdb.set_kind("scrape_duration_seconds", "gauge")
        self.tsdb.add_sample("up", labels, now, up)
        self.tsdb.add_sample("scrape_duration_seconds", labels, now, duration)

    def _ingest(self, target: Target, families: List[Family], now: float) -> None:
        for fam in families:
            self.tsdb.set_kind(fam.name, fam.kind, fam.sample_names())
            for s in fam.samples:
                labels = dict(s.labels)
                # honor_labels=false: a scraped series may not impersonate
                # another target — its own instance/job move aside
                for reserved in ("instance", "job"):
                    if reserved in labels:
                        labels[f"exported_{reserved}"] = labels.pop(reserved)
                labels["instance"] = target.instance
                labels["job"] = target.job
                self.tsdb.add_sample(s.name, labels, now, s.value)

    # -- background loop -----------------------------------------------------
    def start(self, interval_s: float) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.is_set():
                try:
                    self.scrape_once()
                except Exception:
                    log.exception("scrape pass failed")
                self._stop.wait(interval_s)

        self._thread = threading.Thread(target=loop, name="monitoring-scraper",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
