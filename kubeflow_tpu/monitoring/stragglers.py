"""Straggler & hang detection over federated worker beacons.

The :class:`~kubeflow_tpu.training.heartbeat.WorkerBeacon` side publishes
per-worker step telemetry; the monitoring plane scrapes it into the TSDB;
this module closes the loop. Each :meth:`StragglerDetector.tick` (driven
by ``MonitoringPlane.tick``) cross-sections the gang:

- **skew** — a worker whose step wall exceeds the gang median by
  ``skew_factor`` in at least ``k`` of the last ``n`` observation windows
  is flagged a persistent straggler (``training_straggler_score{worker}``
  + a ``WorkerStraggling`` Warning Event). Single-worker gangs have no
  peers to skew against and never self-flag.
- **hang** — a worker that previously made progress but has published no
  new step within ``hang_deadline_s`` gets a hang verdict:
  ``training_hangs_detected_total`` bumps, an all-thread stack dump lands
  in the ``/debug/stacks`` ring (the forensic that names the wedged
  frame), the verdict is attached to the gang's federated trace, and
  remediation kicks in — the hosting node is quarantined
  (``scheduling.kubeflow.org/quarantined``; the ChipLedger cordons it)
  and the gang's pods get drain deadlines so ``ElasticTrainer`` reshards
  around the loss.

Both detectors are restart/counter-reset aware: an incarnation bump or a
step index moving backwards resets the worker's skew window AND hang
clock — a fresh incarnation replaying from step 0 is recovery, never a
hang. Quarantine is idempotent under informer echo: an already-annotated
node (or one in the detector's own cordon set) is never re-patched.
"""

from __future__ import annotations

import json
import logging
import statistics
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from ..api import meta as apimeta
from ..runtime.metrics import METRICS
from ..runtime.obs import capture_stacks
from ..runtime.tracing import BIND_TRACEPARENT_ANNOTATION, parse_traceparent
from ..scheduler.gang import (
    DRAIN_DEADLINE_ANNOTATION,
    POD_GROUP_LABEL,
    QUARANTINE_ANNOTATION,
    drain_grace_of,
    gang_of,
    is_quarantined,
    is_terminal,
)
from .rules import RecordingRule, SLOBurnRateAlert

LOG = logging.getLogger(__name__)


class _WorkerState:
    """Per-worker detector bookkeeping (all mutation under the detector's
    single tick, which MonitoringPlane serializes)."""

    __slots__ = (
        "window", "incarnation", "step", "progress_at", "hang_base",
        "flagged", "hung",
    )

    def __init__(self, now: float, window_n: int) -> None:
        self.window: Deque[bool] = deque(maxlen=window_n)
        self.incarnation: Optional[float] = None
        self.step: Optional[float] = None
        self.progress_at = now
        #: hang clock floor — reset on restart so the restore/replay gap of
        #: a new incarnation can never mature into a hang verdict
        self.hang_base = now
        self.flagged = False
        self.hung = False


class StragglerDetector:
    """Cross-sectional straggler/hang detector over the scraped TSDB."""

    def __init__(
        self,
        tsdb: Any,
        *,
        client: Any = None,
        namespace: Optional[str] = "default",
        skew_factor: float = 2.0,
        k: int = 3,
        n: int = 5,
        hang_deadline_s: float = 5.0,
        default_grace_s: float = 5.0,
        traces: Any = None,
        registry: Any = METRICS,
        component: str = "straggler-detector",
    ) -> None:
        self.tsdb = tsdb
        self._client = client
        self._namespace = namespace
        self.skew_factor = float(skew_factor)
        self.k = int(k)
        self.n = int(n)
        self.hang_deadline_s = float(hang_deadline_s)
        self.default_grace_s = float(default_grace_s)
        self.traces = traces
        self.component = component
        self._ns = registry.namespace("training")
        self._lock = threading.Lock()
        #: guarded by _lock: _state, _quarantined, last_hang_verdict
        self._state: Dict[str, _WorkerState] = {}
        self._quarantined: set = set()
        self.last_hang_verdict: Optional[Dict[str, Any]] = None

    # -- the tick ------------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One detection pass. Returns the hang verdicts minted this tick
        (empty on a healthy gang)."""
        now = time.time() if now is None else float(now)
        walls = self._latest("training_worker_step_wall_seconds")
        steps = self._latest("training_worker_step_index")
        incs = self._latest("training_worker_incarnation")
        lasts = self._latest("training_worker_last_step_timestamp_seconds")
        verdicts: List[Dict[str, Any]] = []
        with self._lock:
            workers = sorted(set(steps) | set(walls))
            restarted = set()
            for w in workers:
                if self._observe_locked(w, now, steps.get(w), incs.get(w)):
                    restarted.add(w)
            self._skew_locked(now, walls, workers)
            for w in workers:
                if w in restarted:
                    continue
                v = self._hang_locked(w, now, lasts.get(w))
                if v is not None:
                    verdicts.append(v)
        # remediation outside the lock: it does apiserver I/O (patches,
        # events, pod lists) — never block the detector's state under it
        for v in verdicts:
            self._remediate(v, now)
        return verdicts

    def _latest(self, name: str) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for labels, _ts, value in self.tsdb.latest(name):
            worker = labels.get("worker")
            if worker:
                out[worker] = float(value)
        return out

    def _observe_locked(
        self, w: str, now: float,
        step: Optional[float], inc: Optional[float],
    ) -> bool:
        """Fold one worker's latest counters in; returns True when this tick
        observed a restart (incarnation bump or step index reset)."""
        st = self._state.get(w)
        if st is None:
            st = self._state[w] = _WorkerState(now, self.n)
        restarted = False
        if inc is not None and st.incarnation is not None and inc > st.incarnation:
            restarted = True
        elif (
            step is not None and st.step is not None and step < st.step
        ):
            # counter reset seen before the incarnation gauge federated —
            # same meaning: the worker restarted, it did not hang
            restarted = True
        if restarted:
            st.window.clear()
            st.hang_base = now
            st.progress_at = now
            st.hung = False
            st.flagged = False
        elif step is not None and step != st.step:
            st.progress_at = now
            st.hung = False
        if inc is not None:
            st.incarnation = inc
        if step is not None:
            st.step = step
        return restarted

    def _skew_locked(
        self, now: float, walls: Dict[str, float], workers: List[str]
    ) -> None:
        fresh = {w: walls[w] for w in workers if walls.get(w, 0.0) > 0.0}
        if len(fresh) < 2:
            # a gang of one has no peers to be slower than — never self-flag
            return
        median = statistics.median(fresh.values())
        if median <= 0.0:
            return
        threshold = median * self.skew_factor
        for w, wall in fresh.items():
            st = self._state[w]
            st.window.append(wall > threshold)
            hits = sum(st.window)
            score = hits / float(self.n)
            self._ns.gauge("straggler_score", worker=w).set(score)
            if hits >= self.k and not st.flagged:
                st.flagged = True
                self._ns.counter("stragglers_flagged_total", worker=w).inc()
                LOG.warning(
                    "straggler: worker %s at %.3fs vs gang median %.3fs "
                    "(%d/%d windows above %.1fx)",
                    w, wall, median, hits, self.n, self.skew_factor,
                )
                self._emit_worker_event(
                    w, "WorkerStraggling",
                    f"worker {w} step wall {wall:.3f}s exceeds gang median "
                    f"{median:.3f}s x{self.skew_factor:g} in {hits}/{self.n} windows",
                )
            elif hits < self.k:
                st.flagged = False

    def _hang_locked(
        self, w: str, now: float, last_ts: Optional[float]
    ) -> Optional[Dict[str, Any]]:
        st = self._state[w]
        if st.hung or st.step is None or st.step < 0:
            return None  # never progressed (or already verdicted): not a hang
        floor = max(st.hang_base, st.progress_at, last_ts or 0.0)
        stalled = now - floor
        if stalled <= self.hang_deadline_s:
            return None
        st.hung = True
        self._ns.counter("hangs_detected_total", worker=w).inc()
        dump = capture_stacks(reason=f"hang:{w}")
        verdict = {
            "kind": "hang",
            "worker": w,
            "stepIndex": st.step,
            "incarnation": st.incarnation,
            "stalledSeconds": round(stalled, 3),
            "deadlineSeconds": self.hang_deadline_s,
            "detectedAt": now,
            # the innermost few frames of every thread: deep enough that a
            # worker parked in WorkerBeacon._wedge_wait is named even though
            # its literal innermost frame is the stdlib Event.wait
            "stackThreads": sorted({
                f["function"]
                for t in dump["threads"]
                for f in t["frames"][-3:]
            }),
        }
        self.last_hang_verdict = verdict
        LOG.error(
            "hang: worker %s stalled %.2fs past step %s (deadline %.2fs); "
            "stack dump captured",
            w, stalled, st.step, self.hang_deadline_s,
        )
        return verdict

    # -- remediation (apiserver I/O, outside the lock) -----------------------
    def _remediate(self, verdict: Dict[str, Any], now: float) -> None:
        if self._client is None:
            return
        w = verdict["worker"]
        pod = self._client.get_opt("v1", "Pod", w, self._namespace)
        if pod is None:
            LOG.warning("hang remediation: no pod named %r to act on", w)
            return
        node = (pod.get("spec") or {}).get("nodeName")
        verdict["node"] = node
        verdict["gang"] = gang_of(pod).name
        self._attach_trace_verdict(pod, verdict)
        self._emit_event(
            pod, "WorkerHung",
            f"worker {w} made no step progress for "
            f"{verdict['stalledSeconds']}s (deadline {self.hang_deadline_s}s)",
        )
        if node:
            self._cordon(node, verdict)
        self._drain_gang(pod, now)

    def _attach_trace_verdict(self, pod: Dict[str, Any], verdict: Dict[str, Any]) -> None:
        if self.traces is None:
            return
        raw = apimeta.annotations_of(pod).get(BIND_TRACEPARENT_ANNOTATION)
        parsed = parse_traceparent(raw) if raw else None
        if parsed is not None:
            self.traces.attach_verdict(parsed[0], dict(verdict))

    def _cordon(self, node: str, verdict: Dict[str, Any]) -> None:
        with self._lock:
            if node in self._quarantined:
                return
        nobj = self._client.get_opt("v1", "Node", node, None)
        if nobj is None:
            return
        if is_quarantined(nobj):
            # informer echo / a prior detector instance already cordoned it
            with self._lock:
                self._quarantined.add(node)
            return
        payload = json.dumps({
            "worker": verdict["worker"],
            "reason": "hang",
            "at": verdict["detectedAt"],
        })
        try:
            self._client.patch(
                "v1", "Node", node,
                {"metadata": {"annotations": {QUARANTINE_ANNOTATION: payload}}},
                None,
            )
        except Exception:
            LOG.exception("failed to quarantine node %s", node)
            return
        with self._lock:
            self._quarantined.add(node)
        self._emit_event(
            nobj, "NodeQuarantined",
            f"node {node} quarantined: worker {verdict['worker']} hang verdict",
        )

    def _drain_gang(self, pod: Dict[str, Any], now: float) -> None:
        gang = gang_of(pod)
        if gang.labeled:
            members = self._client.list(
                "v1", "Pod", gang.namespace,
                label_selector={POD_GROUP_LABEL: gang.name},
            )
        else:
            members = [pod]
        for m in members:
            if is_terminal(m):
                continue
            anns = apimeta.annotations_of(m)
            if DRAIN_DEADLINE_ANNOTATION in anns:
                continue  # a drain is already in flight — idempotent
            grace = drain_grace_of(m) or self.default_grace_s
            try:
                self._client.patch(
                    "v1", "Pod", apimeta.name_of(m),
                    {"metadata": {"annotations": {
                        DRAIN_DEADLINE_ANNOTATION: str(now + grace),
                    }}},
                    apimeta.namespace_of(m),
                )
            except Exception:
                LOG.exception("failed to drain pod %s", apimeta.name_of(m))

    def _emit_worker_event(self, worker: str, reason: str, message: str) -> None:
        if self._client is None:
            return
        pod = self._client.get_opt("v1", "Pod", worker, self._namespace)
        if pod is not None:
            self._emit_event(pod, reason, message)

    def _emit_event(self, obj: Dict[str, Any], reason: str, message: str) -> None:
        try:
            self._client.emit_event(
                obj, reason, message, type_="Warning", component=self.component,
            )
        except Exception:
            LOG.exception("failed to emit %s event", reason)

    # -- read side -----------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """The dashboard / ``/debug/stragglers`` view."""
        with self._lock:
            workers = {
                w: {
                    "score": sum(st.window) / float(self.n),
                    "flagged": st.flagged,
                    "hung": st.hung,
                    "stepIndex": st.step,
                    "incarnation": st.incarnation,
                    "lastProgressAt": st.progress_at,
                }
                for w, st in self._state.items()
            }
            return {
                "workers": workers,
                "quarantined": sorted(self._quarantined),
                "lastHangVerdict": (
                    dict(self.last_hang_verdict)
                    if self.last_hang_verdict else None
                ),
                "config": {
                    "skewFactor": self.skew_factor,
                    "k": self.k,
                    "n": self.n,
                    "hangDeadlineSeconds": self.hang_deadline_s,
                },
            }


def straggler_rules(
    *, step_slo_s: float = 1.0, objective: float = 0.99
) -> List[Any]:
    """The straggler plane's rule-engine bundle: a recording rule tracking
    the gang's max/median step-wall skew ratio, plus an SRE-workbook SLO
    burn-rate alert on per-worker step latency."""

    def _skew(tsdb: Any, now: float):
        rows = tsdb.latest("training_worker_step_wall_seconds")
        vals = [float(v) for _labels, _ts, v in rows if float(v) > 0.0]
        if len(vals) < 2:
            return []
        median = statistics.median(vals)
        if median <= 0.0:
            return []
        return [({}, max(vals) / median)]

    return [
        RecordingRule("platform:training_worker_step_skew", _skew),
        SLOBurnRateAlert(
            "TrainingWorkerStepLatency",
            "training_worker_step_seconds",
            step_slo_s,
            objective=objective,
        ),
    ]
