"""MonitoringPlane: scraper + TSDB + rule engine as one mountable unit.

``tick()`` is the deterministic unit (scrape pass, then rule evaluation —
tests and the e2e driver drive it directly); ``start(interval)`` runs it on
a timer thread for real deployments. ``mount(app)`` serves the aggregate:

- ``GET /federate``     — latest fresh value of every federated series,
  re-exposed in the same OpenMetrics dialect the scraper parses (so a
  higher-level collector, or our own parser in tests, can consume it),
- ``GET /debug/alerts`` — the rule engine's live alert table, via the
  process-global ``obs.register_debug_source`` registry.

``install_cluster_collector`` publishes per-node TPU capacity/allocation
gauges from the apiserver into a *registry* (scraped like any process
metric), which is how the dashboard's node-utilization endpoint gets
federated data instead of re-deriving pod math per poll.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Sequence

from ..runtime.metrics import METRICS, MetricsRegistry
from ..web.http import App, JsonResponse, Request
from .rules import RuleEngine
from .scrape import Scraper, Target, _format_value
from .traces import TraceCollector
from .tsdb import TSDB

log = logging.getLogger("kubeflow_tpu.monitoring")


class MonitoringPlane:
    def __init__(
        self,
        client=None,
        targets: Sequence[Target] = (),
        tsdb: Optional[TSDB] = None,
        scraper: Optional[Scraper] = None,
        rules: Optional[RuleEngine] = None,
        registry: MetricsRegistry = METRICS,
        stale_after: int = 3,
        timeout_s: float = 5.0,
        traces: Optional[TraceCollector] = None,
        stragglers=None,
    ) -> None:
        self.tsdb = tsdb if tsdb is not None else TSDB()
        self.scraper = scraper if scraper is not None else Scraper(
            self.tsdb, targets=targets, client=client,
            stale_after=stale_after, timeout_s=timeout_s, registry=registry,
        )
        self.rules = rules if rules is not None else RuleEngine(
            self.tsdb, client=client, registry=registry,
        )
        # trace federation rides the same discovery + cadence as metrics;
        # optional because not every plane consumer wants the span store
        self.traces = traces
        # straggler/hang detection cross-sections the freshly scraped TSDB
        # on the same cadence (monitoring/stragglers.py); optional likewise
        self.stragglers = stragglers
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """One scrape pass then one rule evaluation; returns alert statuses."""
        now = time.time() if now is None else now
        self.scraper.scrape_once(now)
        if self.traces is not None:
            self.traces.collect_once()
        if self.stragglers is not None:
            self.stragglers.tick(now)
        return self.rules.evaluate(now)

    def start(self, interval_s: float = 5.0) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.is_set():
                try:
                    self.tick()
                except Exception:
                    log.exception("monitoring tick failed")
                self._stop.wait(interval_s)

        self._thread = threading.Thread(target=loop, name="monitoring-plane",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # -- federation ----------------------------------------------------------
    def federate_text(self) -> str:
        """Latest fresh value per federated series, grouped by family in the
        scraper's own dialect — ``parse_exposition(federate_text())`` always
        succeeds (asserted in tests), closing the compliance loop."""
        by_family: Dict[str, List[str]] = {}
        for name in self.tsdb.names():
            by_family.setdefault(self.tsdb.family_of(name), []).append(name)
        lines: List[str] = []
        for family in sorted(by_family):
            kind = self.tsdb.kind(family) or "untyped"
            names = by_family[family]
            if kind == "histogram":
                order = {f"{family}_bucket": 0, f"{family}_sum": 1,
                         f"{family}_count": 2}
                names = sorted(names, key=lambda n: order.get(n, 3))
            sample_lines: List[str] = []
            for name in names:
                for labels, _ts, value in sorted(
                    self.tsdb.latest(name), key=lambda e: sorted(e[0].items())
                ):
                    label_str = ",".join(
                        f'{k}="{v}"' for k, v in sorted(labels.items())
                    )
                    suffix = f"{{{label_str}}}" if label_str else ""
                    sample_lines.append(f"{name}{suffix} {_format_value(value)}")
            if not sample_lines:
                continue
            lines.append(f"# TYPE {family} {kind}")
            lines.extend(sample_lines)
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def mount(self, app: App) -> App:
        from ..runtime.obs import EXPOSITION_CONTENT_TYPE, register_debug_source

        register_debug_source("alerts", lambda req: self.rules.snapshot())
        if self.stragglers is not None:
            register_debug_source(
                "stragglers", lambda req: self.stragglers.snapshot())
        if self.traces is not None:
            self.traces.mount(app)
        if any(pattern == "/federate" for _m, pattern, _fn in app.iter_routes()):
            return app

        @app.route("/federate")
        def federate(req: Request) -> JsonResponse:
            return JsonResponse(
                self.federate_text(),
                headers={"Content-Type": EXPOSITION_CONTENT_TYPE},
            )

        return app


def install_cluster_collector(client, registry: MetricsRegistry = METRICS) -> None:
    """Publish per-node TPU chip capacity/allocation as gauges on
    ``registry`` at every scrape — the same math the dashboard used to do
    per poll from raw Pods, now computed once in whichever process runs the
    collector and federated to every consumer."""
    from ..api import meta as apimeta
    from ..tpu.topology import RESOURCE_TPU, pod_tpu_chips

    def collect() -> None:
        try:
            nodes = client.list("v1", "Node")
            pods = client.list("v1", "Pod")
        except Exception:
            log.exception("cluster collector: list failed")
            return
        for node in nodes:
            name = apimeta.name_of(node)
            capacity = int(
                (node.get("status", {}).get("capacity") or {}).get(RESOURCE_TPU, 0)
            )
            if capacity <= 0:
                continue
            used = sum(
                pod_tpu_chips(p) for p in pods
                if p.get("spec", {}).get("nodeName") == name
            )
            registry.gauge("node_tpu_capacity_chips", node=name).set(float(capacity))
            registry.gauge("node_tpu_allocated_chips", node=name).set(float(used))

    registry.register_collector("cluster-tpu", collect)
