"""Platform monitoring plane: a mini-Prometheus for the control plane.

Nine PRs of instrumentation gave every process a rich ``/metrics`` page and
nothing that could read one. This package closes the loop:

- ``scrape``  — strict OpenMetrics parser for our own exposition plus a
  ``Scraper`` that pulls ``/metrics`` from a target set (static list + live
  discovery of annotated Pods through the apiserver) and writes samples,
  ``up`` and ``scrape_duration_seconds`` into the TSDB,
- ``tsdb``    — bounded in-memory time-series store (per-series ring
  buffers, label matchers) with ``rate()``, ``increase()`` and windowed
  ``histogram_quantile()`` — enough query power for rules, no more,
- ``rules``   — recording rules and multi-window multi-burn-rate SLO
  alerts (SRE-workbook 5m/1h + 30m/6h pairs) with a pending→firing→resolved
  lifecycle, emitted as deduplicated K8s Warning Events,
- ``plane``   — ``MonitoringPlane`` composing the three, serving
  ``/federate`` and ``/debug/alerts``,
- ``stragglers`` — cross-sectional straggler/hang detection over the
  federated worker beacons (``training/heartbeat.py``), with stack-dump
  forensics and quarantine-driven remediation, at ``/debug/stragglers``,
- ``goodput`` — the accounting layer over all of it: wallclock-reconciled
  goodput/badput decomposition per training workload, per-tenant chip and
  token metering, and the serving token-goodput view, at
  ``GET /debug/goodput``.
"""

from .tsdb import TSDB, Matchers  # noqa: F401
from .scrape import (  # noqa: F401
    ParseError,
    Sample,
    Family,
    parse_exposition,
    render_exposition,
    Scraper,
    Target,
    SCRAPE_ANNOTATION,
    SCRAPE_URL_ANNOTATION,
    SCRAPE_JOB_ANNOTATION,
)
from .rules import (  # noqa: F401
    BurnRateWindow,
    DEFAULT_BURN_RATE_WINDOWS,
    RecordingRule,
    RuleEngine,
    SLOBurnRateAlert,
)
from .traces import TraceCollector, critical_path, traces_url  # noqa: F401
from .stragglers import StragglerDetector, straggler_rules  # noqa: F401
from .plane import MonitoringPlane, install_cluster_collector  # noqa: F401
from .goodput import (  # noqa: F401
    BADPUT_BUCKETS,
    GoodputLedger,
    TENANT_METER,
    TenantChipMeter,
    get_ledger,
    goodput_recording_rules,
    serving_goodput_view,
)
