"""Bounded in-memory time-series store for the monitoring plane.

One series = one (metric name, label set); points live in a fixed-size ring
buffer so a long-running monitor holds a sliding window, never the whole
history. Query power is deliberately small — exact/regex label matchers,
``latest``, ``increase``/``rate`` with counter-reset handling, and a
windowed ``histogram_quantile`` over ``<name>_bucket`` series — because the
rule engine and the federated autoscaler source need exactly that and
nothing else.

Staleness is explicit rather than timestamp-heuristic: the scraper marks a
target's series stale after N missed scrapes, and every read path skips
stale series unless asked not to. That is what lets consumers distinguish
"the fleet is idle" from "we stopped seeing the fleet" (the autoscaler
no-flap requirement).
"""

from __future__ import annotations

import re
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, Iterable, List, Optional, Tuple, Union

from ..runtime.metrics import quantile_from_counts

#: label matcher values: exact string or compiled regex (fullmatch semantics)
Matchers = Dict[str, Union[str, re.Pattern]]


@dataclass
class Series:
    name: str
    labels: Dict[str, str]
    points: Deque[Tuple[float, float]] = field(default_factory=deque)  # (ts, value)
    stale: bool = False

    @property
    def last_ts(self) -> float:
        return self.points[-1][0] if self.points else 0.0


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


def _matches(labels: Dict[str, str], matchers: Optional[Matchers]) -> bool:
    if not matchers:
        return True
    for k, want in matchers.items():
        got = labels.get(k)
        if got is None:
            return False
        if isinstance(want, str):
            if got != want:
                return False
        elif not want.fullmatch(got):
            return False
    return True


class TSDB:
    """Thread-safe store of append-only series with per-series ring buffers.

    ``max_points`` bounds each series' ring; ``max_series`` bounds the store
    — when a new series would exceed it, the series with the oldest last
    write is evicted (a scrape-churn guard, not an LRU cache)."""

    def __init__(self, max_points: int = 512, max_series: int = 8192) -> None:
        self.max_points = int(max_points)
        self.max_series = int(max_series)
        self._lock = threading.Lock()
        #: name → label-key → Series
        self._series: Dict[str, Dict[Tuple[Tuple[str, str], ...], Series]] = {}
        #: family kinds (counter/gauge/histogram/untyped), keyed by family name
        self._kinds: Dict[str, str] = {}
        #: sample name → family name (histogram _bucket/_sum/_count fold back)
        self._families: Dict[str, str] = {}
        self._count = 0

    # -- writes --------------------------------------------------------------
    def set_kind(self, family: str, kind: str,
                 sample_names: Iterable[str] = ()) -> None:
        with self._lock:
            self._kinds[family] = kind
            self._families[family] = family
            for s in sample_names:
                self._families[s] = family

    def kind(self, family: str) -> Optional[str]:
        with self._lock:
            return self._kinds.get(family)

    def family_of(self, sample_name: str) -> str:
        with self._lock:
            return self._families.get(sample_name, sample_name)

    def add_sample(self, name: str, labels: Dict[str, str], ts: float,
                   value: float) -> None:
        """Append one point; a fresh write always clears the series' stale
        flag (recovery is implicit — seeing data again IS the signal)."""
        key = _label_key(labels)
        with self._lock:
            by_key = self._series.setdefault(name, {})
            s = by_key.get(key)
            if s is None:
                if self._count >= self.max_series:
                    self._evict_oldest_locked()
                s = Series(name=name, labels=dict(labels),
                           points=deque(maxlen=self.max_points))
                by_key[key] = s
                self._count += 1
            s.points.append((float(ts), float(value)))
            s.stale = False

    def _evict_oldest_locked(self) -> None:
        oldest: Optional[Tuple[str, Tuple[Tuple[str, str], ...]]] = None
        oldest_ts = float("inf")
        for name, by_key in self._series.items():
            for key, s in by_key.items():
                if s.last_ts < oldest_ts:
                    oldest_ts = s.last_ts
                    oldest = (name, key)
        if oldest is not None:
            del self._series[oldest[0]][oldest[1]]
            if not self._series[oldest[0]]:
                del self._series[oldest[0]]
            self._count -= 1

    def mark_stale(self, **labels: str) -> int:
        """Flag every series whose labels match (exactly, on the given keys)
        as stale; returns how many flipped. The scraper calls this with
        ``instance=...`` when a target exceeds its missed-scrape budget."""
        flipped = 0
        with self._lock:
            for by_key in self._series.values():
                for s in by_key.values():
                    if not s.stale and _matches(s.labels, labels):
                        s.stale = True
                        flipped += 1
        return flipped

    # -- reads ---------------------------------------------------------------
    def series(self, name: str, matchers: Optional[Matchers] = None,
               include_stale: bool = False) -> List[Series]:
        with self._lock:
            out = []
            for s in self._series.get(name, {}).values():
                if s.stale and not include_stale:
                    continue
                if _matches(s.labels, matchers):
                    out.append(s)
            return out

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def latest(self, name: str, matchers: Optional[Matchers] = None,
               include_stale: bool = False) -> List[Tuple[Dict[str, str], float, float]]:
        """Newest ``(labels, ts, value)`` per matching series."""
        return [
            (dict(s.labels), *s.points[-1])
            for s in self.series(name, matchers, include_stale)
            if s.points
        ]

    def newest_ts(self, name: str, matchers: Optional[Matchers] = None,
                  include_stale: bool = False) -> Optional[float]:
        stamps = [ts for _l, ts, _v in self.latest(name, matchers, include_stale)]
        return max(stamps) if stamps else None

    def increase(self, name: str, window_s: float, now: float,
                 matchers: Optional[Matchers] = None) -> float:
        """PromQL-style ``increase()``: per-series sum of positive deltas
        between consecutive points inside the window, summed across series.
        A drop between points is a counter reset — the post-reset value IS
        the increase since the reset, matching Prometheus semantics."""
        lo = now - window_s
        total = 0.0
        for s in self.series(name, matchers):
            prev: Optional[float] = None
            for ts, value in s.points:
                if ts < lo or ts > now:
                    prev = value if ts < lo else prev
                    continue
                if prev is not None:
                    total += value - prev if value >= prev else value
                prev = value
        return total

    def rate(self, name: str, window_s: float, now: float,
             matchers: Optional[Matchers] = None) -> float:
        return self.increase(name, window_s, now, matchers) / window_s if window_s > 0 else 0.0

    def windowed_bucket_counts(
        self, name: str, window_s: float, now: float,
        matchers: Optional[Matchers] = None,
    ) -> Optional[Tuple[Tuple[float, ...], List[int], int]]:
        """``(buckets, counts, total)`` of a histogram family's increase over
        the window, aggregated across every matching ``<name>_bucket``
        series. Cumulative ``le`` counts are de-cumulated into the per-bucket
        vector ``quantile_from_counts`` expects. None when no fresh series
        carried any increase (no data ≠ zero latency)."""
        per_le: Dict[float, float] = {}
        lo = now - window_s
        for s in self.series(f"{name}_bucket", matchers):
            le_raw = s.labels.get("le")
            if le_raw is None:
                continue
            le = float("inf") if le_raw in ("+Inf", "inf") else float(le_raw)
            last: Optional[float] = None
            prev: Optional[float] = None
            inc = 0.0
            for ts, value in s.points:
                if ts < lo:
                    prev = value
                    continue
                if ts > now:
                    break
                if prev is not None:
                    inc += value - prev if value >= prev else value
                prev = value
                last = value
            if last is None:
                continue
            per_le[le] = per_le.get(le, 0.0) + inc
        if not per_le or float("inf") not in per_le:
            return None
        finite = sorted(b for b in per_le if b != float("inf"))
        total = per_le[float("inf")]
        counts: List[int] = []
        prev_cum = 0.0
        for b in finite:
            counts.append(int(round(per_le[b] - prev_cum)))
            prev_cum = per_le[b]
        counts.append(int(round(total - prev_cum)))
        total_i = int(round(total))
        if total_i <= 0:
            return None
        return tuple(finite), counts, total_i

    def histogram_quantile(
        self, name: str, q: float, window_s: float, now: float,
        matchers: Optional[Matchers] = None,
    ) -> Optional[float]:
        """Windowed PromQL ``histogram_quantile(q, rate(<name>_bucket[w]))``
        across matching instances. None when the window holds no data."""
        snap = self.windowed_bucket_counts(name, window_s, now, matchers)
        if snap is None:
            return None
        buckets, counts, total = snap
        return quantile_from_counts(buckets, counts, total, q)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "series": self._count,
                "names": len(self._series),
                "stale": sum(
                    1 for by_key in self._series.values()
                    for s in by_key.values() if s.stale
                ),
            }
