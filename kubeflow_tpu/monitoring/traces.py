"""Trace federation: pull per-process span buffers into cross-process traces.

Each role serves its own Tracer ring at ``/debug/traces`` (runtime/obs.py)
— useful for one process, useless for a gang bind whose spans live in the
loadgen client, the apiserver, the scheduler, and a podlet.  The
``TraceCollector`` closes that gap the same way the metrics Scraper does:
it discovers every annotated Pod (the ``monitoring.kubeflow.org/scrape``
idiom, URL rewritten ``/metrics`` → ``/debug/traces``) plus a static target
list, pulls each process's OTLP-shaped buffer, and assembles spans by
``traceId`` — deduplicated by ``spanId``, stamped with the emitting
process's resource identity (``service.name`` / ``service.instance.id``).

The store is bounded with **tail sampling**: when the span budget is
exceeded, traces that are *interesting* — any span errored, or the trace is
in the slowest decile of gang binds — are protected and boring traces are
dropped oldest-first.  ``tracing_collector_traces_dropped_total`` counts
what tail sampling threw away, ``tracing_collector_spans`` gauges the live
store, ``tracing_collector_fetches_total`` tracks pull health.

``critical_path()`` decomposes an assembled gang-bind trace into the
segments operators actually page on — queue (submit → first reconcile),
cycle (reconcile → bind start), bind (the bind write loop) — and checks
they reconstruct the ``scheduler_bind_latency_seconds`` observation the
scheduler recorded on the root span.  ``pod.start`` time is reported
separately: it happens after the bind SLI stops ticking.
"""

from __future__ import annotations

import logging
import threading
import urllib.request
from typing import Any, Dict, List, Optional, Sequence
from urllib.parse import urlsplit, urlunsplit

from ..runtime.metrics import METRICS, MetricsRegistry
from ..web.http import App, HttpError, Request
from .scrape import (
    SCRAPE_ANNOTATION,
    SCRAPE_JOB_ANNOTATION,
    SCRAPE_URL_ANNOTATION,
    Target,
)

log = logging.getLogger("kubeflow_tpu.monitoring")

#: default span budget for the federated store (tail sampling enforces it)
MAX_FEDERATED_SPANS = 20_000


def traces_url(url: str) -> str:
    """The trace endpoint co-served with a scrape URL: same host/port, path
    ``/debug/traces`` (every app that mounts observability serves both)."""
    parts = urlsplit(url)
    return urlunsplit((parts.scheme, parts.netloc, "/debug/traces",
                       "limit=4096", ""))


def _resource_attrs(resource: dict) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for kv in resource.get("attributes", []):
        value = kv.get("value", {})
        out[kv.get("key", "")] = str(value.get("stringValue", ""))
    return out


class TraceCollector:
    """Scraper-shaped federation for spans: static targets + annotated-Pod
    discovery, one bounded tail-sampled store, assembly by trace id."""

    def __init__(
        self,
        targets: Sequence[Target] = (),
        client=None,
        timeout_s: float = 5.0,
        max_spans: int = MAX_FEDERATED_SPANS,
        registry: MetricsRegistry = METRICS,
    ) -> None:
        self._static = list(targets)
        self._client = client
        self._timeout_s = timeout_s
        self.max_spans = int(max_spans)
        self._registry = registry
        # trace_id -> span_id -> span dict (augmented with resource identity)
        self._traces: Dict[str, Dict[str, dict]] = {}
        # trace_id -> monotonic counter of last update (oldest-first drops)
        self._seen_at: Dict[str, int] = {}
        # trace_id -> detector verdicts attached out-of-band (hang forensics)
        self._verdicts: Dict[str, List[dict]] = {}
        self._clock = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- discovery / fetch ---------------------------------------------------
    def add_target(self, target: Target) -> None:
        with self._lock:
            self._static.append(target)

    def discover(self) -> List[Target]:
        """Same target universe as the metrics Scraper — every process worth
        scraping is worth tracing — with the URL pointed at its trace
        buffer instead of its exposition."""
        with self._lock:
            targets: Dict[str, Target] = {t.instance: t for t in self._static}
        if self._client is not None:
            from ..api.meta import annotations_of, name_of

            try:
                pods = self._client.list("v1", "Pod")
            except Exception:
                log.exception("trace discovery: Pod list failed")
                pods = []
            for pod in pods:
                ann = annotations_of(pod)
                if ann.get(SCRAPE_ANNOTATION) != "true":
                    continue
                url = ann.get(SCRAPE_URL_ANNOTATION)
                if not url:
                    continue
                t = Target(job=ann.get(SCRAPE_JOB_ANNOTATION) or name_of(pod),
                           url=traces_url(url))
                targets.setdefault(t.instance, t)
        return list(targets.values())

    def fetch(self, target: Target) -> dict:
        import json

        with urllib.request.urlopen(target.url, timeout=self._timeout_s) as resp:
            if resp.status != 200:
                raise IOError(f"{target.url}: HTTP {resp.status}")
            return json.loads(resp.read().decode("utf-8"))

    def collect_once(self) -> Dict[str, bool]:
        """One federation pass over the discovered targets; instance → ok."""
        results: Dict[str, bool] = {}
        for target in self.discover():
            try:
                doc = self.fetch(target)
                self.ingest(doc, job=target.job)
            except Exception as e:
                log.warning("trace fetch %s failed: %s", target.instance, e)
                self._registry.counter("tracing_collector_fetches_total",
                                       result="error").inc()
                results[target.instance] = False
                continue
            self._registry.counter("tracing_collector_fetches_total",
                                   result="ok").inc()
            results[target.instance] = True
        self._enforce_bound()
        return results

    def ingest(self, doc: dict, job: str = "") -> int:
        """Merge one OTLP resourceSpans document into the store; spans are
        deduplicated by spanId (repeated pulls of an unchanged ring are
        idempotent) and stamped with the emitting process's resource
        identity so the assembled view says where each hop ran."""
        added = 0
        with self._lock:
            for rs in doc.get("resourceSpans", []):
                res = _resource_attrs(rs.get("resource", {}))
                service = res.get("service.name", job or "unknown")
                instance = res.get("service.instance.id", "")
                for scope in rs.get("scopeSpans", []):
                    for span in scope.get("spans", []):
                        tid, sid = span.get("traceId"), span.get("spanId")
                        if not tid or not sid:
                            continue
                        merged = dict(span)
                        # span-level service.name (set per-span by the
                        # Tracer) outranks the process resource: a fleet
                        # replica's engine spans keep the engine identity
                        merged.setdefault("attributes", {})
                        merged["service"] = merged["attributes"].get(
                            "service.name", service)
                        merged["instance"] = instance
                        if sid not in self._traces.setdefault(tid, {}):
                            added += 1
                        self._traces[tid][sid] = merged
                        self._clock += 1
                        self._seen_at[tid] = self._clock
            self._registry.gauge("tracing_collector_spans").set(
                float(sum(len(v) for v in self._traces.values())))
        return added

    # -- tail sampling -------------------------------------------------------
    def _interesting(self) -> set:
        """Trace ids tail sampling must keep: every trace with an errored
        span, plus the slowest decile of gang binds (callers hold _lock)."""
        keep = set()
        bind_latency: Dict[str, float] = {}
        for tid, spans in self._traces.items():
            for s in spans.values():
                if (s.get("status") or {}).get("code") == "ERROR":
                    keep.add(tid)
                if s.get("name") == "gang.lifecycle":
                    lat = s.get("attributes", {}).get("gang.bind_latency_s")
                    if isinstance(lat, (int, float)):
                        bind_latency[tid] = float(lat)
        if bind_latency:
            ranked = sorted(bind_latency, key=bind_latency.get)
            decile = max(1, len(ranked) // 10)
            keep.update(ranked[-decile:])
        return keep

    def _enforce_bound(self) -> int:
        """Drop whole traces, boring and oldest first, until the span budget
        holds.  Protected traces go last — but they DO go if the budget
        demands it: a bounded store is the invariant, sampling the policy."""
        dropped = 0
        with self._lock:
            total = sum(len(v) for v in self._traces.values())
            if total <= self.max_spans:
                return 0
            keep = self._interesting()
            by_age = sorted(self._traces, key=lambda t: self._seen_at.get(t, 0))
            for protected in (False, True):
                for tid in by_age:
                    if total <= self.max_spans:
                        break
                    if tid not in self._traces or (tid in keep) != protected:
                        continue
                    total -= len(self._traces.pop(tid))
                    self._seen_at.pop(tid, None)
                    self._verdicts.pop(tid, None)
                    dropped += 1
                    self._registry.counter(
                        "tracing_collector_traces_dropped_total",
                        protected=str(protected).lower()).inc()
            self._registry.gauge("tracing_collector_spans").set(float(total))
        return dropped

    # -- out-of-band verdicts ------------------------------------------------
    def attach_verdict(self, trace_id: str, verdict: dict) -> None:
        """Attach a detector verdict (a hang/straggler forensic record) to a
        federated trace. Verdicts are not spans — they arrive from the
        monitoring plane, not a scraped ring — but they ride the assembled
        ``trace()`` view so the gang's trace tells the whole story. Verdicts
        for traces the tail sampler has dropped (or never saw) are held
        until the trace shows up or the store drops it."""
        with self._lock:
            self._verdicts.setdefault(trace_id, []).append(dict(verdict))

    # -- assembled views -----------------------------------------------------
    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def trace(self, trace_id: str) -> Optional[dict]:
        """One assembled cross-process trace: spans time-ordered, with the
        set of services that contributed (≥3 for a full gang-bind journey:
        client, apiserver, scheduler)."""
        with self._lock:
            spans = list(self._traces.get(trace_id, {}).values())
            verdicts = [dict(v) for v in self._verdicts.get(trace_id, ())]
        if not spans:
            return None
        spans.sort(key=lambda s: s.get("startTimeUnixNano", 0))
        ends = [s.get("endTimeUnixNano", 0) for s in spans]
        out = {
            "traceId": trace_id,
            "spans": spans,
            "services": sorted({s.get("service", "unknown") for s in spans}),
            "spanCount": len(spans),
            "durationMs": round(
                (max(ends) - spans[0].get("startTimeUnixNano", 0)) / 1e6, 3),
        }
        if verdicts:
            out["verdicts"] = verdicts
        return out

    def slowest_binds(self, n: int = 10) -> List[dict]:
        """Gang-bind traces ranked by the scheduler's recorded bind latency
        — the index an operator opens before asking for any trace by id."""
        rows: List[dict] = []
        with self._lock:
            for tid, spans in self._traces.items():
                for s in spans.values():
                    if s.get("name") != "gang.lifecycle":
                        continue
                    attrs = s.get("attributes", {})
                    lat = attrs.get("gang.bind_latency_s")
                    if not isinstance(lat, (int, float)):
                        continue
                    rows.append({
                        "traceId": tid,
                        "gang": attrs.get("gang", ""),
                        "bindLatencySeconds": float(lat),
                        "bound": bool(attrs.get("gang.bound", False)),
                    })
        rows.sort(key=lambda r: r["bindLatencySeconds"], reverse=True)
        return rows[:max(0, n)]

    # -- serving / loop ------------------------------------------------------
    def mount(self, app: App) -> App:
        """``GET /debug/trace/<trace_id>`` (assembled, with critical path
        when it is a gang bind) + the slowest-binds index.  Safe alongside
        obs's ``/debug/<source>`` catch-all: that pattern is single-segment,
        so the two-segment route here never collides."""
        from ..runtime.obs import register_debug_source

        register_debug_source(
            "slowest-binds",
            lambda req: {"binds": self.slowest_binds(
                int(req.query1("n", "10") or 10))})
        if any(pattern == "/debug/trace/<trace_id>"
               for _m, pattern, _fn in app.iter_routes()):
            return app

        @app.route("/debug/trace/<trace_id>")
        def debug_trace(req: Request) -> dict:
            assembled = self.trace(req.params["trace_id"])
            if assembled is None:
                raise HttpError(404, f"unknown trace {req.params['trace_id']!r}")
            path = critical_path(assembled)
            if path is not None:
                assembled["criticalPath"] = path
            return assembled

        return app

    def start(self, interval_s: float = 5.0) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.is_set():
                try:
                    self.collect_once()
                except Exception:
                    log.exception("trace federation pass failed")
                self._stop.wait(interval_s)

        self._thread = threading.Thread(target=loop, name="trace-collector",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# -- critical-path attribution ------------------------------------------------

def critical_path(assembled: dict) -> Optional[dict]:
    """Decompose an assembled gang-bind trace into the segments that sum to
    ``scheduler_bind_latency_seconds``:

    - ``queue``  — client submit (the root's ``gang.submitted_unix`` anchor,
      the same creationTimestamp epoch the SLI measures from) to the first
      reconcile of the gang (the ``gang.lifecycle`` root opening),
    - ``cycle``  — reconcile start to the successful bind loop opening
      (scheduling cycles, quota checks, preemption attempts),
    - ``bind``   — the ``schedule.bind`` write loop itself.

    The sum is checked against ``gang.bind_latency_s`` (the observation the
    scheduler actually recorded) — ``reconstructionError`` is the gap, and
    honest: if the segments don't explain the SLI, the trace says so.
    ``pod.start`` runs after the bind SLI stops ticking, so it is reported
    as ``postBindPodStart``, not a segment."""
    spans = assembled.get("spans", [])
    roots = [s for s in spans if s.get("name") == "gang.lifecycle"]
    if not roots:
        return None
    root = min(roots, key=lambda s: s.get("startTimeUnixNano", 0))
    attrs = root.get("attributes", {})
    submitted = attrs.get("gang.submitted_unix")
    measured = attrs.get("gang.bind_latency_s")
    if not isinstance(submitted, (int, float)):
        return None
    root_start_s = root.get("startTimeUnixNano", 0) / 1e9
    binds = [s for s in spans if s.get("name") == "schedule.bind"
             and s.get("traceId") == root.get("traceId")]
    segments: List[dict] = []
    segments.append({"name": "queue",
                     "seconds": max(0.0, root_start_s - float(submitted))})
    if binds:
        bind = max(binds, key=lambda s: s.get("endTimeUnixNano", 0))
        bind_start_s = bind.get("startTimeUnixNano", 0) / 1e9
        bind_end_s = bind.get("endTimeUnixNano", 0) / 1e9
        segments.append({"name": "cycle",
                         "seconds": max(0.0, bind_start_s - root_start_s)})
        segments.append({"name": "bind",
                         "seconds": max(0.0, bind_end_s - bind_start_s)})
    total = sum(seg["seconds"] for seg in segments)
    out: Dict[str, Any] = {
        "gang": attrs.get("gang", ""),
        "segments": [{"name": s["name"], "seconds": round(s["seconds"], 6)}
                     for s in segments],
        "totalSeconds": round(total, 6),
    }
    if isinstance(measured, (int, float)):
        out["measuredBindLatencySeconds"] = float(measured)
        out["reconstructionError"] = round(abs(total - float(measured)), 6)
    starts = [s for s in spans if s.get("name") == "pod.start"]
    if starts:
        out["postBindPodStart"] = {
            "pods": len(starts),
            "seconds": round(max(
                (s.get("endTimeUnixNano", 0) - s.get("startTimeUnixNano", 0))
                for s in starts) / 1e9, 6),
        }
    return out
