"""Fused blockwise attention (FlashAttention-2 style) as a Pallas TPU kernel.

Replaces the materialized [lq, lk] score matrix with an online-softmax over
k/v blocks streamed through VMEM: O(block_q x block_k) live scores, f32
accumulators, bf16-friendly inputs, MXU-shaped (128-lane) tiles. Forward and
backward are both Pallas kernels wired through ``jax.custom_vjp`` with the
log-sum-exp residual, so training steps never allocate the full score
matrix either.

``q_offset``/``k_offset`` shift the *global* positions used for causal
masking, which is exactly what ring attention needs: each ring step holds a
k/v block from another device and masks by that block's global position
(parallel/ring_attention.py). Grid iteration on TPU is sequential over the
minor-most grid dim, so accumulators live in VMEM scratch across k-block
steps (the canonical Pallas accumulation pattern).

The reference has no kernels of any kind (SURVEY.md §2.9: its only compiled
code is five Go control-plane binaries); this module is part of the
in-workload compute path the TPU-native build adds.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30
_LANE = 128


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _auto_block(length: int, cap: int) -> int:
    """Largest 128-aligned divisor of ``length`` up to ``cap`` (whole length
    when it is shorter than a lane tile). Lengths with no 128-aligned
    divisor fall back to the largest 8-aligned divisor >= 64 (Mosaic
    sublane tiling), and failing that to the whole length as ONE block —
    which ``flash_attention`` then rejects on the TPU path when it is not
    8-aligned (clear error instead of an opaque Mosaic failure)."""
    if length <= 128:
        return length
    best = 0
    d = 128
    while d <= min(cap, length):
        if length % d == 0:
            best = d
        d += 128
    if best:
        return best
    # No 128-aligned divisor: largest 8-aligned divisor, floored at 64 — a
    # tiny block would explode the grid (lq/bq × lk/bk steps), and Mosaic
    # rejects block shapes whose sublane dim isn't a multiple of 8, so
    # non-8-aligned divisors would only fail later with an opaque compile
    # error (ADVICE r3). Below the floor, run the whole length as ONE
    # block: always a divisor, grid of 1, just more VMEM (the caller
    # rejects it on the TPU path if it isn't 8-aligned).
    for d in range(min(cap, length) & ~7, 63, -8):
        if length % d == 0:
            return d
    return length


def _auto_tile_cap() -> int:
    # The 1024 cap budgets ~4 MiB of f32 scores plus accumulators/iotas
    # against the ~128 MiB VMEM of v4/v5/v6-class chips; v2/v3 (~16 MiB)
    # get a 256 cap so the auto default stays within what the old 128x128
    # tiles compiled under (ADVICE r3: the big cap was a silent portability
    # regression for earlier generations).
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return 1024
    return 256 if ("v2" in kind or "v3" in kind) else 1024


def _block_sizes(lq: int, lk: int, block_q: Optional[int], block_k: Optional[int]) -> Tuple[int, int]:
    # Auto-tiling: measured on v5e at GPT shapes (b8 h16 L1024 d64,
    # fwd+bwd), (block_q, block_k) = (128,128) sustains 8.1 TF/s, (512,1024)
    # 22.8, (1024,1024) 23.7 — bigger tiles amortize the softmax VPU work
    # against MXU dots and cut grid-step overhead ~3x (GPT-2-medium step:
    # 20.9% -> 41.2% MFU). Scores VMEM is bq*bk*4B = 4 MiB at the caps, far
    # under the 128 MiB budget even with q/k/v/o blocks alongside.
    # Round-4 note: an ISOLATED grad-chain probe preferred (512,1024) by
    # 13%, but the full GPT-2-medium train step measured consistently WORSE
    # with a 512 q-cap (41.4 vs 42.4% MFU, two runs each) — in-model, XLA
    # overlaps the flash bwd with surrounding matmuls differently than any
    # attention-only microbenchmark. The 1024 cap stands on the end-to-end
    # number; tune via explicit block_q/block_k, not the auto default.
    cap = _auto_tile_cap()
    bq = _auto_block(lq, cap) if block_q is None else min(block_q, lq)
    bk = _auto_block(lk, cap) if block_k is None else min(block_k, lk)
    if lq % bq or lk % bk:
        raise ValueError(
            f"block sizes ({bq}, {bk}) must divide sequence lengths ({lq}, {lk})"
        )
    return bq, bk


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr,
    *, scale: float, causal: bool, q_offset: int, k_offset: int,
    block_q: int, block_k: int, nk: int, dot_dtype,
):
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[:] = jnp.zeros_like(l_scr)

    iq = pl.program_id(2)
    q_lo = q_offset + iq * block_q
    k_lo = k_offset + ik * block_k

    def _body():
        q = q_ref[0, 0].astype(dot_dtype)
        k = k_ref[0, 0].astype(dot_dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            q_pos = q_lo + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = k_lo + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_BIG)

        m_prev = m_scr[:, 0]
        l_prev = l_scr[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        if causal:
            # When every entry of a row is masked, m_new == _NEG_BIG and
            # exp(s - m_new) == 1 for masked entries; zero them explicitly.
            p = jnp.where(s > 0.5 * _NEG_BIG, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + p.sum(axis=-1)
        v = v_ref[0, 0].astype(dot_dtype)
        pv = jax.lax.dot_general(
            p.astype(dot_dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        acc[:] = acc[:] * alpha[:, None] + pv
        m_scr[:, 0] = m_new
        l_scr[:, 0] = l_new

    if causal:
        # Skip blocks strictly above the diagonal (no query attends there).
        pl.when(q_lo + block_q - 1 >= k_lo)(_body)
    else:
        _body()

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_scr[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zeros
        o_ref[0, 0] = (acc[:] / l_safe[:, None]).astype(o_ref.dtype)
        m = m_scr[:, 0]
        lse = jnp.where(l == 0.0, _NEG_BIG, m + jnp.log(l_safe))
        lse_ref[0, 0] = lse[:, None]


def _fwd(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *, causal: bool, scale: float, q_offset: int, k_offset: int,
    block_q: int, block_k: int, interpret: bool, bf16_dots: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    b, lq, h, d = q.shape
    lk = k.shape[1]
    bq, bk = _block_sizes(lq, lk, block_q, block_k)
    nq, nk = lq // bq, lk // bk
    # [b, l, h, d] -> [b, h, l, d]: heads become a grid dim, seq x head_dim
    # are the (sublane, lane) tile dims the MXU wants.
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        q_offset=q_offset, k_offset=k_offset,
        block_q=bq, block_k=bk, nk=nk,
        dot_dtype=jnp.bfloat16 if bf16_dots else jnp.float32,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda ib, ih, iq, ik: (ib, ih, ik, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda ib, ih, iq, ik: (ib, ih, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            # lse rides in a trailing unit lane dim: TPU blocks need their
            # last two dims (sublane, lane) tileable, so [b, h, lq] row
            # vectors are stored as [b, h, lq, 1].
            pl.BlockSpec((1, 1, bq, 1), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, lq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, lq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, _LANE), jnp.float32),
            pltpu.VMEM((bq, _LANE), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return jnp.swapaxes(out, 1, 2), lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, scale: float, causal: bool, q_offset: int, k_offset: int,
    block_q: int, block_k: int, nk: int, dot_dtype,
):
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    iq = pl.program_id(2)
    q_lo = q_offset + iq * block_q
    k_lo = k_offset + ik * block_k

    def _body():
        q = q_ref[0, 0].astype(dot_dtype)
        k = k_ref[0, 0].astype(dot_dtype)
        v = v_ref[0, 0].astype(dot_dtype)
        do = do_ref[0, 0].astype(dot_dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            q_pos = q_lo + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = k_lo + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_BIG)
        p = jnp.exp(s - lse_ref[0, 0])
        if causal:
            p = jnp.where(s > 0.5 * _NEG_BIG, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0, 0]) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(dot_dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(q_lo + block_q - 1 >= k_lo)(_body)
    else:
        _body()

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, scale: float, causal: bool, q_offset: int, k_offset: int,
    block_q: int, block_k: int, nq: int, dot_dtype,
):
    iq = pl.program_id(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    ik = pl.program_id(2)
    q_lo = q_offset + iq * block_q
    k_lo = k_offset + ik * block_k

    def _body():
        q = q_ref[0, 0].astype(dot_dtype)
        k = k_ref[0, 0].astype(dot_dtype)
        v = v_ref[0, 0].astype(dot_dtype)
        do = do_ref[0, 0].astype(dot_dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            q_pos = q_lo + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = k_lo + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_BIG)
        p = jnp.exp(s - lse_ref[0, 0])  # [bq, bk]
        if causal:
            p = jnp.where(s > 0.5 * _NEG_BIG, p, 0.0)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(dot_dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0, 0]) * scale  # [bq, bk]
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(dot_dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(q_lo + block_q - 1 >= k_lo)(_body)
    else:
        _body()

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(
    q, k, v, out, lse, do,
    *, causal: bool, scale: float, q_offset: int, k_offset: int,
    block_q: int, block_k: int, interpret: bool, bf16_dots: bool = False,
):
    dot_dtype = jnp.bfloat16 if bf16_dots else jnp.float32
    b, lq, h, d = q.shape
    lk = k.shape[1]
    bq, bk = _block_sizes(lq, lk, block_q, block_k)
    nq, nk = lq // bq, lk // bk

    # delta_i = rowsum(dO_i * O_i) — cheap elementwise reduce, XLA fuses it.
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.swapaxes(delta, 1, 2)[..., None]  # [b, h, lq, 1]

    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    dot = jnp.swapaxes(do, 1, 2)

    q_spec = pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0))
    k_spec = pl.BlockSpec((1, 1, bk, d), lambda ib, ih, iq, ik: (ib, ih, ik, 0))
    row_spec = pl.BlockSpec((1, 1, bq, 1), lambda ib, ih, iq, ik: (ib, ih, iq, 0))

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal,
            q_offset=q_offset, k_offset=k_offset, block_q=bq, block_k=bk, nk=nk,
            dot_dtype=dot_dtype,
        ),
        grid=(b, h, nq, nk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, lq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    # k-major grid: the q loop is the accumulating (minor) dim for dk/dv.
    q_spec2 = pl.BlockSpec((1, 1, bq, d), lambda ib, ih, ik, iq: (ib, ih, iq, 0))
    k_spec2 = pl.BlockSpec((1, 1, bk, d), lambda ib, ih, ik, iq: (ib, ih, ik, 0))
    row_spec2 = pl.BlockSpec((1, 1, bq, 1), lambda ib, ih, ik, iq: (ib, ih, iq, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal,
            q_offset=q_offset, k_offset=k_offset, block_q=bq, block_k=bk, nq=nq,
            dot_dtype=dot_dtype,
        ),
        grid=(b, h, nk, nq),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, row_spec2, row_spec2],
        out_specs=[k_spec2, k_spec2],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, lk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, lk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    return (
        jnp.swapaxes(dq, 1, 2),
        jnp.swapaxes(dk, 1, 2),
        jnp.swapaxes(dv, 1, 2),
    )


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10)
)
def _flash(q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret,
           bf16_dots):
    out, _ = _fwd(
        q, k, v, causal=causal, scale=scale, q_offset=q_offset, k_offset=k_offset,
        block_q=block_q, block_k=block_k, interpret=interpret, bf16_dots=bf16_dots,
    )
    return out


def _flash_fwd(q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret,
               bf16_dots):
    out, lse = _fwd(
        q, k, v, causal=causal, scale=scale, q_offset=q_offset, k_offset=k_offset,
        block_q=block_q, block_k=block_k, interpret=interpret, bf16_dots=bf16_dots,
    )
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, q_offset, k_offset, block_q, block_k, interpret,
               bf16_dots, res, do):
    q, k, v, out, lse = res
    return _bwd(
        q, k, v, out, lse, do,
        causal=causal, scale=scale, q_offset=q_offset, k_offset=k_offset,
        block_q=block_q, block_k=block_k, interpret=interpret, bf16_dots=bf16_dots,
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset: int = 0,
    k_offset: int = 0,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    bf16_dots: Optional[bool] = None,
) -> jax.Array:
    """Fused attention. q: [b, lq, h, d]; k/v: [b, lk, h, d] -> [b, lq, h, d].

    Differentiable (custom VJP, both passes Pallas). ``q_offset``/``k_offset``
    are the global positions of element 0 of q/k for causal masking — ring
    attention passes the rotating block's ring position here. On non-TPU
    backends the kernel runs in interpreter mode (tests); pass
    ``interpret=False`` to force compilation.

    ``block_q``/``block_k`` default to auto-tiling (_block_sizes): the
    largest 128-aligned divisors up to 1024 each — measured ~3x faster than
    the old fixed 128x128 tiles at GPT shapes on v5e (see _block_sizes).
    """
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError("expected [batch, seq, heads, head_dim] inputs")
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    interpret = _interpret_default() if interpret is None else interpret
    bq, bk = _block_sizes(q.shape[1], k.shape[1], block_q, block_k)
    if not interpret and (bq % 8 or bk % 8):
        # Mosaic requires sublane dims to be multiples of 8; fail fast with
        # a clear message instead of an opaque TPU compile error (ADVICE r3).
        raise ValueError(
            f"block sizes ({bq}, {bk}) are not 8-aligned; sequence lengths "
            f"({q.shape[1]}, {k.shape[1]}) have no TPU-tileable divisor — "
            "pad the sequence or pass explicit block_q/block_k"
        )
    if bf16_dots is None:
        import os

        bf16_dots = os.environ.get("FLASH_BF16_DOTS") == "1"
    return _flash(
        q, k, v, causal, scale, int(q_offset), int(k_offset),
        bq, bk, interpret, bool(bf16_dots),
    )


def auto_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Pick the fused kernel when the backend/shapes support it.

    Drop-in ``attention_fn`` for kubeflow_tpu.models: Pallas flash attention
    on TPU for 128-tileable sequence lengths, exact XLA attention otherwise
    (CPU tests, ragged prototype shapes).
    """
    lq, lk = q.shape[1], k.shape[1]
    if jax.default_backend() == "tpu":
        if lq % 128 == 0 and lk % 128 == 0:
            return flash_attention(
                q, k, v, causal=causal, scale=scale, interpret=False)
        # Same eligibility cliff as the bq%8/bk%8 fail-fast above, but here
        # the miss used to be silent: the model quietly ran the O(l^2)
        # materialized path on TPU. Make the MFU loss visible.
        from kubeflow_tpu.ops.fallback import record_fallback

        record_fallback(
            "flash_attention",
            f"sequence lengths ({lq}, {lk}) are not 128-tileable; "
            "pad the sequence to recover the fused path")
    from kubeflow_tpu.parallel.ring_attention import full_attention

    return full_attention(q, k, v, causal=causal, scale=scale)
