"""Per-row KV-cache row update — the continuous-batching write primitive.

Slot-based decode (serving/continuous.py) keeps one KV cache of shape
[slots, max_seq, heads, head_dim] with an independent cursor per row. Each
decode step must write ONE [heads, head_dim] vector per row at that row's
cursor. The pure-XLA formulations all touch the whole cache per layer:

- ``jnp.where(position == cursor, new, cache)`` — one full read+write
  elementwise pass over the cache (round-4 measured: turns the 3.3 ms
  shared-cursor decode step into 8.2 ms at 24 layers);
- vmapped ``dynamic_update_slice`` / ``.at[arange, cursors].set`` — lower
  to scatter, measured ~3x slower still (models/gpt.py:164-167).

This kernel touches only the [1, block_t, heads, head_dim] tile containing
each row's cursor: grid over slots, the cursor scalars are prefetched so
the block index map can select the tile, and ``input_output_aliases``
makes the update in place (no fresh cache buffer, no full-cache pass).
Per step it moves S*block_t*h*d elements instead of S*max_seq*h*d — for
the serving bench shapes that is 44x less cache traffic per layer.

The round-5 fused-bottleneck study (BASELINE.md) showed Pallas *streaming*
runs at ~0.5-0.7x XLA's HBM rate on this backend — which is exactly why
this kernel wins: it removes the stream entirely instead of re-emitting it
through Pallas.

No reference analog: the reference (equinor/kubeflow) contains no serving
kernels; this is TPU-first infrastructure for the crud-web-app-adjacent
serving path (SURVEY.md section 2.9/2.10).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _kernel(cur_ref, cache_ref, new_ref, out_ref, *, block_t: int, t: int):
    s = pl.program_id(0)
    cur = cur_ref[s]
    off = jnp.minimum(cur, t - 1) % block_t
    out_ref[...] = cache_ref[...]
    # Out-of-range cursors (retired/idle rows stepping past their end) must
    # be a NO-OP, matching the where-select path where no position compares
    # equal — not a write that corrupts the last KV position.
    out_ref[0, pl.dslice(off, 1)] = jnp.where(
        cur < t, new_ref[0], cache_ref[0, pl.dslice(off, 1)])


@functools.partial(jax.jit, static_argnames=("block_t", "interpret"))
def kv_row_update(cache: jax.Array, new: jax.Array, cursors: jax.Array,
                  *, block_t: int = 8, interpret: bool | None = None) -> jax.Array:
    """Return ``cache`` with ``new[s]`` written at ``cache[s, cursors[s]]``.

    cache: [S, T, H, D]; new: [S, H, D] (or [S, 1, H, D]); cursors: [S] int32.
    In place when the caller donates ``cache`` (the serving engine's step
    donates the whole cache pytree). Cursors at or beyond T are a NO-OP for
    that row: the engine lets retired/idle rows keep stepping past their
    end (static shapes — every row computes every chunk), and the
    where-select path writes nothing there (no position compares equal), so
    the kernel must agree rather than rewrite position T-1. The block index
    still clamps to the last tile to avoid out-of-bounds tile selection;
    the in-kernel predicate keeps the data untouched.
    """
    S, T, H, D = cache.shape
    if new.ndim == 3:
        new = new[:, None]
    if T % block_t != 0:
        # largest divisor of T not above the requested tile
        block_t = next(b for b in range(min(block_t, T), 0, -1) if T % b == 0)
    if interpret is None:
        interpret = _interpret_default()

    def cache_block(s, cur):
        return (s, jnp.minimum(cur[s], T - 1) // block_t, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S,),
        in_specs=[
            pl.BlockSpec((1, block_t, H, D), cache_block),
            pl.BlockSpec((1, 1, H, D), lambda s, cur: (s, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_t, H, D), cache_block),
    )
    return pl.pallas_call(
        functools.partial(_kernel, block_t=block_t, t=T),
        out_shape=jax.ShapeDtypeStruct(cache.shape, cache.dtype),
        grid_spec=grid_spec,
        input_output_aliases={1: 0},  # flattened args: (cursors, cache, new)
        interpret=interpret,
    )(cursors.astype(jnp.int32), cache, new.astype(cache.dtype))
