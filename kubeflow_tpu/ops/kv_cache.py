"""Per-row KV-cache row update — the continuous-batching write primitive.

Slot-based decode (serving/continuous.py) keeps one KV cache of shape
[slots, max_seq, heads, head_dim] with an independent cursor per row. Each
decode step must write ONE [heads, head_dim] vector per row at that row's
cursor. The pure-XLA formulations all touch the whole cache per layer:

- ``jnp.where(position == cursor, new, cache)`` — one full read+write
  elementwise pass over the cache (round-4 measured: turns the 3.3 ms
  shared-cursor decode step into 8.2 ms at 24 layers);
- vmapped ``dynamic_update_slice`` / ``.at[arange, cursors].set`` — lower
  to scatter, measured ~3x slower still (models/gpt.py:164-167).

This kernel touches only the [1, block_t, heads, head_dim] tile containing
each row's cursor: grid over slots, the cursor scalars are prefetched so
the block index map can select the tile, and ``input_output_aliases``
makes the update in place (no fresh cache buffer, no full-cache pass).
Per step it moves S*block_t*h*d elements instead of S*max_seq*h*d — for
the serving bench shapes that is 44x less cache traffic per layer.

The round-5 fused-bottleneck study (BASELINE.md) showed Pallas *streaming*
runs at ~0.5-0.7x XLA's HBM rate on this backend — which is exactly why
this kernel wins: it removes the stream entirely instead of re-emitting it
through Pallas.

No reference analog: the reference (equinor/kubeflow) contains no serving
kernels; this is TPU-first infrastructure for the crud-web-app-adjacent
serving path (SURVEY.md section 2.9/2.10).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _kernel(cur_ref, cache_ref, new_ref, out_ref, *, block_t: int, t: int):
    s = pl.program_id(0)
    cur = cur_ref[s]
    off = jnp.minimum(cur, t - 1) % block_t
    out_ref[...] = cache_ref[...]
    # Out-of-range cursors (retired/idle rows stepping past their end) must
    # be a NO-OP, matching the where-select path where no position compares
    # equal — not a write that corrupts the last KV position.
    out_ref[0, pl.dslice(off, 1)] = jnp.where(
        cur < t, new_ref[0], cache_ref[0, pl.dslice(off, 1)])


@functools.partial(jax.jit, static_argnames=("block_t", "interpret"))
def kv_row_update(cache: jax.Array, new: jax.Array, cursors: jax.Array,
                  *, block_t: int = 8, interpret: bool | None = None) -> jax.Array:
    """Return ``cache`` with ``new[s]`` written at ``cache[s, cursors[s]]``.

    cache: [S, T, H, D]; new: [S, H, D] (or [S, 1, H, D]); cursors: [S] int32.
    In place when the caller donates ``cache`` (the serving engine's step
    donates the whole cache pytree). Cursors at or beyond T are a NO-OP for
    that row: the engine lets retired/idle rows keep stepping past their
    end (static shapes — every row computes every chunk), and the
    where-select path writes nothing there (no position compares equal), so
    the kernel must agree rather than rewrite position T-1. The block index
    still clamps to the last tile to avoid out-of-bounds tile selection;
    the in-kernel predicate keeps the data untouched.
    """
    S, T, H, D = cache.shape
    if new.ndim == 3:
        new = new[:, None]
    if T % block_t != 0:
        # largest divisor of T not above the requested tile
        block_t = next(b for b in range(min(block_t, T), 0, -1) if T % b == 0)
    if interpret is None:
        interpret = _interpret_default()

    def cache_block(s, cur):
        return (s, jnp.minimum(cur[s], T - 1) // block_t, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S,),
        in_specs=[
            pl.BlockSpec((1, block_t, H, D), cache_block),
            pl.BlockSpec((1, 1, H, D), lambda s, cur: (s, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_t, H, D), cache_block),
    )
    return pl.pallas_call(
        functools.partial(_kernel, block_t=block_t, t=T),
        out_shape=jax.ShapeDtypeStruct(cache.shape, cache.dtype),
        grid_spec=grid_spec,
        input_output_aliases={1: 0},  # flattened args: (cursors, cache, new)
        interpret=interpret,
    )(cursors.astype(jnp.int32), cache, new.astype(cache.dtype))


# ---------------------------------------------------------------------------
# Paged (block-table) variants — ISSUE 12.
#
# The paged layout replaces the per-slot [S, T, H, D] cache with one shared
# arena [N, block_t, H, D] plus a per-slot block table [S, MB] of arena row
# ids. The LAST arena row (N-1) is the trash block: table entries for
# unallocated positions point there, so a write through a trash entry lands
# in a row nothing ever reads (the attention mask hides every position at or
# beyond the row's cursor). That single convention is what makes retirement
# safe without device synchronization: the engine redirects a slot's table
# row to trash BEFORE returning its blocks to the free list, and dispatches
# execute in issue order.
# ---------------------------------------------------------------------------


def _paged_kernel(cur_ref, tbl_ref, arena_ref, new_ref, out_ref,
                  *, block_t: int, max_seq: int):
    s = pl.program_id(0)
    cur = cur_ref[s]
    off = jnp.minimum(cur, max_seq - 1) % block_t
    out_ref[...] = arena_ref[...]
    # Same no-op contract as kv_row_update: a cursor at or beyond max_seq
    # leaves the tile untouched (the index map still selects a valid tile).
    out_ref[0, pl.dslice(off, 1)] = jnp.where(
        cur < max_seq, new_ref[0], arena_ref[0, pl.dslice(off, 1)])


@functools.partial(jax.jit, static_argnames=("max_seq", "interpret"))
def kv_block_update(arena: jax.Array, new: jax.Array, cursors: jax.Array,
                    tables: jax.Array, *, max_seq: int,
                    interpret: bool | None = None) -> jax.Array:
    """Paged generalization of :func:`kv_row_update`.

    arena: [N, block_t, H, D] shared block arena (row N-1 is the trash
    block); new: [S, H, D] (or [S, 1, H, D]); cursors: [S] int32 absolute
    positions; tables: [S, MB] int32 arena row ids per slot.

    Writes ``new[s]`` at ``arena[tables[s, cursors[s] // block_t],
    cursors[s] % block_t]``. Both the cursor- and table-scalars are
    prefetched so the block index map can chase the indirection; the grid
    stays (S,) and each step touches exactly one [1, block_t, H, D] tile.
    Cursors at or beyond ``max_seq`` are a no-op for the data (the tile
    selection clamps, the in-kernel predicate skips the write); positions
    whose table entry is the trash block land in the trash row.
    """
    N, block_t, H, D = arena.shape
    S = new.shape[0]
    mb = tables.shape[1]
    if new.ndim == 3:
        new = new[:, None]
    if interpret is None:
        interpret = _interpret_default()

    def arena_block(s, cur, tbl):
        pos = jnp.minimum(cur[s], max_seq - 1)
        return (tbl[s, jnp.minimum(pos // block_t, mb - 1)], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S,),
        in_specs=[
            pl.BlockSpec((1, block_t, H, D), arena_block),
            pl.BlockSpec((1, 1, H, D), lambda s, cur, tbl: (s, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_t, H, D), arena_block),
    )
    return pl.pallas_call(
        functools.partial(_paged_kernel, block_t=block_t, max_seq=max_seq),
        out_shape=jax.ShapeDtypeStruct(arena.shape, arena.dtype),
        grid_spec=grid_spec,
        # flattened args: (cursors, tables, arena, new)
        input_output_aliases={2: 0},
        interpret=interpret,
    )(cursors.astype(jnp.int32), tables.astype(jnp.int32),
      arena, new.astype(arena.dtype))


# ---------------------------------------------------------------------------
# int8 KV quantization — ISSUE 18.
#
# Symmetric per-(position-row, head) quantization: one f32 scale per written
# KV vector's head, computed as abs-max over head_dim / 127. The scale rides
# in a parallel arena shaped [N, block_t, H, 1] so the exact same block-table
# indirection (and the same scatter reference) addresses it. Zero-point is
# implicitly 0 (symmetric): rope'd keys and values are zero-mean enough that
# an asymmetric zero-point buys <0.1% extra SNR for 2x the bookkeeping.
# Everything is computed in f32 with round-half-even, so the Pallas kernel,
# the XLA reference, and the host-side helper produce bit-identical int8 —
# the KV-handoff byte-parity contract depends on that.
# ---------------------------------------------------------------------------


def quantize_kv(x: jax.Array):
    """Quantize KV vectors symmetrically per head row.

    x: [..., H, D] (bf16/f32) -> (int8 [..., H, D], f32 scales [..., H, 1]).
    ``dequantize_kv(q, s)`` recovers x to within scale/2 per element. All-zero
    rows quantize to zeros with scale 0 (dequant is exactly 0).
    """
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / 127.0
    q = jnp.clip(jnp.round(xf / jnp.where(scale > 0, scale, 1.0)),
                 -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_kv(q: jax.Array, scale: jax.Array) -> jax.Array:
    """Inverse of :func:`quantize_kv` (f32 out)."""
    return q.astype(jnp.float32) * scale


# Compiled quantizer shared by the adoption path and the KV-wire exporter.
# Eager and jitted quantize_kv disagree by ~1 ULP in scale (XLA rewrites the
# division to a reciprocal multiply), which flips int8 codes at rounding
# boundaries — jit-vs-jit is bit-identical across batch shapes, so every
# producer of arena bytes must go through this one entry point for the
# moved-vs-never-moved parity contract to hold.
quantize_kv_jit = jax.jit(quantize_kv)


def _paged_quant_kernel(cur_ref, tbl_ref, arena_ref, scale_ref, new_ref,
                        q_out_ref, s_out_ref, *, block_t: int, max_seq: int):
    s = pl.program_id(0)
    cur = cur_ref[s]
    off = jnp.minimum(cur, max_seq - 1) % block_t
    q_out_ref[...] = arena_ref[...]
    s_out_ref[...] = scale_ref[...]
    x = new_ref[0].astype(jnp.float32)                       # [1, H, D]
    scale = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0
    q = jnp.clip(jnp.round(x / jnp.where(scale > 0, scale, 1.0)),
                 -127, 127).astype(jnp.int8)
    write = cur < max_seq
    q_out_ref[0, pl.dslice(off, 1)] = jnp.where(
        write, q, arena_ref[0, pl.dslice(off, 1)])
    s_out_ref[0, pl.dslice(off, 1)] = jnp.where(
        write, scale, scale_ref[0, pl.dslice(off, 1)])


@functools.partial(jax.jit, static_argnames=("max_seq", "interpret"))
def kv_block_update_quant(arena: jax.Array, scales: jax.Array, new: jax.Array,
                          cursors: jax.Array, tables: jax.Array, *,
                          max_seq: int, interpret: bool | None = None):
    """Store-quantized variant of :func:`kv_block_update`.

    arena: [N, block_t, H, D] int8; scales: [N, block_t, H, 1] f32; new:
    [S, H, D] (or [S, 1, H, D]) bf16/f32. Quantizes ``new`` INSIDE the
    kernel (same math as :func:`quantize_kv`) and writes value + scale
    through the block table in one pass — both arenas alias in place. Same
    out-of-range no-op contract as the bf16 kernel.
    """
    N, block_t, H, D = arena.shape
    S = new.shape[0]
    mb = tables.shape[1]
    if new.ndim == 3:
        new = new[:, None]
    if interpret is None:
        interpret = _interpret_default()

    def arena_block(s, cur, tbl):
        pos = jnp.minimum(cur[s], max_seq - 1)
        return (tbl[s, jnp.minimum(pos // block_t, mb - 1)], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S,),
        in_specs=[
            pl.BlockSpec((1, block_t, H, D), arena_block),
            pl.BlockSpec((1, block_t, H, 1), arena_block),
            pl.BlockSpec((1, 1, H, D), lambda s, cur, tbl: (s, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_t, H, D), arena_block),
            pl.BlockSpec((1, block_t, H, 1), arena_block),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_quant_kernel, block_t=block_t,
                          max_seq=max_seq),
        out_shape=[jax.ShapeDtypeStruct(arena.shape, jnp.int8),
                   jax.ShapeDtypeStruct(scales.shape, jnp.float32)],
        grid_spec=grid_spec,
        # flattened args: (cursors, tables, arena, scales, new)
        input_output_aliases={2: 0, 3: 1},
        interpret=interpret,
    )(cursors.astype(jnp.int32), tables.astype(jnp.int32),
      arena, scales, new)


def kv_block_update_ref(arena: jax.Array, seg: jax.Array, cursors: jax.Array,
                        tables: jax.Array, *, max_seq: int) -> jax.Array:
    """XLA scatter reference for :func:`kv_block_update`, generalized to
    multi-token segments (speculative-verify writes ``seg_len`` positions
    per row in one call).

    arena: [N, block_t, H, D]; seg: [S, L, H, D]; cursors: [S] (position of
    ``seg[:, 0]``); tables: [S, MB]. Out-of-range positions are redirected
    to the trash row (N-1) instead of being skipped so the whole update
    stays one scatter per token.
    """
    N, block_t, _, _ = arena.shape
    S, L = seg.shape[:2]
    mb = tables.shape[1]
    rows = jnp.arange(S)
    cursors = cursors.astype(jnp.int32)
    for j in range(L):
        pos = cursors + j
        bi = jnp.clip(pos // block_t, 0, mb - 1)
        blk = jnp.where(pos < max_seq, tables[rows, bi], N - 1)
        arena = arena.at[blk, pos % block_t].set(seg[:, j].astype(arena.dtype))
    return arena
