"""TPU-native hot-path kernels (Pallas).

The reference control plane has no compute kernels (SURVEY.md §2.10 — it
schedules pods); these are the in-workload compute half of the TPU-first
build: fused attention for the notebook/serving/training recipes, used by
``kubeflow_tpu.models`` and composed with the ring in
``kubeflow_tpu.parallel.ring_attention``.
"""

from kubeflow_tpu.ops.fallback import record_fallback, reset_fallback_warnings
from kubeflow_tpu.ops.flash_attention import auto_attention, flash_attention
from kubeflow_tpu.ops.fused_bottleneck import (
    folded_bottleneck,
    fused_bottleneck,
    fused_bottleneck_block,
    fused_transition,
    fused_transition_block,
    reference_bottleneck,
    reference_transition,
)

__all__ = [
    "auto_attention",
    "flash_attention",
    "folded_bottleneck",
    "fused_bottleneck",
    "fused_bottleneck_block",
    "fused_transition",
    "fused_transition_block",
    "record_fallback",
    "reference_bottleneck",
    "reference_transition",
    "reset_fallback_warnings",
]
