"""TPU-native hot-path kernels (Pallas).

The reference control plane has no compute kernels (SURVEY.md §2.10 — it
schedules pods); these are the in-workload compute half of the TPU-first
build: fused attention for the notebook/serving/training recipes, used by
``kubeflow_tpu.models`` and composed with the ring in
``kubeflow_tpu.parallel.ring_attention``.
"""

from kubeflow_tpu.ops.flash_attention import auto_attention, flash_attention
from kubeflow_tpu.ops.fused_bottleneck import (
    fused_bottleneck,
    fused_bottleneck_block,
    reference_bottleneck,
)

__all__ = [
    "auto_attention",
    "flash_attention",
    "fused_bottleneck",
    "fused_bottleneck_block",
    "reference_bottleneck",
]
