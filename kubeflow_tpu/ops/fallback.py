"""Fused-kernel fallback visibility.

Every fused op in ``kubeflow_tpu.ops`` has a compiler-scheduled XLA fallback
for shapes (or backends) the Pallas kernel does not take. The fallbacks are
numerically fine, which is exactly why they used to be silent — a model
could quietly lose a third of its MFU to an ineligible sequence length and
nothing would say so. Eligibility misses now tick
``ops_fused_fallback_total{kernel=...}`` and warn once per (kernel, reason)
so the loss shows up in the metrics plane instead of only in a profile.

Recording happens at trace time (once per compiled shape), not per step —
the counter measures distinct fallback decisions, not executions.
"""

from __future__ import annotations

import warnings
from typing import Set, Tuple

from kubeflow_tpu.runtime.metrics import METRICS

_OPS = METRICS.namespace("ops")
_warned: Set[Tuple[str, str]] = set()


def record_fallback(kernel: str, reason: str) -> None:
    """Count a fused-kernel eligibility miss and warn once per reason."""
    _OPS.counter("fused_fallback_total", kernel=kernel).inc()
    key = (kernel, reason)
    if key not in _warned:
        _warned.add(key)
        warnings.warn(
            f"fused kernel {kernel!r} fell back to the XLA path: {reason} "
            "(counted in ops_fused_fallback_total)",
            RuntimeWarning, stacklevel=3)


def reset_fallback_warnings() -> None:
    """Re-arm the one-time warnings (tests)."""
    _warned.clear()
