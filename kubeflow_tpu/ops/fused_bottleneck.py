"""Fused ResNet bottleneck (1x1 -> 3x3 -> 1x1 + residual) Pallas kernels.

The round-4 conv decomposition (BASELINE.md) pinned ResNet-50's MFU ceiling
on v5e to the 1x1 projection convs: at stage-1 shapes they are HBM-bound at
~39 TF/s (52 F/B arithmetic intensity against a ~770 GB/s part), and they
carry ~2/3 of bottleneck FLOPs. The only remaining lever is cross-op fusion
that keeps the 256-channel activations in VMEM across the whole block —
these kernels are that lever, built to measure (VERDICT r4 #1).

Per grid step (one image), entirely in VMEM:
    x[hw,hw,cin] -> h1 = relu(x @ W1 * s1 + b1)          # 1x1 reduce
                 -> h2 = relu(im2col(h1) @ W2 * s2 + b2) # 3x3 implicit GEMM
                 -> y  = relu(sc + (h2 @ W3 * s3 + b3))  # 1x1 expand + shortcut
HBM traffic: read x once + write y once (the XLA composite moves x, h1,
h2, y through HBM ~6 passes). Norms are folded scale/bias ("frozen norm",
the same setting the round-4 composite measured at 42.6 TF/s — batch-stat
BatchNorm needs a cross-image reduction no per-image kernel can fuse).

Two kernel families cover all 16 ResNet-50 blocks at 224x224:

- ``fused_bottleneck``: identity-shortcut, stride-1 blocks. Row dims that
  are not 8-aligned (14x14 -> 196 rows, 7x7 -> 49) go through sublane-padded
  dots (``_pdot``), so every spatial stage qualifies — not just the %8 ones.
- ``fused_transition``: the stage-head blocks (stride-2 3x3 + 1x1 projection
  shortcut, or the stride-1 channel-expanding stage1 head). The projection
  runs in the same VMEM residency as the main path.

``folded_bottleneck`` is the XLA epilogue-fusion fallback for shapes neither
kernel takes (non-square, odd strided inputs): same folded-norm math, each
conv+scale+relu a single XLA fusion, checkpoint-identical params.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _pdot(a, b):
    """Row-dim sublane-padded matmul: ``a @ b`` with f32 accumulation.

    Mosaic wants (8, 128)-tileable f32 operands; row counts like 196
    (14x14 images) or 49 (7x7) are not. Pad the rows with zeros for the
    MXU pass and slice the product back — zero rows contribute nothing,
    and on 8-aligned shapes both branches are no-ops so the original
    kernels' numerics are untouched.
    """
    m = a.shape[0]
    mp = -(-m // 8) * 8
    if mp != m:
        a = jnp.pad(a, ((0, mp - m), (0, 0)))
    out = jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    return out[:m] if mp != m else out


def _expand_rows_per_chunk(hw: int) -> int:
    """Row-group size for the 1x1 expand stage.

    The f32 [rows, cout] intermediate is the VMEM peak (3.2 MiB whole-image
    at stage-1 shapes, x2 with the shortcut operand), so large images chunk
    by 8 rows as before; 28x28 chunks by 4 (sublane-aligned: 4*28 = 112);
    14x14/7x7 fit whole-image (<1 MiB) and lean on ``_pdot`` padding.
    """
    if hw % 8 == 0:
        return 8
    if hw % 4 == 0 and hw > 16:
        return 4
    return hw


def _kernel(x_ref, w1_ref, s1_ref, w2_ref, s2_ref, w3_ref, s3_ref, o_ref,
            *, hw: int, cin: int, cmid: int, dot_dtype):
    x = x_ref[0]                                    # [hw, hw, cin] bf16
    xm = x.reshape(hw * hw, cin)
    w1 = w1_ref[...].astype(dot_dtype)              # [cin, cmid]
    h1 = _pdot(xm.astype(dot_dtype), w1)
    h1 = jnp.maximum(h1 * s1_ref[0] + s1_ref[1], 0.0)  # bn1 folded + relu

    # 3x3 as ONE implicit-GEMM dot: im2col built in VMEM (9 shifted views
    # of the zero-padded h1 concatenated on the lane dim). K=9*cmid=576
    # feeds the 128-wide MXU contraction far better than 9 K=64 tap dots
    # (measured: tap-dots 28.6 TF/s vs XLA composite 33.5 at stage-1) —
    # and unlike the round-4 HBM im2col experiment, the 9x data blowup
    # lives only in VMEM.
    h1p = jnp.pad(h1.reshape(hw, hw, cmid).astype(dot_dtype),
                  ((1, 1), (1, 1), (0, 0)))
    cols = jnp.concatenate(
        [h1p[di:di + hw, dj:dj + hw, :].reshape(hw * hw, cmid)
         for di in range(3) for dj in range(3)], axis=1)     # [hw*hw, 9*cmid]
    w2m = w2_ref[...].astype(dot_dtype).reshape(9 * cmid, cmid)
    acc = _pdot(cols, w2m)
    h2 = jnp.maximum(acc * s2_ref[0] + s2_ref[1], 0.0)      # bn2 folded + relu
    h2 = h2.astype(dot_dtype)

    # Expand stage in row chunks: the f32 [hw*hw, cin] intermediate would
    # be the VMEM peak (3.2 MiB at stage-1 shapes, x2 with the residual
    # operand — over the 16 MiB scoped stack); chunking keeps the peak at
    # one row-group while h1/h2 (cmid-wide) stay whole-image.
    w3 = w3_ref[...].astype(dot_dtype)              # [cmid, cin]
    rows_per_chunk = _expand_rows_per_chunk(hw)
    n_chunks = hw // rows_per_chunk
    m = rows_per_chunk * hw
    for r in range(n_chunks):
        h2_r = h2[r * m:(r + 1) * m]  # static slice (Mosaic-lowerable)
        y = _pdot(h2_r, w3)
        y = y * s3_ref[0] + s3_ref[1]               # bn3 folded
        x_r = x_ref[0, r * rows_per_chunk:(r + 1) * rows_per_chunk]
        y = jnp.maximum(y + x_r.reshape(m, cin).astype(jnp.float32), 0.0)
        o_ref[0, r * rows_per_chunk:(r + 1) * rows_per_chunk] = (
            y.reshape(rows_per_chunk, hw, cin).astype(o_ref.dtype))


def fused_bottleneck(
    x: jax.Array,          # [n, hw, hw, cin]
    w1: jax.Array,         # [cin, cmid]
    scale1: jax.Array, bias1: jax.Array,   # [cmid] folded bn1
    w2: jax.Array,         # [3, 3, cmid, cmid]
    scale2: jax.Array, bias2: jax.Array,   # [cmid]
    w3: jax.Array,         # [cmid, cin]
    scale3: jax.Array, bias3: jax.Array,   # [cin]
    *,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """relu(x + bn3(conv1x1(relu(bn2(conv3x3(relu(bn1(conv1x1(x)))))))))
    with folded scale/bias norms, one image per grid step, everything
    between the input read and output write resident in VMEM."""
    n, hw, hw2, cin = x.shape
    assert hw == hw2, x.shape
    cmid = w1.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    s1 = jnp.stack([scale1, bias1]).astype(jnp.float32)   # [2, cmid]
    s2 = jnp.stack([scale2, bias2]).astype(jnp.float32)
    s3 = jnp.stack([scale3, bias3]).astype(jnp.float32)
    w2r = w2.reshape(9, cmid, cmid)

    kernel = functools.partial(
        _kernel, hw=hw, cin=cin, cmid=cmid, dot_dtype=jnp.bfloat16)
    return pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, hw, hw, cin), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((cin, cmid), lambda i: (0, 0)),
            pl.BlockSpec((2, cmid), lambda i: (0, 0)),
            pl.BlockSpec((9, cmid, cmid), lambda i: (0, 0, 0)),
            pl.BlockSpec((2, cmid), lambda i: (0, 0)),
            pl.BlockSpec((cmid, cin), lambda i: (0, 0)),
            pl.BlockSpec((2, cin), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hw, hw, cin), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x, w1, s1, w2r, s2, w3, s3)


@jax.custom_vjp
def fused_bottleneck_block(x, w1, scale1, bias1, w2, scale2, bias2,
                           w3, scale3, bias3):
    """Differentiable fused bottleneck: Pallas forward, XLA backward.

    The kernel has no Pallas backward; the VJP recomputes the block through
    ``reference_bottleneck`` (same math, compiler-scheduled) and uses ITS
    cotangents — forward-only fusion, rematerialized backward. Residuals are
    the primal inputs, so the fused path holds no extra activations between
    fwd and bwd (the remat trade the models already make per-block).
    """
    return fused_bottleneck(x, w1, scale1, bias1, w2, scale2, bias2,
                            w3, scale3, bias3)


def _fused_block_fwd(x, w1, scale1, bias1, w2, scale2, bias2, w3, scale3, bias3):
    out = fused_bottleneck(x, w1, scale1, bias1, w2, scale2, bias2,
                           w3, scale3, bias3)
    return out, (x, w1, scale1, bias1, w2, scale2, bias2, w3, scale3, bias3)


def _composite_f32(x, w1, scale1, bias1, w2, scale2, bias2, w3, scale3, bias3):
    """All-f32 twin of ``reference_bottleneck`` for the VJP: the mixed
    bf16-input/f32-accumulate convs the reference uses hit a conv-transpose
    dtype mismatch under ``jax.vjp``; a uniform-dtype composite transposes
    cleanly and gives f32-accurate cotangents."""
    conv = functools.partial(
        jax.lax.conv_general_dilated,
        window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    h1 = jnp.maximum(conv(x, w1[None, None]) * scale1 + bias1, 0.0)
    h2 = jnp.maximum(conv(h1, w2) * scale2 + bias2, 0.0)
    y = conv(h2, w3[None, None]) * scale3 + bias3
    return jnp.maximum(y + x, 0.0)


def _fused_block_bwd(residuals, g):
    primals_f32 = tuple(r.astype(jnp.float32) for r in residuals)
    _, vjp = jax.vjp(_composite_f32, *primals_f32)
    grads = vjp(g.astype(jnp.float32))
    return tuple(dr.astype(r.dtype) for dr, r in zip(grads, residuals))


fused_bottleneck_block.defvjp(_fused_block_fwd, _fused_block_bwd)


def reference_bottleneck(x, w1, scale1, bias1, w2, scale2, bias2,
                         w3, scale3, bias3):
    """The XLA composite the kernel must match (and beat): same math,
    scheduled by the compiler through HBM."""
    f32 = jnp.float32
    h1 = jax.lax.conv_general_dilated(
        x.astype(jnp.bfloat16), w1[None, None].astype(jnp.bfloat16),
        (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=f32)
    h1 = jnp.maximum(h1 * scale1 + bias1, 0.0)
    h2 = jax.lax.conv_general_dilated(
        h1.astype(jnp.bfloat16), w2.astype(jnp.bfloat16),
        (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=f32)
    h2 = jnp.maximum(h2 * scale2 + bias2, 0.0)
    y = jax.lax.conv_general_dilated(
        h2.astype(jnp.bfloat16), w3[None, None].astype(jnp.bfloat16),
        (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=f32)
    y = y * scale3 + bias3
    return jnp.maximum(y + x.astype(f32), 0.0).astype(x.dtype)


# ---------------------------------------------------------------------------
# Transition blocks: stride-2 (or stride-1 channel-expanding) heads with a
# 1x1 projection shortcut — the top hbm-bound sinks in the r6 attribution.
# ---------------------------------------------------------------------------


def _transition_kernel(x_ref, w1_ref, s1_ref, w2_ref, s2_ref, w3_ref, s3_ref,
                       wp_ref, sp_ref, o_ref,
                       *, hw: int, ho: int, cin: int, cmid: int, cout: int,
                       stride: int, dot_dtype):
    x = x_ref[0]                                    # [hw, hw, cin]
    xm = x.reshape(hw * hw, cin)
    w1 = w1_ref[...].astype(dot_dtype)              # [cin, cmid]
    h1 = _pdot(xm.astype(dot_dtype), w1)
    h1 = jnp.maximum(h1 * s1_ref[0] + s1_ref[1], 0.0)

    # Strided implicit-GEMM 3x3. XLA SAME padding for stride 2, kernel 3 on
    # an even input is (lo=0, hi=1): out(i,j) taps in_pad[2i+di, 2j+dj].
    # The 9 tap views become strided static slices of the padded h1 — the
    # lane (channel) dim is untouched, so Mosaic lowers them directly.
    h1sq = h1.reshape(hw, hw, cmid).astype(dot_dtype)
    if stride == 1:
        h1p = jnp.pad(h1sq, ((1, 1), (1, 1), (0, 0)))
        views = [h1p[di:di + ho, dj:dj + ho, :]
                 for di in range(3) for dj in range(3)]
    else:
        h1p = jnp.pad(h1sq, ((0, 2), (0, 2), (0, 0)))
        views = [h1p[di:di + 2 * ho:2, dj:dj + 2 * ho:2, :]
                 for di in range(3) for dj in range(3)]
    cols = jnp.concatenate(
        [v.reshape(ho * ho, cmid) for v in views], axis=1)   # [ho*ho, 9*cmid]
    w2m = w2_ref[...].astype(dot_dtype).reshape(9 * cmid, cmid)
    acc = _pdot(cols, w2m)
    h2 = jnp.maximum(acc * s2_ref[0] + s2_ref[1], 0.0)
    h2 = h2.astype(dot_dtype)

    # Projection shortcut input: a 1x1 stride-s SAME conv reads every s-th
    # pixel, so the subsample is a plain strided slice of x.
    xs = x if stride == 1 else x[::2, ::2, :]       # [ho, ho, cin]

    # Expand + projection in row chunks (same VMEM-peak argument as the
    # identity kernel, with the projection dot riding the same row group).
    w3 = w3_ref[...].astype(dot_dtype)              # [cmid, cout]
    wp = wp_ref[...].astype(dot_dtype)              # [cin, cout]
    rows_per_chunk = _expand_rows_per_chunk(ho)
    n_chunks = ho // rows_per_chunk
    m = rows_per_chunk * ho
    for r in range(n_chunks):
        y = _pdot(h2[r * m:(r + 1) * m], w3)
        y = y * s3_ref[0] + s3_ref[1]               # bn3 folded (zero-init)
        xs_r = xs[r * rows_per_chunk:(r + 1) * rows_per_chunk]
        proj = _pdot(xs_r.reshape(m, cin).astype(dot_dtype), wp)
        proj = proj * sp_ref[0] + sp_ref[1]         # bn_proj folded
        o_ref[0, r * rows_per_chunk:(r + 1) * rows_per_chunk] = (
            jnp.maximum(proj + y, 0.0)
            .reshape(rows_per_chunk, ho, cout).astype(o_ref.dtype))


def fused_transition(
    x: jax.Array,          # [n, hw, hw, cin]
    w1: jax.Array,         # [cin, cmid]
    scale1: jax.Array, bias1: jax.Array,
    w2: jax.Array,         # [3, 3, cmid, cmid]
    scale2: jax.Array, bias2: jax.Array,
    w3: jax.Array,         # [cmid, cout]
    scale3: jax.Array, bias3: jax.Array,
    wp: jax.Array,         # [cin, cout] 1x1 projection shortcut
    scalep: jax.Array, biasp: jax.Array,
    *,
    stride: int = 2,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """relu(proj(x) + bn3(conv1x1(relu(bn2(conv3x3_s(relu(bn1(conv1x1(x)))))))))
    — the downsampling/channel-expanding stage head, fully VMEM-resident,
    projection shortcut included. ``stride`` in {1, 2}; stride 2 requires an
    even spatial dim (SAME padding is then (0, 1))."""
    n, hw, hw2, cin = x.shape
    assert hw == hw2, x.shape
    assert stride in (1, 2), stride
    assert stride == 1 or hw % 2 == 0, (hw, stride)
    cmid = w1.shape[1]
    cout = w3.shape[1]
    ho = hw if stride == 1 else hw // 2
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    s1 = jnp.stack([scale1, bias1]).astype(jnp.float32)
    s2 = jnp.stack([scale2, bias2]).astype(jnp.float32)
    s3 = jnp.stack([scale3, bias3]).astype(jnp.float32)
    sp = jnp.stack([scalep, biasp]).astype(jnp.float32)
    w2r = w2.reshape(9, cmid, cmid)

    kernel = functools.partial(
        _transition_kernel, hw=hw, ho=ho, cin=cin, cmid=cmid, cout=cout,
        stride=stride, dot_dtype=jnp.bfloat16)
    return pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, hw, hw, cin), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((cin, cmid), lambda i: (0, 0)),
            pl.BlockSpec((2, cmid), lambda i: (0, 0)),
            pl.BlockSpec((9, cmid, cmid), lambda i: (0, 0, 0)),
            pl.BlockSpec((2, cmid), lambda i: (0, 0)),
            pl.BlockSpec((cmid, cout), lambda i: (0, 0)),
            pl.BlockSpec((2, cout), lambda i: (0, 0)),
            pl.BlockSpec((cin, cout), lambda i: (0, 0)),
            pl.BlockSpec((2, cout), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, ho, ho, cout), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, ho, ho, cout), x.dtype),
        interpret=interpret,
    )(x, w1, s1, w2r, s2, w3, s3, wp, sp)


def _transition_composite_f32(stride, x, w1, scale1, bias1, w2, scale2, bias2,
                              w3, scale3, bias3, wp, scalep, biasp):
    """All-f32 XLA twin of ``fused_transition`` — the VJP recompute target
    (same role as ``_composite_f32`` for the identity kernel)."""
    conv = functools.partial(
        jax.lax.conv_general_dilated, padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    h1 = jnp.maximum(conv(x, w1[None, None], (1, 1)) * scale1 + bias1, 0.0)
    h2 = jnp.maximum(conv(h1, w2, (stride, stride)) * scale2 + bias2, 0.0)
    y = conv(h2, w3[None, None], (1, 1)) * scale3 + bias3
    proj = conv(x, wp[None, None], (stride, stride)) * scalep + biasp
    return jnp.maximum(proj + y, 0.0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _transition_block(stride, x, w1, scale1, bias1, w2, scale2, bias2,
                      w3, scale3, bias3, wp, scalep, biasp):
    return fused_transition(x, w1, scale1, bias1, w2, scale2, bias2,
                            w3, scale3, bias3, wp, scalep, biasp,
                            stride=stride)


def _transition_fwd(stride, *primals):
    return _transition_block(stride, *primals), primals


def _transition_bwd(stride, residuals, g):
    primals_f32 = tuple(r.astype(jnp.float32) for r in residuals)
    _, vjp = jax.vjp(
        functools.partial(_transition_composite_f32, stride), *primals_f32)
    grads = vjp(g.astype(jnp.float32))
    return tuple(dr.astype(r.dtype) for dr, r in zip(grads, residuals))


_transition_block.defvjp(_transition_fwd, _transition_bwd)


def fused_transition_block(x, w1, scale1, bias1, w2, scale2, bias2,
                           w3, scale3, bias3, wp, scalep, biasp,
                           *, stride: int = 2):
    """Differentiable fused transition block: Pallas forward, XLA backward
    via ``_transition_composite_f32`` cotangents (forward-only fusion,
    rematerialized backward — same contract as ``fused_bottleneck_block``)."""
    return _transition_block(stride, x, w1, scale1, bias1, w2, scale2, bias2,
                             w3, scale3, bias3, wp, scalep, biasp)


def reference_transition(x, w1, scale1, bias1, w2, scale2, bias2,
                         w3, scale3, bias3, wp, scalep, biasp,
                         *, stride: int = 2):
    """The XLA composite the transition kernel must match: bf16 convs with
    f32 accumulation, compiler-scheduled through HBM."""
    f32 = jnp.float32
    bf16 = jnp.bfloat16
    conv = functools.partial(
        jax.lax.conv_general_dilated, padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=f32)
    h1 = conv(x.astype(bf16), w1[None, None].astype(bf16), (1, 1))
    h1 = jnp.maximum(h1 * scale1 + bias1, 0.0)
    h2 = conv(h1.astype(bf16), w2.astype(bf16), (stride, stride))
    h2 = jnp.maximum(h2 * scale2 + bias2, 0.0)
    y = conv(h2.astype(bf16), w3[None, None].astype(bf16), (1, 1))
    y = y * scale3 + bias3
    proj = conv(x.astype(bf16), wp[None, None].astype(bf16), (stride, stride))
    proj = proj * scalep + biasp
    return jnp.maximum(proj + y, 0.0).astype(x.dtype)


def folded_bottleneck(x, w1, scale1, bias1, w2, scale2, bias2,
                      w3, scale3, bias3,
                      *, strides: Tuple[int, int] = (1, 1), proj=None):
    """Epilogue-fused XLA fallback for block shapes neither kernel takes.

    Folding the norm into scale/bias turns each conv+norm+relu into a
    single XLA fusion (conv with a scale/bias/relu epilogue) — batch-stat
    BatchNorm would force a cross-batch reduction pass between convs.
    Computed in f32 throughout so it transposes cleanly under ``jax.vjp``.
    ``proj`` is ``(wp, scalep, biasp)`` for a projection shortcut, or None
    for an identity shortcut.
    """
    f32 = jnp.float32
    conv = functools.partial(
        jax.lax.conv_general_dilated, padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    xf = x.astype(f32)
    h1 = jnp.maximum(
        conv(xf, w1[None, None].astype(f32), (1, 1))
        * scale1 + bias1, 0.0)
    h2 = jnp.maximum(
        conv(h1, w2.astype(f32), tuple(strides)) * scale2 + bias2, 0.0)
    y = conv(h2, w3[None, None].astype(f32), (1, 1)) * scale3 + bias3
    if proj is None:
        shortcut = xf
    else:
        wp, scalep, biasp = proj
        shortcut = (conv(xf, wp[None, None].astype(f32), tuple(strides))
                    * scalep + biasp)
    return jnp.maximum(shortcut + y, 0.0).astype(x.dtype)
