"""Fused ResNet bottleneck (1x1 -> 3x3 -> 1x1 + residual) Pallas kernel.

The round-4 conv decomposition (BASELINE.md) pinned ResNet-50's MFU ceiling
on v5e to the 1x1 projection convs: at stage-1 shapes they are HBM-bound at
~39 TF/s (52 F/B arithmetic intensity against a ~770 GB/s part), and they
carry ~2/3 of bottleneck FLOPs. The only remaining lever is cross-op fusion
that keeps the 256-channel activations in VMEM across the whole block —
this kernel is that lever, built to measure (VERDICT r4 #1).

Per grid step (one image), entirely in VMEM:
    x[56,56,256] -> h1 = relu(x @ W1 * s1 + b1)          # 1x1 reduce
                 -> h2 = relu(sum_taps shift(h1) @ W2t)  # 3x3 as 9 tap dots
                 -> y  = relu(x + (h2 @ W3 * s3 + b3))   # 1x1 expand + res
HBM traffic: read x once + write y once (the XLA composite moves x, h1,
h2, y through HBM ~6 passes). Norms are folded scale/bias ("frozen norm",
the same setting the round-4 composite measured at 42.6 TF/s — batch-stat
BatchNorm needs a cross-image reduction no per-image kernel can fuse).

Identity-shortcut, stride-1 blocks only (13 of ResNet-50's 16 blocks) —
the downsampling head blocks keep the XLA path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(x_ref, w1_ref, s1_ref, w2_ref, s2_ref, w3_ref, s3_ref, o_ref,
            *, hw: int, cin: int, cmid: int, dot_dtype):
    x = x_ref[0]                                    # [hw, hw, cin] bf16
    xm = x.reshape(hw * hw, cin)
    w1 = w1_ref[...].astype(dot_dtype)              # [cin, cmid]
    h1 = jax.lax.dot_general(
        xm.astype(dot_dtype), w1, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    h1 = jnp.maximum(h1 * s1_ref[0] + s1_ref[1], 0.0)  # bn1 folded + relu

    # 3x3 as ONE implicit-GEMM dot: im2col built in VMEM (9 shifted views
    # of the zero-padded h1 concatenated on the lane dim). K=9*cmid=576
    # feeds the 128-wide MXU contraction far better than 9 K=64 tap dots
    # (measured: tap-dots 28.6 TF/s vs XLA composite 33.5 at stage-1) —
    # and unlike the round-4 HBM im2col experiment, the 9x data blowup
    # lives only in VMEM.
    h1p = jnp.pad(h1.reshape(hw, hw, cmid).astype(dot_dtype),
                  ((1, 1), (1, 1), (0, 0)))
    cols = jnp.concatenate(
        [h1p[di:di + hw, dj:dj + hw, :].reshape(hw * hw, cmid)
         for di in range(3) for dj in range(3)], axis=1)     # [hw*hw, 9*cmid]
    w2m = w2_ref[...].astype(dot_dtype).reshape(9 * cmid, cmid)
    acc = jax.lax.dot_general(
        cols, w2m, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    h2 = jnp.maximum(acc * s2_ref[0] + s2_ref[1], 0.0)      # bn2 folded + relu
    h2 = h2.astype(dot_dtype)

    # Expand stage in row chunks: the f32 [hw*hw, cin] intermediate would
    # be the VMEM peak (3.2 MiB at stage-1 shapes, x2 with the residual
    # operand — over the 16 MiB scoped stack); chunking keeps the peak at
    # one row-group while h1/h2 (cmid-wide) stay whole-image.
    w3 = w3_ref[...].astype(dot_dtype)              # [cmid, cin]
    rows_per_chunk = 8
    n_chunks = hw // rows_per_chunk
    m = rows_per_chunk * hw
    for r in range(n_chunks):
        h2_r = h2[r * m:(r + 1) * m]  # static slice (Mosaic-lowerable)
        y = jax.lax.dot_general(
            h2_r, w3, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        y = y * s3_ref[0] + s3_ref[1]               # bn3 folded
        x_r = x_ref[0, r * rows_per_chunk:(r + 1) * rows_per_chunk]
        y = jnp.maximum(y + x_r.reshape(m, cin).astype(jnp.float32), 0.0)
        o_ref[0, r * rows_per_chunk:(r + 1) * rows_per_chunk] = (
            y.reshape(rows_per_chunk, hw, cin).astype(o_ref.dtype))


def fused_bottleneck(
    x: jax.Array,          # [n, hw, hw, cin]
    w1: jax.Array,         # [cin, cmid]
    scale1: jax.Array, bias1: jax.Array,   # [cmid] folded bn1
    w2: jax.Array,         # [3, 3, cmid, cmid]
    scale2: jax.Array, bias2: jax.Array,   # [cmid]
    w3: jax.Array,         # [cmid, cin]
    scale3: jax.Array, bias3: jax.Array,   # [cin]
    *,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """relu(x + bn3(conv1x1(relu(bn2(conv3x3(relu(bn1(conv1x1(x)))))))))
    with folded scale/bias norms, one image per grid step, everything
    between the input read and output write resident in VMEM."""
    n, hw, hw2, cin = x.shape
    assert hw == hw2, x.shape
    cmid = w1.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    s1 = jnp.stack([scale1, bias1]).astype(jnp.float32)   # [2, cmid]
    s2 = jnp.stack([scale2, bias2]).astype(jnp.float32)
    s3 = jnp.stack([scale3, bias3]).astype(jnp.float32)
    w2r = w2.reshape(9, cmid, cmid)

    kernel = functools.partial(
        _kernel, hw=hw, cin=cin, cmid=cmid, dot_dtype=jnp.bfloat16)
    return pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, hw, hw, cin), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((cin, cmid), lambda i: (0, 0)),
            pl.BlockSpec((2, cmid), lambda i: (0, 0)),
            pl.BlockSpec((9, cmid, cmid), lambda i: (0, 0, 0)),
            pl.BlockSpec((2, cmid), lambda i: (0, 0)),
            pl.BlockSpec((cmid, cin), lambda i: (0, 0)),
            pl.BlockSpec((2, cin), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hw, hw, cin), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x, w1, s1, w2r, s2, w3, s3)


@jax.custom_vjp
def fused_bottleneck_block(x, w1, scale1, bias1, w2, scale2, bias2,
                           w3, scale3, bias3):
    """Differentiable fused bottleneck: Pallas forward, XLA backward.

    The kernel has no Pallas backward; the VJP recomputes the block through
    ``reference_bottleneck`` (same math, compiler-scheduled) and uses ITS
    cotangents — forward-only fusion, rematerialized backward. Residuals are
    the primal inputs, so the fused path holds no extra activations between
    fwd and bwd (the remat trade the models already make per-block).
    """
    return fused_bottleneck(x, w1, scale1, bias1, w2, scale2, bias2,
                            w3, scale3, bias3)


def _fused_block_fwd(x, w1, scale1, bias1, w2, scale2, bias2, w3, scale3, bias3):
    out = fused_bottleneck(x, w1, scale1, bias1, w2, scale2, bias2,
                           w3, scale3, bias3)
    return out, (x, w1, scale1, bias1, w2, scale2, bias2, w3, scale3, bias3)


def _composite_f32(x, w1, scale1, bias1, w2, scale2, bias2, w3, scale3, bias3):
    """All-f32 twin of ``reference_bottleneck`` for the VJP: the mixed
    bf16-input/f32-accumulate convs the reference uses hit a conv-transpose
    dtype mismatch under ``jax.vjp``; a uniform-dtype composite transposes
    cleanly and gives f32-accurate cotangents."""
    conv = functools.partial(
        jax.lax.conv_general_dilated,
        window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    h1 = jnp.maximum(conv(x, w1[None, None]) * scale1 + bias1, 0.0)
    h2 = jnp.maximum(conv(h1, w2) * scale2 + bias2, 0.0)
    y = conv(h2, w3[None, None]) * scale3 + bias3
    return jnp.maximum(y + x, 0.0)


def _fused_block_bwd(residuals, g):
    primals_f32 = tuple(r.astype(jnp.float32) for r in residuals)
    _, vjp = jax.vjp(_composite_f32, *primals_f32)
    grads = vjp(g.astype(jnp.float32))
    return tuple(dr.astype(r.dtype) for dr, r in zip(grads, residuals))


fused_bottleneck_block.defvjp(_fused_block_fwd, _fused_block_bwd)


def reference_bottleneck(x, w1, scale1, bias1, w2, scale2, bias2,
                         w3, scale3, bias3):
    """The XLA composite the kernel must match (and beat): same math,
    scheduled by the compiler through HBM."""
    f32 = jnp.float32
    h1 = jax.lax.conv_general_dilated(
        x.astype(jnp.bfloat16), w1[None, None].astype(jnp.bfloat16),
        (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=f32)
    h1 = jnp.maximum(h1 * scale1 + bias1, 0.0)
    h2 = jax.lax.conv_general_dilated(
        h1.astype(jnp.bfloat16), w2.astype(jnp.bfloat16),
        (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=f32)
    h2 = jnp.maximum(h2 * scale2 + bias2, 0.0)
    y = jax.lax.conv_general_dilated(
        h2.astype(jnp.bfloat16), w3[None, None].astype(jnp.bfloat16),
        (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=f32)
    y = y * scale3 + bias3
    return jnp.maximum(y + x.astype(f32), 0.0).astype(x.dtype)
