"""Model serving: JAX/TPU inference behind the TF-Serving REST shape.

The reference's serving story is an e2e test POSTing to a TF Serving pod
(testing/test_tf_serving.py: /v1/models/<name>:predict, tolerance 1e-3).
Here serving is in-tree and TPU-native: an InferenceService CR + controller
(Deployment/Service materialization) and a JAX model server whose forward
is one jitted, batched call.
"""

from kubeflow_tpu.serving.server import ModelServer, ServedModel  # noqa: F401
from kubeflow_tpu.serving.continuous import ContinuousBatcher  # noqa: F401
from kubeflow_tpu.serving.controller import InferenceServiceReconciler  # noqa: F401
from kubeflow_tpu.serving.fleet import EngineFleet  # noqa: F401
from kubeflow_tpu.serving.router import FleetSaturated, PrefixRouter  # noqa: F401
from kubeflow_tpu.serving.autoscaler import (  # noqa: F401
    AutoscalerConfig,
    FederatedWindowSource,
    RegistryWindowSource,
    SLOAutoscaler,
)
