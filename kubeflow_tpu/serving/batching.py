"""Dynamic request batching: coalesce concurrent predicts into one forward.

TF Serving's batching layer is the reference-era analog (enable_batching in
the serving images the e2e drives); on TPU it matters more: a batch-1
forward wastes almost the whole MXU tile, so concurrent requests should
ride one padded executable. Mechanics:

- requests enqueue and block; one worker drains the queue,
- the worker waits up to ``max_wait_ms`` for more work (latency bound) or
  until ``max_batch`` rows accumulate (the largest serving bucket),
- one padded forward runs; each request gets exactly its rows back,
- a failed batch fails only the requests in it.

Shapes stay static: the combined batch pads to the same bucket ladder the
unbatched path uses (serving/server.py BATCH_BUCKETS), so no new XLA
compilations are introduced by batching.

When it pays: on hardware where dispatches serialize (a dedicated local
chip), N coalesced rows cost ~one dispatch instead of N. Measured on this
repo's tunneled/virtualized dev chip the proxy parallelizes concurrent
single-row dispatches, so batching does NOT win there — which is why it
stays opt-in (``ModelServer(batching=True)``) rather than default-on.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence

from ..runtime.metrics import METRICS
from .errors import DeadlineExceeded


class BatcherClosed(RuntimeError):
    """The batcher was shut down (model reload/unload) — retry unbatched."""


#: coalescing-window waits are ms-scale (max_wait_ms default 5) but a
#: busy queue can push them to seconds — same ladder as the engine's
QUEUE_WAIT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0,
                      60.0)


@dataclass
class _Pending:
    instances: Sequence[Any]
    shape_sig: Any  # (per-instance shape, dtype) — only like-shaped requests co-batch
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[List[Any]] = None
    error: Optional[BaseException] = None
    waited: bool = False  # sat through a full coalescing window already
    enqueued_at: float = field(default_factory=time.perf_counter)
    deadline: Optional[float] = None  # absolute time.monotonic(); None = none


class DynamicBatcher:
    """Wraps a ``predict(instances) -> results`` callable with coalescing.

    ``max_batch`` bounds the combined row count (use the model's largest
    batch bucket); ``max_wait_ms`` bounds added latency for the first
    request in a batch.
    """

    def __init__(
        self,
        predict_fn,
        max_batch: int = 64,
        max_wait_ms: float = 5.0,
        name: str = "model",
    ):
        self.predict_fn = predict_fn
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1000.0
        self.name = name
        self._lock = threading.Condition()
        self._queue: List[_Pending] = []
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, name=f"batcher-{name}", daemon=True
        )
        self._worker.start()

    # -- client side ---------------------------------------------------------
    @staticmethod
    def _signature(instances: Sequence[Any]):
        """Per-instance (shape, dtype); raises ValueError for ragged input so
        a malformed request fails ALONE, never inside someone else's batch.

        Returns ``None`` for object-dtype input (list-of-dict instances for
        models with a preprocess fn, or ragged nests numpy tolerates as
        object arrays): such requests have no usable structural signature,
        so co-batching them would let one malformed request fail strangers'
        requests — they serve unbatched instead."""
        import numpy as np

        arr = np.asarray(instances)  # raises on inhomogeneous shapes
        if arr.dtype == object:
            return None
        return arr.shape[1:], str(arr.dtype)

    def predict(self, instances: Sequence[Any],
                deadline: Optional[float] = None) -> List[Any]:
        """``deadline`` (absolute ``time.monotonic()``): an expired pending
        is shed from the queue without ever joining a forward, and the
        caller's wait is bounded by the deadline instead of being
        indefinite."""
        if len(instances) >= self.max_batch:
            # Oversized requests run alone — no point queueing behind them
            # (and no point paying for a signature they won't use).
            return self.predict_fn(instances)
        sig = self._signature(instances)
        if sig is None:
            # Unsignaturable (object-dtype) requests also run alone.
            return self.predict_fn(instances)
        pending = _Pending(instances, sig, deadline=deadline)
        with self._lock:
            if self._closed:
                raise BatcherClosed("batcher closed")
            self._queue.append(pending)
            self._lock.notify()
        timeout = None
        if deadline is not None:
            # grace past the deadline: an in-forward batch finishes and
            # returns real results rather than racing the shed
            timeout = max(0.0, deadline - time.monotonic()) + 1.0
        if not pending.done.wait(timeout):
            raise DeadlineExceeded("request missed its deadline in the "
                                   "batching queue")
        if pending.error is not None:
            raise pending.error
        return pending.result  # type: ignore[return-value]

    def close(self) -> None:
        with self._lock:
            self._closed = True
            # Wake EVERY condition waiter, not just one: with notify() the
            # single wakeup can land on a thread that re-waits (a future
            # multi-waiter worker, or a straggler mid-window) and the rest
            # sleep through shutdown.
            self._lock.notify_all()
        self._worker.join(timeout=5)
        # The worker drains the queue before exiting; if it died or the
        # join timed out (predict_fn wedged), fail the leftovers instead
        # of leaving their callers blocked on done.wait() forever.
        with self._lock:
            leftover, self._queue = self._queue, []
        for p in leftover:
            if not p.done.is_set():
                p.error = BatcherClosed("batcher closed before serving request")
                p.done.set()

    def drain(self, timeout: float = 60.0) -> None:
        """Graceful shutdown, distinct from ``close()``: stop admission
        (predict raises BatcherClosed) but let the worker SERVE everything
        already queued before it exits — close() instead fails leftovers.
        Safe to call close() afterwards (idempotent no-op)."""
        with self._lock:
            self._closed = True
            self._lock.notify_all()
        # the worker's loop exits only once the queue is empty
        # (_take_batch returns [] when closed AND drained), so a plain
        # join is the "finish in-flight" barrier
        self._worker.join(timeout=timeout)
        with self._lock:
            leftover, self._queue = self._queue, []
        for p in leftover:  # worker wedged past the timeout: fail, don't hang
            if not p.done.is_set():
                p.error = BatcherClosed("batcher drain timed out")
                p.done.set()

    # -- worker side ---------------------------------------------------------
    def _shed_expired_locked(self) -> None:
        """Fail queued pendings whose deadline passed — they never join a
        forward (fail fast, keep the batch for live requests). Caller
        holds the lock."""
        now = time.monotonic()
        live: List[_Pending] = []
        for p in self._queue:
            if p.deadline is not None and now >= p.deadline:
                METRICS.counter("serving_deadline_expired_total",
                                stage="queued").inc()
                p.error = DeadlineExceeded(
                    "deadline expired while queued for batching")
                p.done.set()
            else:
                live.append(p)
        self._queue = live

    def _take_batch(self) -> List[_Pending]:
        with self._lock:
            while True:
                self._shed_expired_locked()
                if self._queue:
                    break
                if self._closed:
                    return []
                self._lock.wait()
            # A head pending that already sat through a full window (left
            # over from a mixed-shape round) serves immediately; fresh
            # arrivals get the normal coalescing window.
            if not self._queue[0].waited:
                deadline = time.monotonic() + self.max_wait_s
                while True:
                    rows = sum(len(p.instances) for p in self._queue)
                    remaining = deadline - time.monotonic()
                    if rows >= self.max_batch or remaining <= 0 or self._closed:
                        break
                    self._lock.wait(remaining)
                for p in self._queue:
                    p.waited = True
            # Take like-shaped pendings only (mixed shapes cannot share one
            # array), up to max_batch rows. Every queued pending has
            # < max_batch rows, so this always takes at least one; other
            # shapes stay queued for the next round.
            batch: List[_Pending] = []
            rows = 0
            sig = self._queue[0].shape_sig
            remaining_queue: List[_Pending] = []
            for p in self._queue:
                if p.shape_sig == sig and rows + len(p.instances) <= self.max_batch:
                    batch.append(p)
                    rows += len(p.instances)
                else:
                    remaining_queue.append(p)
            self._queue = remaining_queue
            return batch

    def _run(self) -> None:
        while True:
            batch = self._take_batch()
            if not batch:
                return
            combined: List[Any] = []
            started = time.perf_counter()
            for p in batch:
                combined.extend(p.instances)
                # enqueue→forward-start wait: the coalescing window plus any
                # time spent queued behind other shapes
                METRICS.histogram(
                    "serving_batch_queue_wait_seconds",
                    buckets=QUEUE_WAIT_BUCKETS, model=self.name,
                ).observe(started - p.enqueued_at)
            try:
                results = self.predict_fn(combined)
                if len(results) != len(combined):
                    raise RuntimeError(
                        f"predict returned {len(results)} results for {len(combined)} rows"
                    )
                offset = 0
                for p in batch:
                    p.result = list(results[offset : offset + len(p.instances)])
                    offset += len(p.instances)
                METRICS.counter("serving_batches_total", model=self.name).inc()
                METRICS.histogram("serving_batch_rows", model=self.name).observe(len(combined))
            except BaseException as e:  # noqa: BLE001 — routed to callers
                for p in batch:
                    p.error = e
            finally:
                for p in batch:
                    p.done.set()
