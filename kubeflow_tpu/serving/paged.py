"""Paged KV-cache block accounting for the continuous-batching engine.

ISSUE 12: the per-slot contiguous KV cache reserved worst-case
``max_seq`` rows per slot whether a request used 20 tokens or 2000. The
paged layout keeps ONE shared arena of fixed-size blocks per layer
(``[n_blocks + 1, block_t, heads, head_dim]`` — the last row is the trash
block) and a host-side per-slot block table mapping absolute positions to
arena rows. This module owns the host-side half: the free-list allocator
that reserves capacity at admission and grants physical blocks as cursors
advance, published as ``serving_kv_blocks_{free,used}`` gauges so arena
sizing is an observable capacity knob rather than a silent OOM.

Two-phase accounting (reserve → grant) is deliberate:

- **reserve** happens at admission and covers the request's worst case
  (``ceil((prompt + budget) / block_t)`` blocks). Admission back-pressure
  is decided here: if the arena cannot promise the blocks, the request
  stays pending (:class:`KVBlocksExhausted` is a
  :class:`~kubeflow_tpu.serving.errors.FleetSaturated` so the HTTP layer's
  503/Retry-After mapping applies unchanged) — it never admits a request
  that could later need a block the arena cannot produce, so a granted
  write can never be redirected into another slot's data.
- **grant** happens just before each dispatch and only up to the cursor
  frontier that dispatch will reach. Until granted, the reserved blocks
  stay on the free list (they count against :meth:`available`, not the
  gauges), and the slot's table entries point at the trash block.

The device-side correctness contract lives in
``kubeflow_tpu/ops/kv_cache.py`` (trash-block convention) and
``serving/continuous.py`` (retire ordering: table row → trash BEFORE
blocks return to the free list, so stale in-flight dispatches write to
trash, never into a re-granted block).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from ..runtime.metrics import METRICS
from .errors import FleetSaturated


class KVBlocksExhausted(FleetSaturated):
    """The arena cannot reserve the blocks a request needs right now.

    Subclasses :class:`FleetSaturated` on purpose: exhaustion is admission
    back-pressure, not corruption — the engine keeps the request pending
    and retries as retirements return blocks, and if it must give up the
    HTTP layer already maps FleetSaturated to 503 + Retry-After.
    """


@dataclass
class KVReservation:
    """One slot's promised block budget: ``total`` blocks reserved, of
    which ``granted`` have been popped off the free list (in position
    order — ``granted[i]`` backs positions ``[i*block_t, (i+1)*block_t)``).
    """
    total: int
    granted: List[int] = field(default_factory=list)


class KVBlockAllocator:
    """LIFO free-list allocator over ``n_blocks`` arena rows.

    Row ``n_blocks`` (the arena's last row — callers allocate
    ``n_blocks + 1`` rows) is the trash block and is never handed out;
    :attr:`trash` exposes its id for table initialization.
    """

    def __init__(self, n_blocks: int, block_t: int, *, engine_id: str = "0"):
        if n_blocks <= 0:
            raise ValueError(f"need at least one KV block, got {n_blocks}")
        self.n_blocks = int(n_blocks)
        self.block_t = int(block_t)
        self.trash = self.n_blocks
        self.engine_id = engine_id
        self._free: List[int] = list(range(self.n_blocks))
        self._promised = 0  # reserved but not yet granted
        self._publish()

    # -- accounting ---------------------------------------------------------

    def available(self) -> int:
        """Blocks that can still be promised to new reservations."""
        return len(self._free) - self._promised

    def used(self) -> int:
        """Blocks physically granted (out of the free list)."""
        return self.n_blocks - len(self._free)

    def blocks_for(self, tokens: int) -> int:
        """Blocks needed to back ``tokens`` positions."""
        return -(-int(tokens) // self.block_t)

    # -- lifecycle ----------------------------------------------------------

    def reserve(self, n_blocks: int) -> KVReservation:
        """Promise ``n_blocks`` to one request or raise
        :class:`KVBlocksExhausted`. Impossible requests (bigger than the
        whole arena) raise ValueError — waiting would never help."""
        if n_blocks > self.n_blocks:
            raise ValueError(
                f"request needs {n_blocks} KV blocks but the arena only has "
                f"{self.n_blocks}; raise kv_blocks or shrink the request")
        if n_blocks > self.available():
            raise KVBlocksExhausted(
                f"KV arena exhausted: need {n_blocks} blocks, "
                f"{self.available()} available of {self.n_blocks}",
                retry_after_s=0.05)
        self._promised += n_blocks
        return KVReservation(total=n_blocks)

    def grant(self, res: KVReservation, upto_blocks: int) -> List[int]:
        """Materialize the reservation up to ``upto_blocks`` granted blocks
        (capped at ``res.total``); returns only the newly granted ids, in
        position order."""
        upto_blocks = min(upto_blocks, res.total)
        newly: List[int] = []
        while len(res.granted) < upto_blocks:
            blk = self._free.pop()
            self._promised -= 1
            res.granted.append(blk)
            newly.append(blk)
        if newly:
            self._publish()
        return newly

    def release(self, res: KVReservation) -> None:
        """Return a reservation's blocks (granted and promised) to the
        free list. The caller MUST have redirected the slot's table row to
        trash before calling this (retire ordering invariant)."""
        self._free.extend(res.granted)
        self._promised -= res.total - len(res.granted)
        res.granted = []
        res.total = 0
        self._publish()

    def _publish(self) -> None:
        METRICS.gauge("serving_kv_blocks_free",
                      replica=self.engine_id).set(len(self._free))
        METRICS.gauge("serving_kv_blocks_used",
                      replica=self.engine_id).set(self.used())
