"""JAX model server: the TF-Serving-compatible predict surface.

API shape (what testing/test_tf_serving.py drives):
    POST /v1/models/<name>:predict   {"instances": [...]}
    ->                               {"predictions": [...]}
    GET  /v1/models/<name>           status/metadata

TPU-first serving decisions:
- ONE jitted forward per (model, padded batch-size bucket); requests are
  padded to the next bucket so XLA never sees a new shape (no recompiles
  in steady state, static shapes on the MXU),
- bf16 weights with f32 outputs, batch dimension sharded over the mesh
  batch axes when a mesh is configured.
"""

from __future__ import annotations

import math
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime.metrics import METRICS
from ..web.http import App, HttpError, Request
from .errors import DeadlineExceeded, FleetSaturated

BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)

#: per-request budget when the client sends neither the
#: ``X-Request-Deadline-Ms`` header nor a ``timeout_ms`` body field —
#: matches the old hard-coded ``result(timeout=600)`` ceiling
DEFAULT_DEADLINE_MS = 600_000.0

#: extra wait past the deadline for the engine to reap an expired slot
#: and hand back the partial tokens (reaping happens within ~one decode
#: chunk; the grace also covers event-pipeline fetch latency)
DEADLINE_GRACE_S = 5.0


def request_deadline_opts(req: Request, body: Any) -> Tuple[float, str]:
    """(absolute monotonic deadline, priority) for one predict request.

    The ``X-Request-Deadline-Ms`` header wins over the body's
    ``timeout_ms`` field; both express a RELATIVE budget in milliseconds
    from arrival. Zero/negative budgets are legal and expire immediately
    (an upstream that already blew its own deadline should get the 504
    without costing this server a slot). Priority comes from the body's
    ``priority`` field or the ``X-Request-Priority`` header."""
    raw: Any = req.header("x-request-deadline-ms") or None
    if raw is None and isinstance(body, dict):
        raw = body.get("timeout_ms")
    try:
        ms = float(raw) if raw is not None else DEFAULT_DEADLINE_MS
    except (TypeError, ValueError):
        raise HttpError(400, f"bad deadline {raw!r}: expected milliseconds") \
            from None
    priority = ""
    if isinstance(body, dict):
        priority = str(body.get("priority") or "")
    priority = priority or req.header("x-request-priority") or "interactive"
    if priority not in ("interactive", "batch"):
        raise HttpError(
            400, f"priority {priority!r}: expected 'interactive' or 'batch'")
    return time.monotonic() + ms / 1000.0, priority


def retry_after_headers(e: FleetSaturated) -> Dict[str, str]:
    """``Retry-After`` from the router's queue-drain hint (whole seconds,
    minimum 1 — the header's unit)."""
    hint = e.retry_after_s if e.retry_after_s else 1.0
    return {"Retry-After": str(max(1, int(math.ceil(hint))))}


@dataclass
class ServedModel:
    """One deployable model: a pure ``apply(params, batch) -> out`` pair."""

    name: str
    apply_fn: Callable[[Any, jax.Array], jax.Array]
    params: Any
    input_dtype: Any = jnp.float32
    version: str = "1"
    # Optional preprocessing: raw JSON instances -> np.ndarray batch.
    preprocess: Optional[Callable[[Sequence[Any]], np.ndarray]] = None
    _compiled: Dict[int, Callable] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def _fn_for_bucket(self, bucket: int) -> Callable:
        with self._lock:
            if bucket not in self._compiled:
                self._compiled[bucket] = jax.jit(self.apply_fn)
            return self._compiled[bucket]

    def predict(self, instances: Sequence[Any]) -> List[Any]:
        if not instances:
            return []
        if self.preprocess is not None:
            batch = np.asarray(self.preprocess(instances))
        else:
            batch = np.asarray(instances, dtype=np.dtype(jnp.dtype(self.input_dtype).name))
        n = batch.shape[0]
        bucket = next((b for b in BATCH_BUCKETS if b >= n), None)
        if bucket is None:
            raise HttpError(413, f"batch of {n} exceeds max {BATCH_BUCKETS[-1]}")
        if bucket != n:
            pad = np.repeat(batch[:1], bucket - n, axis=0)
            batch = np.concatenate([batch, pad], axis=0)
        fn = self._fn_for_bucket(bucket)
        out = np.asarray(fn(self.params, jnp.asarray(batch)))
        return out[:n].tolist()


class ModelServer:
    """Hosts ServedModels over the predict API; servable with app.serve().

    ``batching=True`` coalesces concurrent requests per model into one
    padded forward (serving/batching.py) — the TPU-shaped default for
    production; off by default so single-request paths stay trivial."""

    def __init__(self, batching: bool = False, max_batch: int = BATCH_BUCKETS[-1],
                 max_wait_ms: float = 5.0):
        if max_batch > BATCH_BUCKETS[-1]:
            # A combined batch above the largest serving bucket would 413 on
            # every co-batched request.
            raise ValueError(f"max_batch {max_batch} exceeds largest bucket {BATCH_BUCKETS[-1]}")
        self.models: Dict[str, ServedModel] = {}
        self.app = App("model-server")
        self._batching = batching
        self._max_batch = max_batch
        self._max_wait_ms = max_wait_ms
        self._batchers: Dict[str, "DynamicBatcher"] = {}
        self._register_routes()
        # /metrics + /debug/traces + /debug/vars on the serving port itself:
        # the SLO histograms live in this process, so the scrape must too
        from ..runtime.obs import mount_observability

        mount_observability(self.app)

    def add(self, model: ServedModel) -> "ModelServer":
        self.models[model.name] = model
        if self._batching:
            from .batching import DynamicBatcher

            old = self._batchers.pop(model.name, None)
            if old is not None:
                old.close()  # model reload: stop the old worker, release params
            self._batchers[model.name] = DynamicBatcher(
                model.predict,
                max_batch=self._max_batch,
                max_wait_ms=self._max_wait_ms,
                name=model.name,
            )
        return self

    def _predict(self, model: ServedModel, instances,
                 deadline: Optional[float] = None,
                 priority: str = "interactive",
                 model_id: Optional[str] = None) -> List[Any]:
        from .batching import BatcherClosed

        batcher = self._batchers.get(model.name)
        if batcher is not None:
            try:
                return batcher.predict(instances, deadline=deadline)
            except BatcherClosed:
                # Model hot-reload raced this request: the batcher we fetched
                # was closed by add(). Serve directly — correctness over
                # coalescing for the handful of in-flight requests.
                pass
        if isinstance(model, GenerativeModel):
            return model.predict(instances, deadline=deadline,
                                 priority=priority, model=model_id)
        return model.predict(instances)

    def close(self) -> None:
        for b in self._batchers.values():
            b.close()

    def _model(self, name: str) -> ServedModel:
        model = self.models.get(name)
        if model is None:
            raise HttpError(404, f"model {name!r} not loaded")
        return model

    def _register_routes(self) -> None:
        app = self.app

        @app.route("/healthz")
        def healthz(req: Request):
            return {"status": "ok", "models": sorted(self.models)}

        @app.route("/v1/models/<name>")
        def model_status(req: Request):
            model = self._model(req.params["name"])
            return {
                "model_version_status": [
                    {"version": model.version, "state": "AVAILABLE", "status": {"error_code": "OK"}}
                ]
            }

        @app.route("/v1/models/<name>:predict", methods=("POST",))
        def predict(req: Request):
            model = self._model(req.params["name"])
            body = req.json or {}
            instances = body.get("instances")
            if instances is None:
                raise HttpError(400, "body must carry 'instances'")
            deadline, priority = request_deadline_opts(req, body)
            # multiplexed servables route on the body's "model" id
            model_id = body.get("model") if isinstance(body, dict) else None

            t0 = time.perf_counter()
            try:
                predictions = self._predict(model, instances,
                                            deadline=deadline,
                                            priority=priority,
                                            model_id=model_id)
            except HttpError:
                raise
            except DeadlineExceeded as e:
                METRICS.counter("serving_predict_total", model=model.name, result="error").inc()
                raise HttpError(504, f"deadline exceeded: {e}") from None
            except Exception as e:
                METRICS.counter("serving_predict_total", model=model.name, result="error").inc()
                raise HttpError(400, f"inference failed: {e}") from None
            METRICS.counter("serving_predict_total", model=model.name, result="success").inc()
            METRICS.histogram("serving_predict_seconds", model=model.name).observe(
                time.perf_counter() - t0
            )
            return {"predictions": predictions}

    def serve(self, port: int = 0):
        return self.app.serve(port)


@dataclass
class GenerativeModel(ServedModel):
    """Serves autoregressive generation through the predict surface:
    instances = equal-length token-id prompts, predictions = full generated
    sequences. Decoding manages its own compilation cache (models/gpt.py
    generate), so the bucket-jit path is bypassed.

    ``continuous=True`` (the default since round 5) routes requests
    through the slot-based continuous-batching engine
    (serving/continuous.py): concurrent HTTP requests share one running
    decode batch, each sequence retiring at its own budget instead of the
    batch's max (VERDICT r3 #8). Sampling rides per-slot temperatures and
    keys inside the shared batch. Round 5's pipelined engine measures at
    0.9-1.1x the OFFLINE static oracle's tokens/s with consistently lower
    mean request latency on the mixed-budget bench
    (e2e/serving_bench.py:bench_continuous) — and online it needs no
    oracle grouping, so it is the right default. ``continuous=False``
    falls back to lockstep bucketed generate(); prompts longer than the
    engine's largest prefill bucket take that static path automatically,
    so the servable prompt range stays cfg.max_seq."""

    cfg: Any = None
    max_new_tokens: int = 16
    temperature: float = 0.0
    continuous: bool = True
    slots: int = 8
    #: >1 builds an EngineFleet (serving/fleet.py) instead of a single
    #: engine: prefix-aware routing + drain/handoff across N replicas
    replicas: int = 1
    #: autoscaler headroom; None pins the fleet at ``replicas``
    max_replicas: Optional[int] = None
    # -- ISSUE-12 engine knobs (docs/SERVING.md has the full table) --------
    #: paged (block-arena) KV layout; False keeps the contiguous parity path
    paged: bool = True
    #: allocatable arena blocks (None = contiguous-capacity parity)
    kv_blocks: Optional[int] = None
    #: requested arena tile (auto-shrunk to divide max_seq + the buckets)
    kv_block_t: int = 16
    #: chunked-prefill budget (None = largest prefill bucket; 0 disables —
    #: over-bucket prompts then fall back to the static generate() path)
    prefill_chunk: Optional[int] = None
    #: (draft_cfg, draft_params) enables speculative decoding
    spec_draft: Optional[Any] = None
    spec_k: int = 4
    # -- ISSUE-18 disaggregation knobs -------------------------------------
    #: KV arena storage precision: "bf16" (bit-parity ground truth) or
    #: "int8" (2x KV positions per HBM byte, tested logit tolerance)
    kv_dtype: str = "bf16"
    #: role pools for a disaggregated fleet, e.g. {"prefill": 1,
    #: "decode": 2}; None keeps homogeneous replicas
    pools: Optional[Dict[str, int]] = None
    #: model_id -> (cfg, params): multiplex several models over one fleet;
    #: requests pick one via the body's "model" field
    mux_models: Optional[Dict[str, Any]] = None
    #: model_id -> default admission class ("interactive"/"batch")
    model_slo: Optional[Dict[str, str]] = None

    def __post_init__(self):
        # Per-request sampling state: a base key seeded from OS entropy folded
        # with a monotone counter gives distinct draws per request without
        # re-seeding numpy/jax global state.
        self._rng_lock = threading.Lock()
        self._rng_counter = 0
        self._base_rng = jax.random.PRNGKey(
            int.from_bytes(os.urandom(4), "little")
        )
        self._engine = None
        self._engine_lock = threading.Lock()

    def _wants_fleet(self) -> bool:
        # pools and multiplexing are fleet-level concepts; a single engine
        # only exists for the plain one-replica case
        return bool(self.replicas > 1 or self.max_replicas or self.pools
                    or self.mux_models)

    def _continuous_engine(self):
        from .continuous import ContinuousBatcher

        engine_kwargs = dict(paged=self.paged, kv_blocks=self.kv_blocks,
                             kv_block_t=self.kv_block_t,
                             prefill_chunk=self.prefill_chunk,
                             spec_draft=self.spec_draft, spec_k=self.spec_k,
                             kv_dtype=self.kv_dtype)
        with self._engine_lock:
            if self._engine is None:
                if self._wants_fleet():
                    from .fleet import EngineFleet

                    self._engine = EngineFleet(
                        self.cfg, self.params, replicas=self.replicas,
                        max_replicas=self.max_replicas or max(self.replicas, 1),
                        slots=self.slots, name=self.name,
                        pools=self.pools, models=self.mux_models,
                        model_slo=self.model_slo,
                        engine_kwargs=engine_kwargs)
                else:
                    self._engine = ContinuousBatcher(self.cfg, self.params,
                                                     slots=self.slots,
                                                     **engine_kwargs)
            return self._engine

    def close(self) -> None:
        # Swap under the lock (close() racing _continuous_engine() must not
        # orphan a freshly-built engine), shut down outside it: engine close
        # joins worker threads and must not stall new-engine construction.
        with self._engine_lock:
            engine, self._engine = self._engine, None
        if engine is not None:
            engine.close()

    def predict(self, instances: Sequence[Any],
                deadline: Optional[float] = None,
                priority: str = "interactive",
                model: Optional[str] = None) -> List[Any]:
        from kubeflow_tpu.models.gpt import generate

        if not instances:
            return []
        if model and not self.mux_models:
            raise HttpError(400, f"servable {self.name!r} does not "
                                 "multiplex models")
        if self.mux_models and not model:
            raise HttpError(400, "body must carry 'model': this servable "
                                 f"multiplexes {sorted(self.mux_models)}")
        if deadline is None:
            # direct callers (tests, DynamicBatcher) get the server default
            deadline = time.monotonic() + DEFAULT_DEADLINE_MS / 1000.0
        prompts = np.asarray(instances, dtype=np.int32)
        if prompts.ndim != 2:
            raise HttpError(400, "instances must be equal-length token-id lists")
        from .continuous import PREFILL_BUCKETS

        # client errors must surface as 4xx BEFORE anything is enqueued or
        # compiled (a mid-listcomp failure would abandon submitted futures;
        # the static path's generate() would turn this into a 500)
        if prompts.shape[1] + self.max_new_tokens > self.cfg.max_seq:
            raise HttpError(413, "prompt + generation budget exceeds max_seq")
        # prompts longer than the engine's largest prefill bucket: chunked
        # prefill (ISSUE 12) serves them through the engine when enabled —
        # effective_prefill_chunk here MUST mirror the engine's own
        # resolution so routing and admission agree; when disabled they
        # take the static generate() path instead of erroring (flipping
        # continuous on must not shrink the servable prompt range below
        # cfg.max_seq — review finding, round 5)
        from .continuous import _block_tile, effective_prefill_chunk

        chunk = effective_prefill_chunk(
            self.prefill_chunk, self.cfg.max_seq,
            _block_tile(self.cfg.max_seq, self.kv_block_t)
            if self.paged else 1)
        if self.continuous and (prompts.shape[1] <= PREFILL_BUCKETS[-1]
                                or chunk > 0):
            from ..runtime.tracing import TRACER, format_traceparent

            eng = self._continuous_engine()
            # hand the engine our trace context: when this runs inside the
            # HTTP dispatch span, every serving.request span parents there
            # (continuing the client's traceparent if one came in)
            cur = TRACER.current_span()
            tp = format_traceparent(cur) if cur is not None else None
            futs: List[Any] = []
            # a multiplexed model's SLO class is deployment policy, not a
            # client choice: it overrides whatever the request asked for
            if model and self.model_slo and model in self.model_slo:
                priority = self.model_slo[model]
            submit_kw: Dict[str, Any] = (
                {"model": model or ""} if self._wants_fleet() else {})
            try:
                for row in prompts:
                    futs.append(eng.submit(row, self.max_new_tokens,
                                           temperature=self.temperature,
                                           traceparent=tp,
                                           deadline=deadline,
                                           priority=priority,
                                           **submit_kw))
                out = []
                for row, f in zip(prompts, futs):
                    # the wait derives from the request's own deadline: at
                    # expiry the engine reaps the slot and completes the
                    # future with the partial tokens (grace covers the reap)
                    remaining = max(0.0, deadline - time.monotonic())
                    out.append(row.tolist()
                               + f.result(timeout=remaining + DEADLINE_GRACE_S))
                return out
            except FleetSaturated as e:
                raise HttpError(503, f"fleet saturated: {e}",
                                headers=retry_after_headers(e)) from e
            except ValueError as e:
                # structurally unservable request (e.g. prompt + budget
                # needs more KV blocks than the arena holds): the client's
                # fault, so 400 — never a 500 (ISSUE 12 satellite)
                raise HttpError(400, str(e)) from e
            except DeadlineExceeded as e:
                raise HttpError(504, f"deadline exceeded: {e}") from e
            except TimeoutError as e:
                # engine wedged past deadline + grace — same contract as a
                # deadline miss, the slot reap just never surfaced
                raise HttpError(504, f"deadline exceeded: {e}") from e
            except RuntimeError as e:
                raise HttpError(503, f"decode engine unavailable: {e}") from e
            finally:
                # this handler is the requests' only consumer: anything not
                # finished when we unwind is abandoned — cancel so the
                # engine frees the slots instead of decoding for nobody
                for f in futs:
                    if not f.done.is_set():
                        f.cancel()
        # Batch-bucket like ServedModel.predict: arbitrary client batch
        # sizes must not mint unbounded XLA compilations.
        n = prompts.shape[0]
        bucket = next((b for b in BATCH_BUCKETS if b >= n), None)
        if bucket is None:
            raise HttpError(413, f"batch of {n} exceeds max {BATCH_BUCKETS[-1]}")
        if bucket != n:
            prompts = np.concatenate([prompts, np.repeat(prompts[:1], bucket - n, axis=0)])
        # Temperature sampling needs a fresh key per request — a fixed key
        # would return the identical sample for identical prompts.
        rng = None
        if self.temperature > 0.0:
            with self._rng_lock:
                self._rng_counter += 1
                counter = self._rng_counter
            # fold_in dispatches device work — keep it outside the lock so
            # concurrent sampled requests don't serialize on it.
            rng = jax.random.fold_in(self._base_rng, counter)
        out = generate(
            self.cfg,
            self.params,
            jnp.asarray(prompts),
            self.max_new_tokens,
            rng=rng,
            temperature=self.temperature,
        )
        return np.asarray(out)[:n].tolist()


def gpt_served_model(
    name: str = "gpt",
    tiny: bool = True,
    max_new_tokens: int = 16,
    temperature: float = 0.0,
    replicas: int = 1,
) -> GenerativeModel:
    """GPT text-generation servable (``tiny`` for CPU CI; ``tiny=False``
    builds the GPT-2-small-class config). ``replicas`` > 1 serves through
    an EngineFleet instead of a single engine."""
    from kubeflow_tpu.models.gpt import GptConfig, GptLM

    cfg = GptConfig.tiny() if tiny else GptConfig.small()
    sample = jnp.zeros((1, 8), jnp.int32)
    params = GptLM(cfg).init(jax.random.PRNGKey(0), sample)["params"]
    return GenerativeModel(
        name=name,
        apply_fn=None,
        params=params,
        cfg=cfg,
        max_new_tokens=max_new_tokens,
        temperature=temperature,
        replicas=replicas,
    )


def bert_served_model(name: str = "bert", tiny: bool = True) -> ServedModel:
    """BERT MLM logits server (the BASELINE 'tf-serving -> JAX BERT' config).

    ``tiny=True`` for CPU CI; ``tiny=False`` builds BERT-base for real
    serving on a chip.
    """
    from kubeflow_tpu.models import BertConfig, BertForMaskedLM

    cfg = BertConfig.tiny() if tiny else BertConfig.base()
    model = BertForMaskedLM(cfg)
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1, 16), jnp.int32)
    params = model.init(rng, sample)["params"]

    def apply_fn(p, ids):
        return model.apply({"params": p}, ids)

    return ServedModel(name=name, apply_fn=apply_fn, params=params, input_dtype=jnp.int32)


def main() -> None:
    """``python -m kubeflow_tpu.serving.server`` — the model-server image
    CMD. The InferenceService controller materializes ``spec.replicas``
    as the ``FLEET_REPLICAS`` env / ``--replicas`` arg, which sizes the
    in-process engine fleet here."""
    import argparse

    from ..runtime.bootstrap import block_forever

    parser = argparse.ArgumentParser(description="JAX model server")
    parser.add_argument("--model",
                        default=os.environ.get("MODEL_NAME", "gpt"))
    parser.add_argument("--port", type=int,
                        default=int(os.environ.get("SERVING_PORT", "8500")))
    parser.add_argument("--replicas", type=int,
                        default=int(os.environ.get("FLEET_REPLICAS", "1")))
    args = parser.parse_args()

    server = ModelServer()
    if args.model == "bert":
        server.add(bert_served_model(name=args.model))
    else:
        server.add(gpt_served_model(name=args.model,
                                    replicas=args.replicas))
    httpd = server.serve(args.port)
    print(f"model-server: {args.model!r} on :{httpd.port} "
          f"(fleet replicas={args.replicas})", flush=True)
    try:
        block_forever()
    finally:
        httpd.close()
        server.close()


if __name__ == "__main__":
    main()
