"""KV wire format — how a prefilled request moves between replica pools.

A prefill-pool replica (serving/continuous.py ``role="prefill"``) runs the
prompt's compute-bound phase, then ships the resulting KV state to a
decode-pool replica as ONE self-describing blob. Framing follows the PR 7
canonical per-layer checkpoint: a JSON manifest naming every array (dtype,
shape, crc32, byte count) followed by the raw buffers in manifest order —
crc-verified on import, so a truncated or corrupted handoff fails loudly on
the importer's thread instead of poisoning a decode arena.

Layout per request::

    b"KVW1" | u32 manifest_len | manifest JSON (utf-8) | payload bytes

Arrays are BLOCK-shaped: ``block_{i}/k`` and ``block_{i}/v`` are
``[nb, block_t, heads, head_dim]`` where ``nb = ceil(prompt_len /
block_t)`` — exactly the granted-block span a decode replica scatters into
its arena (serving/paged.py). Positions past ``prompt_len`` inside the
last block carry prefill-padding garbage; the attention mask hides them
until decode overwrites, the same contract as never-moved adoption. With
``kv_dtype="int8"`` the values ship PRE-QUANTIZED (``block_{i}/k_scale`` /
``v_scale`` ride alongside, ``[nb, block_t, heads, 1]`` f32): the importer
scatters bytes without re-quantizing, so a moved request's arena blocks
are byte-identical to a never-moved request's — the handoff parity
contract in tests/test_fleet.py.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Any, Dict, Tuple

import numpy as np

MAGIC = b"KVW1"
WIRE_VERSION = 1


def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _dtype_name(arr: np.ndarray) -> str:
    # ml_dtypes.bfloat16 prints as "bfloat16" already; keep numpy names
    # for everything else
    return arr.dtype.name


def pack(meta: Dict[str, Any], arrays: Dict[str, np.ndarray]) -> bytes:
    """Frame ``arrays`` (name -> ndarray, insertion order preserved) behind
    a manifest carrying ``meta`` plus per-array dtype/shape/crc32."""
    entries = []
    payload = bytearray()
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        buf = arr.tobytes()
        entries.append({
            "name": name,
            "dtype": _dtype_name(arr),
            "shape": list(arr.shape),
            "nbytes": len(buf),
            "crc32": zlib.crc32(buf) & 0xFFFFFFFF,
        })
        payload.extend(buf)
    manifest = dict(meta)
    manifest["version"] = WIRE_VERSION
    manifest["arrays"] = entries
    mbytes = json.dumps(manifest, sort_keys=True).encode("utf-8")
    return MAGIC + struct.pack("<I", len(mbytes)) + mbytes + bytes(payload)


def unpack(blob: bytes) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """Inverse of :func:`pack`; verifies the magic, framing, and every
    array's crc32. Raises ``ValueError`` on any mismatch."""
    if len(blob) < len(MAGIC) + 4 or blob[:len(MAGIC)] != MAGIC:
        raise ValueError("not a KV wire blob (bad magic)")
    (mlen,) = struct.unpack_from("<I", blob, len(MAGIC))
    mstart = len(MAGIC) + 4
    if len(blob) < mstart + mlen:
        raise ValueError("truncated KV wire manifest")
    manifest = json.loads(blob[mstart:mstart + mlen].decode("utf-8"))
    if manifest.get("version") != WIRE_VERSION:
        raise ValueError(f"KV wire version {manifest.get('version')!r} "
                         f"(expected {WIRE_VERSION})")
    arrays: Dict[str, np.ndarray] = {}
    off = mstart + mlen
    for e in manifest["arrays"]:
        buf = blob[off:off + e["nbytes"]]
        if len(buf) != e["nbytes"]:
            raise ValueError(f"truncated KV wire payload at {e['name']!r}")
        if (zlib.crc32(buf) & 0xFFFFFFFF) != e["crc32"]:
            raise ValueError(f"crc mismatch for {e['name']!r}")
        arrays[e["name"]] = np.frombuffer(
            buf, dtype=_np_dtype(e["dtype"])).reshape(e["shape"])
        off += e["nbytes"]
    if off != len(blob):
        raise ValueError("trailing bytes after KV wire payload")
    return manifest, arrays


def export_kv(row_cache: Dict[str, Any], *, prompt_len: int, block_t: int,
              kv_dtype: str, first_token: int, model_id: str = "") -> bytes:
    """Export ONE prefilled request's KV to the wire.

    ``row_cache``: ``{"block_{i}": {"k": [max_seq, h, d], "v": ...}}`` —
    one contiguous prefill-cache row per layer (bf16, host or device).
    Truncates to whole blocks covering the prompt, reshapes block-wise,
    and (int8) quantizes with the SAME compiled ``quantize_kv_jit`` the
    decode engine's adoption path uses — bit-identical quantization is what
    makes moved-vs-never-moved arenas byte-identical.
    """
    if block_t <= 0:
        raise ValueError("export_kv needs a positive block_t")
    nb = -(-int(prompt_len) // int(block_t))
    arrays: Dict[str, np.ndarray] = {}
    for name, layer in row_cache.items():
        k = np.asarray(layer["k"])[:nb * block_t]
        v = np.asarray(layer["v"])[:nb * block_t]
        h, d = k.shape[-2], k.shape[-1]
        k = k.reshape(nb, block_t, h, d)
        v = v.reshape(nb, block_t, h, d)
        if kv_dtype == "int8":
            # The jitted quantizer, NOT the eager one: the decode engine's
            # adoption path quantizes under jit, and eager quantize drifts
            # by 1 ULP in scale — enough to flip codes at rounding
            # boundaries and break moved-vs-never-moved byte parity.
            from ..ops.kv_cache import quantize_kv_jit

            kq, ks = quantize_kv_jit(k)
            vq, vs = quantize_kv_jit(v)
            arrays[f"{name}/k"] = np.asarray(kq)
            arrays[f"{name}/v"] = np.asarray(vq)
            arrays[f"{name}/k_scale"] = np.asarray(ks)
            arrays[f"{name}/v_scale"] = np.asarray(vs)
        else:
            arrays[f"{name}/k"] = k
            arrays[f"{name}/v"] = v
    meta = {
        "prompt_len": int(prompt_len),
        "block_t": int(block_t),
        "kv_dtype": str(kv_dtype),
        "first_token": int(first_token),
        "model_id": str(model_id),
        "n_layers": len(row_cache),
    }
    return pack(meta, arrays)


def unpack_kv(blob: bytes) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """Verify and parse a KV wire blob (alias of :func:`unpack` with the
    export_kv manifest fields guaranteed present)."""
    manifest, arrays = unpack(blob)
    for field in ("prompt_len", "block_t", "kv_dtype", "first_token"):
        if field not in manifest:
            raise ValueError(f"KV wire manifest missing {field!r}")
    return manifest, arrays
