"""Serving fleet: N engine replicas behind one routing plane (ISSUE 6).

The Podracer architectures paper (PAPERS.md) frames replicas as cheap,
preemptible, re-schedulable gangs; here each replica is

- one :class:`~kubeflow_tpu.serving.continuous.ContinuousBatcher`
  engine (its gauges labeled ``replica=<id>``), and
- optionally one Pod registered through the gang scheduler
  (``scheduling.kubeflow.org/pod-group`` of size 1 requesting the
  replica's chips), so the chip ledger, quota, and priority preemption
  apply to serving capacity exactly as they do to training gangs.

The fleet composes the other two ISSUE-6 modules:

- :class:`~kubeflow_tpu.serving.router.PrefixRouter` picks a replica per
  request (warm-prefix affinity, least-loaded fallback, 503 when
  saturated),
- :class:`~kubeflow_tpu.serving.autoscaler.SLOAutoscaler` calls
  ``scale_to`` from windowed TTFT/queue-wait quantiles.

Drain/handoff: ``drain_replica`` flips the replica out of the routing
set, lets its engine finish in-flight slots (``ContinuousBatcher.drain``),
then re-submits the unserved pendings to survivors — the ORIGINAL request
futures stay valid (a bridge thread copies the survivor's result back),
so callers blocked in ``result()`` never see the drain. With a client
attached, a watcher thread notices the scheduler preempting/deleting a
replica's pod and runs the same drain, then re-creates the pod so the
replica re-enters the scheduling queue.

``EngineFleet.submit`` mirrors ``ContinuousBatcher.submit`` so
``GenerativeModel`` can use either interchangeably.

Disaggregation (ISSUE 18): ``pools={"prefill": p, "decode": d}`` splits
the fleet by phase — requests enter through prefill specialists
(``role="prefill"`` engines), which ship the finished KV state over the
wire format (serving/kv_wire.py) to the fleet's handoff sink; the sink
routes each blob to the least-loaded same-model decode replica via
``submit_handoff``. A long prompt therefore never occupies a decode slot
during its compute-bound phase. ``models={model_id: (cfg, params)}``
multiplexes several models over the same pools: every pool holds its
per-model target count of replicas, routing is scoped to same-role
same-model handles, and ``model_slo`` maps each model to its default
admission class (the PR 9 two-class reserve).
"""

from __future__ import annotations

import collections
import inspect
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..runtime.metrics import METRICS
from ..runtime.obs import register_debug_source
from ..runtime.tracing import TRACER
from .errors import DeadlineExceeded, FleetSaturated
from .router import PrefixRouter

#: drain wall time is dominated by the slowest in-flight request — seconds
#: scale, with headroom for a replica finishing a long budget
DRAIN_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                 120.0)

#: replica created → first routable (pod bind + any prewarm): the ROADMAP
#: item-5 baseline SLI, so the ladder reaches from in-process fakes
#: (milliseconds) to real weight-loading cold starts (minutes)
COLD_START_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                      60.0, 120.0)

#: how long a handoff bridge waits on the survivor when the request
#: carries NO deadline (deadline-bearing requests wait out their own
#: remaining budget instead)
BRIDGE_TIMEOUT_S = 600.0

#: ceiling for the pod watcher's crash-restart backoff
WATCHER_BACKOFF_CAP_S = 5.0

#: breaker gauge encoding for ``fleet_breaker_state{replica}``
BREAKER_STATE_CODES = {"closed": 0, "open": 1, "half_open": 2}

LOG = logging.getLogger(__name__)


class ReplicaBreaker:
    """Per-replica circuit breaker (closed → open → half_open → closed).

    ``record_failure`` counts CONSECUTIVE bad outcomes (errors, deadline
    expiries — a slow replica shows up as deadline expiries, so slowness
    trips the breaker the same way crashes do); at ``failure_threshold``
    the breaker opens and ``allow()`` refuses the replica for ``open_s``
    seconds. The first ``allow()`` after that window admits exactly ONE
    probe (half_open); the probe's outcome closes or re-opens it.
    ``clock`` is injectable so tests drive the state machine without
    sleeping.
    """

    def __init__(self, failure_threshold: int = 3, open_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = max(1, int(failure_threshold))
        self.open_s = float(open_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_at = 0.0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def state_code(self) -> int:
        return BREAKER_STATE_CODES[self.state]

    def allow(self) -> bool:
        """May a request route to this replica right now? Transitions
        open → half_open (admitting the single probe) once ``open_s`` has
        elapsed; half_open refuses everything while the probe is out. A
        probe whose outcome never arrives (the admitting caller routed
        elsewhere, or the request vanished) is presumed lost after another
        ``open_s`` and a fresh probe is admitted — the breaker must never
        wedge half_open forever."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if self._clock() - self._opened_at >= self.open_s:
                    self._state = "half_open"
                    self._probe_at = self._clock()
                    return True  # this caller IS the probe
                return False
            # half_open: one probe at a time, re-issued if presumed lost
            if self._clock() - self._probe_at >= self.open_s:
                self._probe_at = self._clock()
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._state = "closed"

    def record_failure(self) -> None:
        with self._lock:
            if self._state == "half_open":
                # the probe failed — straight back to open, fresh window
                self._state = "open"
                self._opened_at = self._clock()
                return
            self._consecutive_failures += 1
            if (self._state == "closed"
                    and self._consecutive_failures >= self.failure_threshold):
                self._state = "open"
                self._opened_at = self._clock()


class RetryBudget:
    """Token bucket bounding fleet-level retries: every first submission
    deposits ``ratio`` tokens (capped), every retry withdraws one — so the
    sustained retry rate can't exceed ``ratio`` × the request rate and a
    sick fleet can't retry-storm itself into the ground. Starts full so a
    cold fleet can still absorb its first hiccups."""

    def __init__(self, ratio: float = 0.1, cap: float = 10.0):
        self.ratio = float(ratio)
        self.cap = float(cap)
        self._tokens = float(cap)
        self._lock = threading.Lock()

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def deposit(self) -> None:
        with self._lock:
            self._tokens = min(self.cap, self._tokens + self.ratio)

    def try_withdraw(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
        METRICS.counter("fleet_retry_budget_exhausted_total").inc()
        return False


@dataclass
class ReplicaHandle:
    """Fleet-side record of one engine replica."""

    id: str
    engine: Any
    gauge_id: str  # the engine's ``replica`` gauge label
    state: str = "pending"  # pending | ready | draining | stopped
    role: str = "unified"  # unified | prefill | decode (the engine's pool)
    model_id: str = ""  # multiplexed model this replica serves ("" = only)
    #: LRU of prefix keys routed here (contents owned by PrefixRouter)
    prefixes: "collections.OrderedDict" = field(
        default_factory=collections.OrderedDict)
    pod_name: Optional[str] = None
    node: Optional[str] = None
    started_at: float = field(default_factory=time.monotonic)
    breaker: ReplicaBreaker = field(default_factory=ReplicaBreaker)


class EngineFleet:
    """ReplicaSet manager for continuous-batching engines.

    ``engine_factory(engine_id) -> engine`` defaults to building a
    :class:`ContinuousBatcher` from ``cfg``/``params``; tests inject
    fakes. With ``client`` set, each replica also materializes a Pod
    gang-labeled for the TPU scheduler (``replica_chips`` chips at
    ``priority_class``), a replica only becomes routable ("ready") once
    its pod binds, and a watcher thread turns pod deletion/preemption
    into a drain + re-queue + pod re-create.
    """

    def __init__(self, cfg: Any = None, params: Any = None, *,
                 replicas: int = 1, min_replicas: int = 1,
                 max_replicas: int = 8, slots: int = 8, chunk: int = 16,
                 pipeline: int = 3, name: str = "fleet",
                 router: Optional[PrefixRouter] = None,
                 engine_factory: Optional[Callable[..., Any]] = None,
                 engine_kwargs: Optional[Dict[str, Any]] = None,
                 pools: Optional[Dict[str, int]] = None,
                 models: Optional[Dict[str, Tuple[Any, Any]]] = None,
                 model_slo: Optional[Dict[str, str]] = None,
                 client: Any = None, namespace: str = "default",
                 replica_chips: int = 0, priority_class: str = "default",
                 poll_interval: float = 0.2, register_debug: bool = True,
                 breaker_factory: Optional[Callable[[], "ReplicaBreaker"]] = None,
                 retry_budget: Optional[RetryBudget] = None,
                 metrics_url: Optional[str] = None):
        self.name = name
        #: /metrics URL replica Pods advertise for monitoring-plane scrape
        #: discovery (replicas share the ModelServer process, so they all
        #: advertise ONE URL — the scraper dedups by instance)
        self._metrics_url = metrics_url
        self._breaker_factory = breaker_factory or ReplicaBreaker
        self.retry_budget = retry_budget or RetryBudget()
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.router = router or PrefixRouter()
        self._client = client
        self._namespace = namespace
        self._replica_chips = int(replica_chips)
        self._priority_class = priority_class
        self._poll_interval = poll_interval
        # -- ISSUE-18 disaggregation / multiplexing config -------------------
        if pools is not None:
            if (set(pools) != {"prefill", "decode"}
                    or any(int(n) < 1 for n in pools.values())):
                raise ValueError(
                    "pools must map BOTH 'prefill' and 'decode' to >= 1 "
                    f"replicas, got {pools!r}")
        self._pools_cfg = ({k: int(v) for k, v in pools.items()}
                           if pools else None)
        self._models = dict(models) if models else None
        #: model ids replicas are minted for ("" = the single anonymous one)
        self._model_ids = list(self._models) if self._models else [""]
        self._model_slo = dict(model_slo or {})
        for mid in self._model_slo:
            if self._models is not None and mid not in self._models:
                raise ValueError(f"model_slo names unknown model {mid!r}")
        if engine_factory is None:
            if self._models is None and (cfg is None or params is None):
                raise ValueError(
                    "EngineFleet needs cfg+params, models=, or an engine_factory")
            fleet = self

            def engine_factory(engine_id: str, role: str = "unified",
                               model_id: str = ""):
                from .continuous import ContinuousBatcher

                # engine_kwargs: ISSUE-12 per-engine knobs (paged KV arena
                # sizing, chunked prefill, speculative decoding) forwarded
                # verbatim so GenerativeModel configures fleets and single
                # engines identically
                mcfg, mparams = (fleet._models[model_id] if fleet._models
                                 else (cfg, params))
                return ContinuousBatcher(
                    mcfg, mparams, slots=slots, chunk=chunk,
                    pipeline=pipeline, engine_id=engine_id,
                    role=role, model_id=model_id,
                    handoff_sink=(fleet._handoff_sink if role == "prefill"
                                  else None),
                    **(engine_kwargs or {}))

        self._factory = engine_factory
        # injected factories predate pools/models: only call them with
        # role=/model_id= when their signature can take the keywords
        try:
            sig = inspect.signature(self._factory)
            self._factory_pool_aware = (
                "role" in sig.parameters
                or any(p.kind is inspect.Parameter.VAR_KEYWORD
                       for p in sig.parameters.values()))
        except (TypeError, ValueError):
            self._factory_pool_aware = False
        self._lock = threading.RLock()
        self._replicas: Dict[str, ReplicaHandle] = {}
        self._next_id = 0
        self._closed = False
        #: recent drains for /debug/fleet: (replica, reason, seconds, requeued)
        self._drains: "collections.deque" = collections.deque(maxlen=32)
        self._scale_log: "collections.deque" = collections.deque(maxlen=32)
        #: last scale_to target PER (role, model) — the watcher restores
        #: preempted pools to these
        self._targets: Dict[Tuple[str, str], int] = {}
        if self._pools_cfg:
            # each pool keeps >= 1 replica per model: a disaggregated fleet
            # with no prefill (or no decode) replicas can serve nothing
            self._pool_min = {r: 1 for r in self._pools_cfg}
            self._pool_max = {r: self.max_replicas for r in self._pools_cfg}
            for role, count in self._pools_cfg.items():
                self.scale_to(count, reason="initial", pool=role)
        else:
            self._pool_min = {"unified": self.min_replicas}
            self._pool_max = {"unified": self.max_replicas}
            self.scale_to(max(self.min_replicas, min(int(replicas),
                                                     self.max_replicas)),
                          reason="initial")
        self._watcher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if client is not None:
            self._watcher = threading.Thread(target=self._watch_pods,
                                             name=f"{name}-pod-watcher",
                                             daemon=True)
            self._watcher.start()
        if register_debug:
            register_debug_source("fleet", lambda query: self.debug_snapshot())

    # -- sizing --------------------------------------------------------------
    @property
    def desired_replicas(self) -> int:
        with self._lock:
            return sum(1 for h in self._replicas.values()
                       if h.state in ("pending", "ready"))

    def live_handles(self) -> List[ReplicaHandle]:
        with self._lock:
            return [h for h in self._replicas.values()
                    if h.state in ("pending", "ready")]

    @property
    def pools(self) -> Optional[Dict[str, int]]:
        """Configured role pools (``None`` = unified fleet). The
        autoscaler keys its per-pool evaluation off this."""
        return dict(self._pools_cfg) if self._pools_cfg else None

    def _default_pool(self) -> str:
        # pool=None targets the pool serving capacity competes for:
        # "unified" normally, "decode" when disaggregated
        return "decode" if self._pools_cfg else "unified"

    def _pool_handles(self, role: str, model_id: str) -> List[ReplicaHandle]:
        """Caller holds the lock."""
        return [h for h in self._replicas.values()
                if h.role == role and h.model_id == model_id
                and h.state in ("pending", "ready")]

    def pool_size(self, pool: Optional[str] = None) -> int:
        """Live replicas in ``pool``, per model (the fleet keeps every
        model at the same per-pool count, so this reports the max)."""
        role = pool or self._default_pool()
        with self._lock:
            return max((len(self._pool_handles(role, mid))
                        for mid in self._model_ids), default=0)

    def scale_to(self, n: int, reason: str = "",
                 pool: Optional[str] = None) -> None:
        """Grow or shrink ``pool`` to ``n`` live replicas PER MODEL
        (clamped to the pool's bounds; ``pool=None`` targets the unified
        pool — or the decode pool on a disaggregated fleet, since decode
        slots are the capacity callers compete for). Shrinking drains the
        newest ready replicas — their pendings re-queue to survivors."""
        role = pool or self._default_pool()
        lo = self._pool_min.get(role, 1)
        hi = self._pool_max.get(role, self.max_replicas)
        n = max(lo, min(int(n), hi))
        victims: List[str] = []
        with self._lock:
            if self._closed:
                return
            for mid in self._model_ids:
                self._targets[(role, mid)] = n
                handles = self._pool_handles(role, mid)
                current = len(handles)
                while current < n:
                    self._add_replica(role=role, model_id=mid)
                    current += 1
                if current > n:
                    handles.sort(key=lambda h: h.started_at, reverse=True)
                    victims.extend(h.id for h in handles[: current - n])
            self._scale_log.append({"at": time.time(), "to": n,
                                    "pool": role, "reason": reason})
        for rid in victims:
            self.drain_replica(rid, reason=reason or "scale_down")
        self._set_replica_gauge()

    def _add_replica(self, role: str = "unified",
                     model_id: str = "") -> ReplicaHandle:
        """Caller holds the lock."""
        created_at = time.monotonic()
        rid = str(self._next_id)
        self._next_id += 1
        gauge_id = f"{self.name}-{rid}"
        if self._factory_pool_aware:
            engine = self._factory(gauge_id, role=role, model_id=model_id)
        elif role != "unified" or model_id:
            raise ValueError(
                "engine_factory must accept role=/model_id= keywords to "
                "build pooled or multi-model replicas")
        else:
            engine = self._factory(gauge_id)
        handle = ReplicaHandle(id=rid, engine=engine, gauge_id=gauge_id,
                               role=role, model_id=model_id,
                               breaker=self._breaker_factory())
        # anchor cold start at replica creation, BEFORE engine construction
        # finished, so prewarm/weight-load time is inside the measurement
        handle.started_at = created_at
        METRICS.gauge("fleet_breaker_state", replica=gauge_id).set(
            handle.breaker.state_code)
        if self._client is not None:
            handle.pod_name = gauge_id
            self._create_pod(handle)
            handle.state = "pending"  # routable once the scheduler binds it
        else:
            handle.state = "ready"
            self._observe_cold_start(handle)
        self._replicas[rid] = handle
        return handle

    @staticmethod
    def _observe_cold_start(handle: ReplicaHandle) -> None:
        """Replica just became routable: record created → first-routable."""
        METRICS.histogram(
            "fleet_replica_cold_start_seconds", buckets=COLD_START_BUCKETS
        ).observe(time.monotonic() - handle.started_at)

    def _set_replica_gauge(self) -> None:
        METRICS.gauge("fleet_replicas").set(self.desired_replicas)
        if self._pools_cfg:
            with self._lock:
                for role in self._pools_cfg:
                    n = sum(1 for h in self._replicas.values()
                            if h.role == role
                            and h.state in ("pending", "ready"))
                    METRICS.gauge("fleet_pool_replicas", pool=role).set(n)

    # -- scheduler integration ----------------------------------------------
    def _pod_body(self, handle: ReplicaHandle) -> Dict[str, Any]:
        from ..api import meta as apimeta
        from ..scheduler.gang import (POD_GROUP_LABEL,
                                      POD_GROUP_SIZE_ANNOTATION)
        from ..tpu.topology import RESOURCE_TPU

        container: Dict[str, Any] = {"name": "engine",
                                     "image": "kubeflow-tpu/model-server"}
        if self._replica_chips > 0:
            container["resources"] = {
                "limits": {RESOURCE_TPU: str(self._replica_chips)}}
        annotations = {POD_GROUP_SIZE_ANNOTATION: "1"}
        if self._metrics_url:
            from ..monitoring.scrape import (SCRAPE_ANNOTATION,
                                             SCRAPE_JOB_ANNOTATION,
                                             SCRAPE_URL_ANNOTATION)

            annotations[SCRAPE_ANNOTATION] = "true"
            annotations[SCRAPE_URL_ANNOTATION] = self._metrics_url
            annotations[SCRAPE_JOB_ANNOTATION] = self.name
        return apimeta.new_object(
            "v1", "Pod", handle.pod_name, self._namespace,
            labels={POD_GROUP_LABEL: handle.pod_name,
                    "app": "serving-fleet", "fleet": self.name},
            annotations=annotations,
            spec={"priorityClassName": self._priority_class,
                  "containers": [container]})

    def _create_pod(self, handle: ReplicaHandle) -> None:
        self._client.create_or_get(self._pod_body(handle))

    def _watch_pods(self) -> None:
        """Thread target: the poll loop, wrapped so an unexpected exception
        restarts it (log + exponential backoff + counter) instead of
        silently killing the only thing noticing preempted replicas."""
        backoff = max(self._poll_interval, 0.01)
        while not self._stop.is_set():
            try:
                self._watch_pods_loop()
                return  # _stop set: clean shutdown
            except Exception:
                LOG.exception("fleet %s: pod watcher crashed; restarting in %.2fs",
                              self.name, backoff)
                METRICS.counter("fleet_watcher_restarts_total").inc()
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, WATCHER_BACKOFF_CAP_S)

    def _watch_pods_loop(self) -> None:
        """Poll replica pods: a bind promotes pending → ready; a deletion
        (scheduler preemption, operator kubectl delete) drains the replica
        and re-creates the pod so the gang re-enters the queue."""
        while not self._stop.wait(self._poll_interval):
            with self._lock:
                handles = list(self._replicas.values())
            for h in handles:
                if h.pod_name is None or h.state in ("draining", "stopped"):
                    continue
                try:
                    pod = self._client.get_opt("v1", "Pod", h.pod_name,
                                               self._namespace)
                except Exception:
                    continue  # apiserver hiccup: keep last known state
                phase = ((pod or {}).get("status") or {}).get("phase")
                if pod is None or phase in ("Failed", "Succeeded"):
                    # preempted (scheduler deletes victim pods) or killed
                    self.drain_replica(h.id, reason="preempted")
                    with self._lock:
                        # restore the last scale_to target for the victim's
                        # (pool, model): the replacement replica re-enters
                        # the scheduler queue and binds whenever the ledger
                        # next has chips
                        tgt = self._targets.get((h.role, h.model_id), 0)
                        if (not self._closed
                                and len(self._pool_handles(h.role,
                                                           h.model_id)) < tgt):
                            self._add_replica(role=h.role,
                                              model_id=h.model_id)
                    self._set_replica_gauge()
                    continue
                node = (pod.get("spec") or {}).get("nodeName")
                if h.state == "pending" and node:
                    promoted = False
                    with self._lock:
                        if h.state == "pending":
                            h.state = "ready"
                            h.node = node
                            promoted = True
                    if promoted:
                        self._observe_cold_start(h)

    # -- request path --------------------------------------------------------
    #: attempts per submit (first + retries); each RETRY also needs a
    #: retry-budget token, so the real bound under sustained failure is
    #: the budget's refill ratio, not this constant
    MAX_ATTEMPTS = 3

    def _record_outcome(self, handle: ReplicaHandle, ok: bool) -> None:
        """Breaker feedback: every finished request reports its replica's
        health. Deadline expiries count as failures (a slow replica IS a
        failing replica from the SLO's point of view); client-side
        cancellations are nobody's fault and are not reported here."""
        (handle.breaker.record_success if ok
         else handle.breaker.record_failure)()
        METRICS.gauge("fleet_breaker_state", replica=handle.gauge_id).set(
            handle.breaker.state_code)

    def _note_tenant_tokens(self, direction: str, n: int) -> None:
        """Per-tenant token metering (the fleet's namespace IS the tenant):
        ``in`` = prompt tokens admitted, ``out`` = tokens delivered."""
        if n > 0:
            METRICS.counter("tenant_tokens_total",
                            namespace=self._namespace or "default",
                            direction=direction).inc(n)

    def _outcome_cb(self, handle: ReplicaHandle) -> Callable[[Any], None]:
        def on_done(req: Any) -> None:
            # count delivered tokens BEFORE any early return: a cancelled
            # request still delivered what it streamed, and on_done fires
            # exactly once per request (handoff rebinds it to the final
            # decode replica)
            self._note_tenant_tokens("out", len(getattr(req, "tokens", ()) or ()))
            reason = getattr(req, "finish_reason", None)
            if reason == "cancelled":
                return  # client walked away; says nothing about the replica
            if isinstance(getattr(req, "error", None), FleetSaturated):
                return  # queue-full shed is backpressure, not ill-health
            self._record_outcome(
                handle, ok=req.error is None and reason != "deadline")
        return on_done

    def _admissible(self) -> List[ReplicaHandle]:
        """Live handles whose breaker admits traffic right now. Calling
        ``allow()`` here is what flips an expired open breaker to
        half_open — the admitted request is the probe."""
        out = []
        for h in self.live_handles():
            allowed = h.breaker.allow()
            METRICS.gauge("fleet_breaker_state", replica=h.gauge_id).set(
                h.breaker.state_code)
            if allowed:
                out.append(h)
        return out

    def submit(self, prompt_ids, max_new_tokens: int,
               eos_id: Optional[int] = None, temperature: float = 0.0,
               traceparent: Optional[str] = None,
               deadline: Optional[float] = None,
               priority: Optional[str] = None,
               model: str = ""):
        """Route and submit; same signature/return as
        ``ContinuousBatcher.submit`` so GenerativeModel can't tell the
        difference. Raises :class:`FleetSaturated` (a RuntimeError → the
        HTTP layer's 503) when no replica can take the request.

        ``model`` picks the multiplexed model (required when ``models=``
        was configured); ``priority=None`` resolves the model's default
        admission class from ``model_slo`` (falling back to interactive).
        On a disaggregated fleet the request enters through the prefill
        pool; its KV then hands off to a decode replica behind the same
        returned future.

        Replicas whose circuit breaker is open are excluded from routing;
        retries beyond the first attempt draw from the fleet-wide
        :class:`RetryBudget` so a dying fleet fails fast instead of
        retry-storming."""
        if self._models is not None and model not in self._models:
            raise ValueError(
                f"unknown model {model!r}: fleet serves {sorted(self._models)}")
        if priority is None:
            priority = self._model_slo.get(model, "interactive")
        entry_role = "prefill" if self._pools_cfg else "unified"
        self.retry_budget.deposit()
        last_err: Optional[BaseException] = None
        for attempt in range(self.MAX_ATTEMPTS):
            if attempt > 0 and not self.retry_budget.try_withdraw():
                raise FleetSaturated(
                    f"retry budget exhausted after replica failure: {last_err}")
            with self._lock:
                if self._closed:
                    raise RuntimeError("fleet closed")
                live = [h for h in self.live_handles()
                        if h.role == entry_role and h.model_id == model]
                admissible = [h for h in self._admissible()
                              if h.role == entry_role and h.model_id == model]
                if live and not admissible:
                    raise FleetSaturated(
                        f"all {len(live)} replica breakers open",
                        retry_after_s=self.router.retry_after_hint(live))
                handle, _policy = self.router.route(admissible, prompt_ids,
                                                    priority=priority,
                                                    model_id=model)
                try:
                    fut = handle.engine.submit(
                        prompt_ids, max_new_tokens, eos_id=eos_id,
                        temperature=temperature, traceparent=traceparent,
                        deadline=deadline, priority=priority,
                        on_done=self._outcome_cb(handle))
                    self._note_tenant_tokens("in", len(prompt_ids))
                    return fut
                except RuntimeError as e:
                    # engine wedged/closed outside our control: retire the
                    # handle and retry the route against the survivors
                    handle.state = "stopped"
                    self._record_outcome(handle, ok=False)
                    last_err = e
        raise FleetSaturated(f"no replica accepted the request: {last_err}")

    def _handoff_sink(self, req: Any, blob: bytes) -> None:
        """Prefill engines call this (from their worker thread) with a
        finished request's KV wire blob. Route it to the least-loaded
        same-model decode replica; ``submit_handoff`` resumes the ORIGINAL
        request object, so the caller's future survives the move. On total
        failure the request fails — the prefill compute is lost, and the
        client's retry re-enters through the prefill pool."""
        model = getattr(req, "model_id", "") or ""
        last_err: Optional[BaseException] = None
        for _ in range(self.MAX_ATTEMPTS):
            with self._lock:
                if self._closed:
                    last_err = RuntimeError("fleet closed mid-handoff")
                    break
                cands = [h for h in self._admissible()
                         if h.role == "decode" and h.model_id == model]
            if not cands:
                last_err = FleetSaturated(
                    f"no decode replica for model {model!r}")
                break
            handle = min(cands, key=self.router.load_score)
            try:
                # the decode replica owns the outcome now — rebind the
                # breaker callback before the import can finish
                req.on_done = self._outcome_cb(handle)
                handle.engine.submit_handoff(req, blob)
            except Exception as e:
                req.on_done = None
                last_err = e
                continue
            # the warm KV lives on the decode replica: future same-prefix
            # requests should prefill next to it
            self.router.note_prefix(handle, req.prompt, model)
            return
        self._fail_request(req, last_err
                           or RuntimeError("KV handoff found no route"))

    # -- drain / handoff ------------------------------------------------------
    def drain_replica(self, rid: str, reason: str = "scale_down") -> int:
        """Drain one replica and re-queue its unserved requests to the
        survivors; returns how many were re-queued. Blocking: when this
        returns the engine has finished its in-flight slots."""
        with self._lock:
            handle = self._replicas.get(rid)
            if handle is None or handle.state in ("draining", "stopped"):
                return 0
            handle.state = "draining"
        t0 = time.perf_counter()
        try:
            unserved = handle.engine.drain()
        except Exception:
            unserved = []
        drain_s = time.perf_counter() - t0
        METRICS.histogram("fleet_drain_seconds",
                          buckets=DRAIN_BUCKETS).observe(drain_s)
        requeued = self._requeue(unserved, exclude=rid)
        with self._lock:
            handle.state = "stopped"
            handle.prefixes.clear()  # its KV cache is gone with it
            self._replicas.pop(rid, None)
            pod_name = handle.pod_name
        if pod_name is not None and self._client is not None:
            try:
                self._client.delete_opt("v1", "Pod", pod_name,
                                        self._namespace)
            except Exception:
                pass  # preemption already deleted it
        self._drains.append({"replica": handle.gauge_id, "reason": reason,
                             "seconds": round(drain_s, 4),
                             "requeued": requeued, "at": time.time()})
        self._set_replica_gauge()
        return requeued

    def _requeue(self, unserved: List[Any], exclude: str) -> int:
        """Re-submit drained requests to surviving replicas. The drained
        engine handed back its ORIGINAL ``_Request`` objects (futures the
        HTTP handlers still hold), so each re-submission gets a bridge
        thread that copies the survivor's outcome back into the original."""
        requeued = 0
        entry_role = "prefill" if self._pools_cfg else "unified"
        for req in unserved:
            # detach the drained replica's breaker callback: the outcome
            # about to be bridged belongs to the SURVIVOR, which gets its
            # own callback on the shadow submission below
            if hasattr(req, "on_done"):
                req.on_done = None
            model = getattr(req, "model_id", "") or ""
            blob = getattr(req, "kv_blob", None)
            if blob is not None and self._pools_cfg:
                # already prefilled: re-IMPORT into a surviving decode
                # replica — the prefill compute is paid for, and
                # submit_handoff resumes the ORIGINAL request object, so
                # no bridge thread is needed
                with self._lock:
                    cands = [h for h in self.live_handles()
                             if h.role == "decode" and h.model_id == model
                             and h.id != exclude]
                imported = False
                for handle in sorted(cands, key=self.router.load_score):
                    try:
                        req.on_done = self._outcome_cb(handle)
                        handle.engine.submit_handoff(req, blob)
                        imported = True
                        break
                    except Exception:
                        req.on_done = None
                        continue
                if imported:
                    requeued += 1
                    METRICS.counter("fleet_requeued_total").inc()
                    continue
                # no decode survivor took it: fall through to a full
                # re-submission (re-runs prefill elsewhere)
            try:
                with self._lock:
                    handles = [h for h in self.live_handles()
                               if h.role == entry_role
                               and h.model_id == model]
                    handle, _policy = self.router.route(
                        handles, req.prompt, exclude=exclude,
                        priority=getattr(req, "priority", "interactive"),
                        model_id=model)
                    shadow = handle.engine.submit(
                        req.prompt, req.max_new_tokens, eos_id=req.eos_id,
                        temperature=req.temperature,
                        deadline=getattr(req, "deadline", None),
                        priority=getattr(req, "priority", "interactive"),
                        on_done=self._outcome_cb(handle))
            except Exception as e:
                self._fail_request(req, e)
                continue
            requeued += 1
            METRICS.counter("fleet_requeued_total").inc()
            threading.Thread(target=self._bridge, args=(req, shadow),
                             name=f"{self.name}-handoff", daemon=True).start()
        return requeued

    @staticmethod
    def _bridge(original: Any, shadow: Any) -> None:
        # the wait derives from the shadow's remaining deadline (plus a
        # grace period for the survivor to reap+complete it at expiry);
        # only deadline-less requests fall back to the fixed ceiling
        deadline = getattr(shadow, "deadline", None)
        if deadline is not None:
            timeout = max(0.0, deadline - time.monotonic()) + 5.0
        else:
            timeout = BRIDGE_TIMEOUT_S
        done = shadow.done.wait(timeout=timeout)
        original.tokens = list(shadow.tokens)
        original.finish_reason = getattr(shadow, "finish_reason", None)
        if done:
            error = shadow.error
        elif deadline is not None:
            error = DeadlineExceeded("handoff request missed its deadline")
        else:
            error = TimeoutError("handoff request not finished")
        span = getattr(original, "span", None)
        if span is not None:
            span.add_event("requeued")
            TRACER.end_span(span, error=error)
            original.span = None
        original.error = error
        original.done.set()

    @staticmethod
    def _fail_request(req: Any, error: BaseException) -> None:
        span = getattr(req, "span", None)
        if span is not None:
            TRACER.end_span(span, error=error)
            req.span = None
        req.error = error
        req.done.set()

    # -- lifecycle -----------------------------------------------------------
    def wait_ready(self, n: Optional[int] = None, timeout: float = 30.0) -> bool:
        """Block until ``n`` (default: all live) replicas are routable."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                ready = sum(1 for h in self._replicas.values()
                            if h.state == "ready")
                want = n if n is not None else self.desired_replicas
            if ready >= want:
                return True
            time.sleep(0.02)
        return False

    def close(self) -> None:
        with self._lock:
            self._closed = True
            handles = list(self._replicas.values())
            self._replicas.clear()
        self._stop.set()
        if self._watcher is not None:
            self._watcher.join(timeout=10)
        for h in handles:
            try:
                h.engine.close()
            except Exception:
                pass
            if h.pod_name is not None and self._client is not None:
                try:
                    self._client.delete_opt("v1", "Pod", h.pod_name,
                                            self._namespace)
                except Exception:
                    pass
        self._set_replica_gauge()

    # -- debug surface -------------------------------------------------------
    def debug_snapshot(self) -> Dict[str, Any]:
        reg = self.router._registry
        with self._lock:
            replicas = [{
                "id": h.gauge_id,
                "state": h.state,
                "role": h.role,
                "model": h.model_id,
                "queue_depth": reg.value("serving_queue_depth",
                                         replica=h.gauge_id),
                "active_slots": reg.value("serving_continuous_active_slots",
                                          replica=h.gauge_id),
                "slot_occupancy": reg.value("serving_slot_occupancy",
                                            replica=h.gauge_id),
                "warm_prefixes": len(h.prefixes),
                "breaker": h.breaker.state,
                "pod": h.pod_name,
                "node": h.node,
            } for h in self._replicas.values()]
            scale_log = list(self._scale_log)
            drains = list(self._drains)
        return {
            "fleet": self.name,
            "desired_replicas": self.desired_replicas,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "replicas": replicas,
            "retry_budget_tokens": round(self.retry_budget.tokens, 3),
            "router": {
                "max_queue_depth": self.router.max_queue_depth,
                "prefix_len": self.router.prefix_len,
                "routed": {p: METRICS.value("fleet_routed_total", policy=p)
                           for p in ("prefix", "prefix_spill",
                                     "least_loaded")},
                "prefix_hits": METRICS.value("fleet_prefix_hits_total"),
                "saturated": METRICS.value("fleet_saturated_total"),
            },
            "scale_log": scale_log,
            "drains": drains,
        }
