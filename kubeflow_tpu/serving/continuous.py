"""Continuous batching for KV-cache decode (VERDICT r3 #8).

The static batcher (serving/batching.py) coalesces whole requests: a batch
decodes in lockstep and every sequence pays for the LONGEST member's token
budget. For autoregressive serving the mechanism that matters is
slot-based admission — vLLM-style scheduling expressed the TPU way:

- ONE compiled decode step over a fixed ``slots``-row batch (static
  shapes, compiled once), every step produces one token per slot,
- the shared KV cache keeps a cursor PER ROW (models/gpt.py
  ``per_slot=True``), so rows are independent sequences at independent
  positions,
- a new request prefills into a free slot between steps (per-bucket
  prefill programs on a [1, P] cache, rows adopted into the big cache with
  one jitted splice) while other slots keep decoding,
- finished slots (budget reached / EOS) free immediately and the next
  queued request takes the row — no drain barrier, no padding to the
  longest request.

Throughput model: mixed arrivals with budgets b_i on S slots cost
~max-ish(sum b_i / S) steps here vs sum-of-group-max for the static
batcher. e2e/serving_bench.py:bench_continuous measures both on the same
workload; BASELINE.md records the numbers.
"""

from __future__ import annotations

import functools
import os
import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gpt import GptConfig, GptLM
from ..runtime.metrics import METRICS

#: prompt-length buckets — one prefill compilation each (static shapes)
PREFILL_BUCKETS = (16, 32, 64, 128, 256)


def _bucket_for(n: int) -> int:
    for b in PREFILL_BUCKETS:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds the largest prefill bucket")


@dataclass
class _Request:
    prompt: np.ndarray  # [prompt_len] int32
    max_new_tokens: int
    done: threading.Event = field(default_factory=threading.Event)
    tokens: List[int] = field(default_factory=list)
    error: Optional[BaseException] = None
    eos_id: Optional[int] = None
    temperature: float = 0.0  # 0 = greedy; >0 samples with a per-slot key
    done_at: Optional[float] = None  # perf_counter at retirement (latency acct)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError("request not finished")
        if self.error is not None:
            raise self.error
        return self.tokens


class ContinuousBatcher:
    """Slot-based decode engine over one per-slot KV cache.

    Usage:
        eng = ContinuousBatcher(cfg, params, slots=8)
        fut = eng.submit([1, 2, 3], max_new_tokens=32)
        tokens = fut.result(timeout=60)
        eng.close()

    ``chunk`` = decode steps per dispatch: each engine iteration runs a
    jitted ``lax.scan`` of that many single-token steps and fetches the
    [slots, chunk] token block once. chunk=1 is purest continuous batching
    but pays one dispatch + host round-trip PER TOKEN — measured 3x slower
    than the static path on this repo's tunneled backend. Chunking
    amortizes dispatch like the training benches amortize scan overhead;
    admission/retirement happen at chunk boundaries (a slot finishing
    mid-chunk discards its tail tokens — the cache stays correct because
    adoption resets the row cursor).
    """

    def __init__(self, cfg: GptConfig, params: Any, slots: int = 8, chunk: int = 16):
        self.cfg = cfg
        self.params = params
        self.slots = slots
        self.chunk = max(1, int(chunk))
        self.model = GptLM(cfg, decode=True, per_slot=True)
        self._prefill_model = GptLM(cfg, decode=True)  # [1, P], scalar cursor
        self.cache = self._fresh_cache()
        self.last_tok = jnp.zeros((slots,), jnp.int32)
        # per-slot sampling state: temperature 0 = greedy; each admission
        # folds a fresh counter into the base key so sampled requests draw
        # independent streams (same recipe as GenerativeModel's rng)
        self.temps = jnp.zeros((slots,), jnp.float32)
        self._base_rng = jax.random.PRNGKey(int.from_bytes(os.urandom(4), "little"))
        self._rng_counter = 0
        # split (not fold_in) for the initial keys so they can never collide
        # with the admission counter's fold_in stream
        self.rngs = jax.random.split(self._base_rng, slots)
        self._queue: "queue.Queue[Optional[_Request]]" = queue.Queue()
        self._active: Dict[int, _Request] = {}
        self._free = list(range(slots))
        self._lock = threading.Lock()
        self._closed = False
        self._step_fn = self._build_step()
        self._adopt_fn = self._build_adopt()
        self._prefill_fns: Dict[int, Any] = {}
        self._worker = threading.Thread(target=self._loop, name="continuous-batcher",
                                        daemon=True)
        self._worker.start()

    # -- compiled pieces -----------------------------------------------------
    def _fresh_cache(self) -> Dict[str, Any]:
        cfg, S = self.cfg, self.slots
        kv = (S, cfg.max_seq, cfg.n_heads, cfg.head_dim)
        return {
            f"block_{i}": {"attention": {
                "k": jnp.zeros(kv, cfg.dtype),
                "v": jnp.zeros(kv, cfg.dtype),
                "cursors": jnp.zeros((S,), jnp.int32),
            }}
            for i in range(cfg.n_layers)
        }

    def _build_step(self):
        model = self.model
        chunk = self.chunk

        # donate cache+tok+rngs: without donation every dispatch COPIES the
        # full multi-GB KV cache into fresh output buffers (measured: the
        # copy, not the math, dominated chunked stepping)
        @functools.partial(jax.jit, donate_argnums=(1, 2, 4))
        def step(params, cache, tok, temps, rngs):
            def one(carry, _):
                cache, tok, rngs = carry
                logits, updated = model.apply(
                    {"params": params, "cache": cache}, tok[:, None], mutable=["cache"]
                )
                lg = logits[:, -1]                               # [slots, vocab]
                greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                pairs = jax.vmap(jax.random.split)(rngs)   # [slots, 2, 2]
                rngs, keys = pairs[:, 0], pairs[:, 1]
                sampled = jax.vmap(
                    lambda k, l, t: jax.random.categorical(k, l / jnp.maximum(t, 1e-6))
                )(keys, lg, temps).astype(jnp.int32)
                nxt = jnp.where(temps > 0.0, sampled, greedy)
                return (updated["cache"], nxt, rngs), nxt

            (cache, tok, rngs), toks = jax.lax.scan(
                one, (cache, tok, rngs), None, length=chunk)
            return cache, tok, rngs, jnp.moveaxis(toks, 0, 1)  # [slots, chunk]

        return step

    def _build_adopt(self):
        @functools.partial(jax.jit, donate_argnums=(0, 5, 6, 7))
        def adopt(cache, small, slot, true_len, first_tok, last_tok,
                  temps, rngs, temperature, slot_rng):
            """Splice a [1, max_seq] prefill cache into row ``slot`` and
            reset that row's cursor to the TRUE prompt length (bucket
            padding beyond it stays invisible and is overwritten by the
            next decode steps). Also installs the slot's sampling state."""
            out = {}
            for name, layer in cache.items():
                att, small_att = layer["attention"], small[name]["attention"]
                k = jax.lax.dynamic_update_slice(att["k"], small_att["k"], (slot, 0, 0, 0))
                v = jax.lax.dynamic_update_slice(att["v"], small_att["v"], (slot, 0, 0, 0))
                cursors = att["cursors"].at[slot].set(true_len)
                out[name] = {"attention": {"k": k, "v": v, "cursors": cursors}}
            return (out, last_tok.at[slot].set(first_tok),
                    temps.at[slot].set(temperature),
                    rngs.at[slot].set(slot_rng))

        return adopt

    def _prefill(self, prompt: np.ndarray, temperature: float, key) -> Any:
        bucket = _bucket_for(len(prompt))
        if bucket not in self._prefill_fns:
            model = self._prefill_model

            @jax.jit
            def prefill(params, cache, ids, true_len, temperature, key):
                logits, updated = model.apply(
                    {"params": params, "cache": cache}, ids, mutable=["cache"]
                )
                # first generated token comes from the TRUE last prompt
                # position, not the padded bucket end
                lg = logits[0, true_len - 1]
                greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                sampled = jax.random.categorical(
                    key, lg / jnp.maximum(temperature, 1e-6)).astype(jnp.int32)
                first = jnp.where(temperature > 0.0, sampled, greedy)
                return updated["cache"], first

            self._prefill_fns[bucket] = prefill
        cfg = self.cfg
        kv = (1, cfg.max_seq, cfg.n_heads, cfg.head_dim)
        small = {
            f"block_{i}": {"attention": {
                "k": jnp.zeros(kv, cfg.dtype),
                "v": jnp.zeros(kv, cfg.dtype),
                "cursor": jnp.zeros((), jnp.int32),
            }}
            for i in range(cfg.n_layers)
        }
        padded = np.zeros((1, bucket), np.int32)
        padded[0, : len(prompt)] = prompt
        return self._prefill_fns[bucket](self.params, small, jnp.asarray(padded),
                                         len(prompt), jnp.float32(temperature), key)

    # -- public API ----------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int,
               eos_id: Optional[int] = None,
               temperature: float = 0.0) -> _Request:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if len(prompt) + max_new_tokens > self.cfg.max_seq:
            raise ValueError("prompt + budget exceeds max_seq")
        req = _Request(prompt, max_new_tokens, eos_id=eos_id,
                       temperature=float(temperature))
        # closed-check and enqueue under one lock: a put racing close()
        # could otherwise land AFTER the shutdown sentinel and hang its
        # caller forever (the worker stops at the sentinel)
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher closed")
            self._queue.put(req)
        return req

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._queue.put(None)
        self._worker.join(timeout=30)

    # -- engine loop ---------------------------------------------------------
    def _admit(self, req: _Request) -> None:
        # fresh sampling key per admission (distinct stream per request)
        self._rng_counter += 1
        slot_rng = jax.random.fold_in(self._base_rng, self._rng_counter)
        # prefill BEFORE taking the slot: a failing prefill (e.g. prompt
        # outside every bucket) must fail only this request, not leak a slot
        small, first = self._prefill(req.prompt, req.temperature, slot_rng)
        slot = self._free.pop()
        # drop the scalar cursor — adopt() resets the row cursor itself
        small = {n: {"attention": {"k": l["attention"]["k"], "v": l["attention"]["v"]}}
                 for n, l in small.items()}
        self.cache, self.last_tok, self.temps, self.rngs = self._adopt_fn(
            self.cache, small, slot, len(req.prompt), first, self.last_tok,
            self.temps, self.rngs, jnp.float32(req.temperature),
            jax.random.fold_in(slot_rng, 1))
        req.tokens.append(int(first))
        hit_eos = req.eos_id is not None and req.tokens[-1] == req.eos_id
        if req.max_new_tokens <= 1 or hit_eos:
            import time

            self._free.append(slot)
            req.done_at = time.perf_counter()
            req.done.set()
            METRICS.counter("serving_continuous_requests_total").inc()
            return
        self._active[slot] = req
        METRICS.gauge("serving_continuous_active_slots").set(len(self._active))

    def _retire(self, slot: int) -> None:
        import time

        req = self._active.pop(slot)
        self._free.append(slot)
        req.done_at = time.perf_counter()
        req.done.set()
        METRICS.counter("serving_continuous_requests_total").inc()
        METRICS.gauge("serving_continuous_active_slots").set(len(self._active))

    def _loop(self) -> None:
        while True:
            # admit as many queued requests as there are free slots; block
            # when fully idle (no busy-wait)
            try:
                timeout = None if not self._active else 0.0
                while self._free:
                    item = self._queue.get(timeout=timeout) if timeout is None \
                        else self._queue.get_nowait()
                    if item is None:
                        for req in self._active.values():
                            req.error = RuntimeError("batcher closed mid-flight")
                            req.done.set()
                        while True:  # fail anything still queued behind us
                            try:
                                rest = self._queue.get_nowait()
                            except queue.Empty:
                                return
                            if rest is not None:
                                rest.error = RuntimeError("batcher closed")
                                rest.done.set()
                    try:
                        self._admit(item)
                    except Exception as e:  # bad request fails alone
                        item.error = e
                        item.done.set()
                    timeout = 0.0
            except queue.Empty:
                pass
            if not self._active:
                continue
            # one CHUNK of decode steps for every slot (inactive rows
            # compute too — static shapes are the TPU contract; their
            # outputs are ignored, and a retiring row's tail tokens are
            # discarded below)
            try:
                self.cache, self.last_tok, self.rngs, toks = self._step_fn(
                    self.params, self.cache, self.last_tok, self.temps, self.rngs)
                toks = np.asarray(toks)  # host fetch = chunk barrier
            except Exception as e:
                # a device/RPC failure must not wedge the engine silently:
                # fail everything in flight and queued, refuse new work
                with self._lock:
                    self._closed = True
                err = RuntimeError(f"decode step failed: {e}")
                for req in self._active.values():
                    req.error = err
                    req.done.set()
                self._active.clear()
                while True:
                    try:
                        rest = self._queue.get_nowait()
                    except queue.Empty:
                        return
                    if rest is not None:
                        rest.error = err
                        rest.done.set()
            for slot in list(self._active):
                req = self._active[slot]
                for j in range(toks.shape[1]):
                    tok = int(toks[slot, j])
                    req.tokens.append(tok)
                    hit_eos = req.eos_id is not None and tok == req.eos_id
                    if len(req.tokens) >= req.max_new_tokens or hit_eos:
                        self._retire(slot)
                        break
