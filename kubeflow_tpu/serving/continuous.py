"""Continuous batching for KV-cache decode (VERDICT r3 #8).

The static batcher (serving/batching.py) coalesces whole requests: a batch
decodes in lockstep and every sequence pays for the LONGEST member's token
budget. For autoregressive serving the mechanism that matters is
slot-based admission — vLLM-style scheduling expressed the TPU way:

- ONE compiled decode step over a fixed ``slots``-row batch (static
  shapes, compiled once), every step produces one token per slot,
- the shared KV cache keeps a cursor PER ROW (models/gpt.py
  ``per_slot=True``), so rows are independent sequences at independent
  positions,
- new requests admit in WAVES: arrivals coalesce, each same-prompt-bucket
  group (chunked to at most ``min(slots, MAX_GROUP)`` rows) runs ONE
  batched prefill padded to that fixed size and ONE multi-row adopt
  splice — no host round trip on the admission path (first tokens are
  fetched lazily as pipelined events),
- finished slots (budget reached / EOS) free at event-processing time and
  the next queued request takes the row — no drain barrier, no padding to
  the longest request,
- chunk dispatches overlap (bounded ``pipeline`` depth) so the backend's
  ~115 ms dispatch+fetch round trip hides behind decode compute — the
  round-5 change that took the engine from 0.32x to 0.9-1.1x the offline
  static oracle's tokens/s at strictly lower mean latency (BASELINE.md
  round-5 serving section; e2e/kv_update_probe.py for the cost model).

Throughput model: mixed arrivals with budgets b_i on S slots cost
~max-ish(sum b_i / S) steps here vs sum-of-group-max for the static
batcher. e2e/serving_bench.py:bench_continuous measures both on the same
workload; BASELINE.md records the numbers.
"""

from __future__ import annotations

import collections
import functools
import math
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gpt import GptConfig, GptLM
from ..runtime.metrics import METRICS
from ..runtime.tracing import TRACER, Span
from .errors import (DeadlineExceeded, EngineClosed, FleetSaturated,
                     RequestCancelled)
from .paged import KVBlockAllocator, KVReservation

#: admission priority classes; batch is shed first under saturation
PRIORITIES = ("interactive", "batch")

#: prompt-length buckets — one prefill compilation each (static shapes)
PREFILL_BUCKETS = (16, 32, 64, 128, 256)

#: SLO histogram ladders (docs/OBSERVABILITY.md). The registry default
#: (1ms–30s) cannot resolve ms-scale inter-token latency, and TTFT needs
#: headroom past 30s for cold-compile admissions.
TTFT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                10.0, 30.0, 60.0)
ITL_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
               0.5, 1.0)
QUEUE_WAIT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0,
                      60.0)
PREFILL_BUCKETS_S = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                     10.0)
DECODE_CHUNK_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                        0.5, 1.0, 2.5)
#: KV handoff blob sizes span ~KBs (tiny configs) to ~100s of MB (long
#: prompts on the base config) — a power-of-8 ladder covers both
HANDOFF_BYTES_BUCKETS = (1024.0, 8192.0, 65536.0, 524288.0, 4194304.0,
                         33554432.0, 268435456.0)

#: ceiling on one batched prefill's rows: every admission group is padded
#: to ``min(slots, MAX_GROUP)`` (ONE prefill program + ONE reusable zero
#: template per prompt bucket; larger waves are chunked). Padding a
#: 1-request group to 8 rows costs only hidden prefill compute — the
#: round-5 cost model says dispatch round trips, not prompt flops, bound
#: admission.
MAX_GROUP = 8

#: drain-queue sentinel (distinct from the ``None`` shutdown sentinel):
#: the worker stops admitting, finishes in-flight slots, then parks the
#: unserved pendings for handoff instead of failing them
_DRAIN = object()


def _bucket_for(n: int) -> int:
    for b in PREFILL_BUCKETS:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds the largest prefill bucket")


def _block_tile(max_seq: int, requested: int = 16) -> int:
    """Arena tile (``block_t``) for the paged KV layout: the largest value
    not above ``requested`` that divides both ``max_seq`` (so the gathered
    [S, max_blocks*block_t] view is shape-identical to the contiguous
    cache — the bit-parity contract) and the smallest prefill bucket (so
    every bucket splice is a whole number of blocks)."""
    base = math.gcd(int(max_seq), PREFILL_BUCKETS[0])
    return next(b for b in range(min(int(requested), base), 0, -1)
                if base % b == 0)


def effective_prefill_chunk(requested: Optional[int], max_seq: int,
                            block_t: int = 1) -> int:
    """Resolve the chunked-prefill chunk size an engine will actually use:
    the largest value not above ``requested`` that divides ``max_seq``
    (chunk starts must never clamp inside the scalar-cursor prefill cache)
    and is a whole number of KV blocks. ``requested`` None defaults to the
    largest prefill bucket; 0/negative disables chunking (returns 0).
    ``GenerativeModel`` calls this too, so routing and engine agree."""
    if requested is None:
        requested = PREFILL_BUCKETS[-1]
    requested = min(int(requested), int(max_seq))
    if requested <= 0:
        return 0
    step = max(int(block_t), 1)
    for c in range(requested, 0, -1):
        if max_seq % c == 0 and c % step == 0:
            return c
    return 0


@dataclass(eq=False)  # identity equality: field eq would compare ndarrays
class _Request:
    prompt: np.ndarray  # [prompt_len] int32
    max_new_tokens: int
    done: threading.Event = field(default_factory=threading.Event)
    tokens: List[int] = field(default_factory=list)
    error: Optional[BaseException] = None
    eos_id: Optional[int] = None
    temperature: float = 0.0  # 0 = greedy; >0 samples with a per-slot key
    done_at: Optional[float] = None  # perf_counter at retirement (latency acct)
    # overload-protection state (ISSUE 9):
    deadline: Optional[float] = None  # absolute time.monotonic(); None = no deadline
    priority: str = "interactive"     # "interactive" | "batch"
    cancel_requested: bool = False    # client abandoned; worker reaps the slot
    #: how the request ended: "ok" (budget/EOS), "deadline" (expired
    #: mid-decode, partial tokens), "cancelled" (abandoned mid-decode),
    #: "error" (failed) — the fleet's breaker feedback keys off this
    finish_reason: Optional[str] = None
    #: fired exactly once when ``done`` is set, from whichever thread
    #: finished the request — the fleet hangs replica-outcome accounting
    #: (circuit breakers) here
    on_done: Optional[Callable[["_Request"], None]] = None
    # observability (None on internal requests, e.g. prewarm's dummies):
    # one span covers submit()→_retire(), crossing the caller thread into
    # the engine worker — hence start_span/end_span, not the contextmanager
    span: Optional[Span] = None
    submit_at: Optional[float] = None       # perf_counter at enqueue
    first_token_at: Optional[float] = None  # perf_counter at first token
    last_token_at: Optional[float] = None   # perf_counter at latest token
    #: multiplexing id (ISSUE 18) — which served model this request targets
    model_id: str = ""
    #: the request's exported KV wire blob, once a prefill replica has
    #: shipped it — a decode-pool drain hands the request back with this
    #: set so the fleet can re-import it on a surviving decode replica
    #: instead of re-running prefill
    kv_blob: Optional[bytes] = None

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError("request not finished")
        if self.error is not None:
            raise self.error
        return self.tokens

    def remaining(self, default: Optional[float] = None) -> Optional[float]:
        """Seconds until the deadline (negative once past); ``default``
        when no deadline is set."""
        if self.deadline is None:
            return default
        return self.deadline - time.monotonic()

    def expired(self) -> bool:
        return self.deadline is not None and time.monotonic() >= self.deadline

    def cancel(self) -> bool:
        """Abandon the request (client disconnect / explicit cancel). A
        queued request fails fast with :class:`RequestCancelled`; an
        in-flight one frees its slot within ~one decode chunk and
        completes with the partial tokens. False if already finished."""
        if self.done.is_set():
            return False
        self.cancel_requested = True
        return True

    def _notify(self) -> None:
        cb, self.on_done = self.on_done, None
        if cb is not None:
            try:
                cb(self)
            except Exception:
                pass


def _ev(req: _Request, name: str, **attrs: Any) -> None:
    if req.span is not None:
        req.span.add_event(name, **attrs)


def _trace_id(req: _Request) -> Optional[str]:
    return req.span.trace_id if req.span is not None else None


def _fail(req: _Request, error: BaseException) -> None:
    """Single failure path: error the future AND close the span — every
    branch that drops a request (bad bucket, prefill/adopt failure,
    shutdown) must leave its trace ERROR-terminated, not dangling."""
    req.error = error
    if req.finish_reason is None:
        req.finish_reason = "error"
    if req.span is not None:
        TRACER.end_span(req.span, error=error)
        req.span = None
    req.done.set()
    req._notify()


@dataclass(eq=False)
class _ChunkedPrefill:
    """One long prompt mid-chunked-prefill (ISSUE 12): it owns a slot and
    (paged) a KV reservation from the first chunk, prefills into a private
    [1, max_seq] scalar-cursor cache one fixed-size chunk per engine
    iteration — decode chunks keep dispatching in between, which is the
    whole point — and adopts into the shared cache when the last chunk
    lands."""
    req: _Request
    slot: int
    cache: Any
    key: Any
    pos: int = 0                       # prompt tokens prefilled so far
    res: Optional[KVReservation] = None


@dataclass(eq=False)
class _Import:
    """One KV-wire import awaiting a decode slot (ISSUE 18): the request
    was prefilled on a PREFILL-pool replica; its KV blocks arrived here
    already computed (and, int8, already quantized). Admission reserves
    arena blocks like any other request — wire imports get no back-pressure
    exemption — then scatters the blocks in one jitted call."""
    req: _Request
    manifest: Dict[str, Any]
    arrays: Dict[str, np.ndarray]


class ContinuousBatcher:
    """Slot-based decode engine over one per-slot KV cache.

    Usage:
        eng = ContinuousBatcher(cfg, params, slots=8)
        fut = eng.submit([1, 2, 3], max_new_tokens=32)
        tokens = fut.result(timeout=60)
        eng.close()

    ``chunk`` = decode steps per dispatch: each engine iteration runs a
    jitted ``lax.scan`` of that many single-token steps and fetches the
    [slots, chunk] token block once. chunk=1 is purest continuous batching
    but pays one dispatch + host round-trip PER TOKEN. Chunking amortizes
    dispatch like the training benches amortize scan overhead; admission/
    retirement happen at chunk boundaries (a slot finishing mid-chunk
    discards its tail tokens — the cache stays correct because adoption
    resets the row cursor).

    ``pipeline`` = chunk dispatches kept in flight. The round-5 probes
    (e2e/kv_update_probe.py) measured this backend's real cost model: a
    dispatch+fetch ROUND TRIP costs ~115 ms fixed while the marginal
    decode compute is ~2-3 ms/token — and a deep dispatch queue (10+
    outstanding) degrades ~4x. So the engine keeps a bounded event
    pipeline: chunks are dispatched asynchronously (token blocks fetched
    via ``copy_to_host_async``), and retirement/admission decisions lag
    ``pipeline`` chunks behind the dispatch frontier. Measured at depth 3:
    51.6 ms/chunk vs 146 unpipelined — the RTT fully hidden behind
    compute. Lagged decisions are safe because inactive rows cost nothing
    (the batch shape is fixed; a retired row's tail tokens are discarded
    against the dispatch-time snapshot) and adoptions join the donated
    cache chain in dispatch order.
    """

    def __init__(self, cfg: GptConfig, params: Any, slots: int = 8,
                 chunk: int = 16, pipeline: int = 3,
                 kv_kernel: Optional[bool] = None,
                 engine_id: str = "0",
                 max_pending: int = 0,
                 interactive_reserve: float = 0.25,
                 paged: bool = True,
                 kv_blocks: Optional[int] = None,
                 kv_block_t: int = 16,
                 prefill_chunk: Optional[int] = None,
                 spec_draft: Optional[Tuple[GptConfig, Any]] = None,
                 spec_k: int = 4,
                 kv_dtype: str = "bf16",
                 role: str = "unified",
                 model_id: str = "",
                 handoff_sink: Optional[Callable[["_Request", bytes], None]] = None):
        """New ISSUE-12 knobs (defaults keep every pre-existing behavior):

        ``paged``: shared block-arena KV layout with a per-slot block table
        (default). ``paged=False`` keeps the contiguous per-slot cache as
        the parity ground truth — the same pattern as
        ``ChipLedger(indexed=True)``.

        ``kv_blocks``: allocatable arena blocks (None = full capacity
        parity, ``slots * ceil(max_seq / block_t)`` — no admission
        back-pressure beyond the contiguous layout's). Smaller arenas trade
        HBM for ``KVBlocksExhausted`` back-pressure under long-prompt load;
        watch ``serving_kv_blocks_{free,used}``.

        ``kv_block_t``: requested arena tile; auto-shrunk so it divides
        ``max_seq`` and the smallest prefill bucket (bit-parity contract).

        ``prefill_chunk``: prompts longer than this prefill in fixed-size
        chunks interleaved with decode dispatches (None = the largest
        prefill bucket, which also extends the engine's servable prompt
        range from that bucket up to ``max_seq - budget``; 0 disables —
        long prompts then fail fast at admission).

        ``spec_draft``: ``(draft_cfg, draft_params)`` enables speculative
        decoding — the draft greedily proposes ``spec_k - 1`` tokens per
        round, the target verifies all positions in ONE batched forward,
        and the accepted prefix commits with cursor rollback on both
        caches. Greedy requests stay bit-identical to plain decode;
        sampled slots accept exactly one token per round, drawn from the
        verify logits.

        New ISSUE-18 knobs:

        ``kv_dtype``: arena storage precision — ``"bf16"`` (default,
        bit-parity ground truth) or ``"int8"`` (symmetric per-(row, head)
        quantized arena + f32 scale arena: 2x KV positions per HBM byte;
        greedy decode stays within the tested logit tolerance). int8
        requires the paged layout.

        ``role``: ``"unified"`` (default — prefill and decode in one
        engine), ``"prefill"`` (runs prefill ONLY: every admitted request
        is prefilled, exported to the KV wire format, and handed to
        ``handoff_sink(req, blob)`` — ownership transfers; the sink routes
        it to a decode replica), or ``"decode"`` (additionally accepts
        :meth:`submit_handoff` imports whose KV arrives pre-filled over
        the wire).

        ``model_id``: the served model's multiplexing id — stamped into
        exported KV manifests so a decode replica can refuse a wire blob
        from the wrong model.
        """
        self.cfg = cfg
        self.params = params
        self.slots = slots
        self.kv_dtype = str(kv_dtype)
        if self.kv_dtype not in ("bf16", "int8"):
            raise ValueError(f"kv_dtype {self.kv_dtype!r}: expected bf16|int8")
        if self.kv_dtype == "int8" and not paged:
            raise ValueError("kv_dtype='int8' requires paged=True")
        self.role = str(role)
        if self.role not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"role {self.role!r}: expected unified|prefill|decode")
        if self.role != "unified" and not paged:
            raise ValueError(
                "prefill/decode roles require paged=True (the KV wire "
                "format is block-shaped)")
        self.model_id = str(model_id)
        self.handoff_sink = handoff_sink
        # engine id -> the ``replica`` label on this engine's gauges: N
        # engines sharing one process registry (the fleet) must not clobber
        # each other's queue_depth / slot_occupancy series
        self.engine_id = str(engine_id)
        self.chunk = max(1, int(chunk))
        self.pipeline = max(1, int(pipeline))
        # admission-queue cap (0 = unbounded): when the queue is full,
        # batch requests shed at (1 - interactive_reserve) * max_pending
        # while interactive keeps the full depth — a batch flood cannot
        # starve interactive admission (ISSUE 9)
        self.max_pending = max(0, int(max_pending))
        self.interactive_reserve = min(max(float(interactive_reserve), 0.0), 1.0)
        #: chaos hooks (runtime/chaos.py slow_replica /
        #: crash_replica_mid_decode): added latency per engine iteration,
        #: and a one-shot poison that fails the next iteration
        self.step_delay_s = 0.0
        self.fail_next_step = False
        # fixed admission-group pad: one prefill program + one zero
        # template per prompt bucket; waves larger than this are chunked
        self._group_pad = min(slots, MAX_GROUP)
        # -- paged KV layout (ISSUE 12) ------------------------------------
        self.paged = bool(paged)
        if self.paged:
            self.kv_block_t = _block_tile(cfg.max_seq, kv_block_t)
            self._max_blocks = cfg.max_seq // self.kv_block_t
            n_blocks = (int(kv_blocks) if kv_blocks
                        else slots * self._max_blocks)
            self._alloc: Optional[KVBlockAllocator] = KVBlockAllocator(
                n_blocks, self.kv_block_t, engine_id=self.engine_id)
            # ONE host-side block table shared by every layer (each
            # dispatch snapshots it to device); entries default to the
            # trash block so unallocated positions can never hit real data
            self._tables = np.full((slots, self._max_blocks),
                                   self._alloc.trash, np.int32)
            self._slot_res: Dict[int, KVReservation] = {}
            # upper bound on each slot's device cursor at the dispatch
            # frontier — spec rounds advance the real cursor by a
            # data-dependent amount, so granting tracks the bound
            self._ub_cursor = np.zeros((slots,), np.int64)
        else:
            self.kv_block_t = 0
            self._alloc = None
        # -- chunked prefill (ISSUE 12) ------------------------------------
        self.prefill_chunk = effective_prefill_chunk(
            prefill_chunk, cfg.max_seq, self.kv_block_t or 1)
        self._chunked: Optional[_ChunkedPrefill] = None
        self._chunk_prefill_fn: Optional[Any] = None
        self._draft_full_prefill_fn: Optional[Any] = None
        # -- speculative decoding (ISSUE 12) -------------------------------
        self.spec_k = 0
        if spec_draft is not None:
            draft_cfg, draft_params = spec_draft
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError("spec draft must share the target's vocab")
            if draft_cfg.max_seq < cfg.max_seq:
                raise ValueError("spec draft max_seq must cover the target's")
            self.spec_k = max(2, int(spec_k))
            self._draft_cfg = draft_cfg
            self._draft_params = draft_params
            self._draft_model = GptLM(draft_cfg, decode=True, per_slot=True,
                                      kv_kernel=False)
            self._draft_prefill_model = GptLM(draft_cfg, decode=True)
        # kv_kernel: per-slot KV-write strategy (None = the
        # KUBEFLOW_TPU_KV_KERNEL env default; see models.gpt)
        if self.paged:
            self.model = GptLM(cfg, decode=True, per_slot=True,
                               kv_kernel=kv_kernel, paged=True,
                               kv_blocks=self._alloc.n_blocks + 1,
                               kv_block_t=self.kv_block_t,
                               kv_dtype=self.kv_dtype)
        else:
            self.model = GptLM(cfg, decode=True, per_slot=True,
                               kv_kernel=kv_kernel)
        self._prefill_model = GptLM(cfg, decode=True)  # [1, P], scalar cursor
        self.cache = self._fresh_cache()
        if self.spec_k:
            self.draft_cache = self._fresh_draft_cache()
        self.last_tok = jnp.zeros((slots,), jnp.int32)
        # per-slot sampling state: temperature 0 = greedy; each admission
        # folds a fresh counter into the base key so sampled requests draw
        # independent streams (same recipe as GenerativeModel's rng)
        self.temps = jnp.zeros((slots,), jnp.float32)
        self._base_rng = jax.random.PRNGKey(int.from_bytes(os.urandom(4), "little"))
        self._rng_counter = 0
        # split (not fold_in) for the initial keys so they can never collide
        # with the admission counter's fold_in stream
        self.rngs = jax.random.split(self._base_rng, slots)
        # queue items are WAVES (lists of requests enqueued atomically) so a
        # caller can hand the worker a group it should admit together;
        # submit() enqueues singleton waves. None is the shutdown sentinel.
        self._queue: "queue.Queue[Optional[List[_Request]]]" = queue.Queue()
        self._pending: "collections.deque[_Request]" = collections.deque()
        self._active: Dict[int, _Request] = {}
        self._free = list(range(slots))
        self._lock = threading.Lock()
        self._closed = False
        self._draining = False
        #: requests drain() could not serve — handed off to the fleet router
        self._handoff: List[_Request] = []
        #: wire-format KV imports awaiting a slot (decode role, ISSUE 18)
        self._imports: "collections.deque[_Import]" = collections.deque()
        self._step_fn = self._build_step()
        self._adopt_fn = self._build_adopt()
        self._import_fn = self._build_import() if self.paged else None
        self._spec_fn = self._build_spec_step() if self.spec_k else None
        self._draft_adopt_fn = self._build_draft_adopt() if self.spec_k else None
        self._prefill_fns: Dict[Tuple[int, int, bool], Any] = {}
        # reusable zero prefill-cache per group bucket: prefill does NOT
        # donate its cache input, so one template serves every admission —
        # without it each wave re-allocates 2*n_layers zero buffers on the
        # device (measured as dispatch-stream noise on the tunnel)
        self._zero_small: Dict[Tuple[int, bool], Any] = {}
        self._worker = threading.Thread(target=self._loop, name="continuous-batcher",
                                        daemon=True)
        self._worker.start()

    # -- compiled pieces -----------------------------------------------------
    def _fresh_cache(self) -> Dict[str, Any]:
        cfg, S = self.cfg, self.slots
        if self.paged:
            arena = (self._alloc.n_blocks + 1, self.kv_block_t,
                     cfg.n_heads, cfg.head_dim)
            quant = self.kv_dtype == "int8"
            arena_dtype = jnp.int8 if quant else cfg.dtype

            def layer() -> Dict[str, Any]:
                att = {
                    "k_arena": jnp.zeros(arena, arena_dtype),
                    "v_arena": jnp.zeros(arena, arena_dtype),
                    "cursors": jnp.zeros((S,), jnp.int32),
                }
                if quant:
                    scale = arena[:3] + (1,)
                    att["k_scale"] = jnp.zeros(scale, jnp.float32)
                    att["v_scale"] = jnp.zeros(scale, jnp.float32)
                return {"attention": att}

            return {f"block_{i}": layer() for i in range(cfg.n_layers)}
        kv = (S, cfg.max_seq, cfg.n_heads, cfg.head_dim)
        return {
            f"block_{i}": {"attention": {
                "k": jnp.zeros(kv, cfg.dtype),
                "v": jnp.zeros(kv, cfg.dtype),
                "cursors": jnp.zeros((S,), jnp.int32),
            }}
            for i in range(cfg.n_layers)
        }

    def _fresh_draft_cache(self) -> Dict[str, Any]:
        # the draft stays contiguous: it is small by construction, so the
        # paged arena's memory win does not apply to it
        dcfg, S = self._draft_cfg, self.slots
        kv = (S, dcfg.max_seq, dcfg.n_heads, dcfg.head_dim)
        return {
            f"block_{i}": {"attention": {
                "k": jnp.zeros(kv, dcfg.dtype),
                "v": jnp.zeros(kv, dcfg.dtype),
                "cursors": jnp.zeros((S,), jnp.int32),
            }}
            for i in range(dcfg.n_layers)
        }

    def _build_step(self):
        model = self.model
        chunk = self.chunk
        paged = self.paged

        # donate cache+tok+rngs: without donation every dispatch COPIES the
        # full multi-GB KV cache into fresh output buffers (measured: the
        # copy, not the math, dominated chunked stepping)
        @functools.partial(jax.jit, donate_argnums=(1, 2, 4))
        def step(params, cache, tok, temps, rngs, *tables):
            def one(carry, _):
                cache, tok, rngs = carry
                kwargs = {"block_tables": tables[0]} if paged else {}
                logits, updated = model.apply(
                    {"params": params, "cache": cache}, tok[:, None],
                    mutable=["cache"], **kwargs
                )
                lg = logits[:, -1]                               # [slots, vocab]
                greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                pairs = jax.vmap(jax.random.split)(rngs)   # [slots, 2, 2]
                rngs, keys = pairs[:, 0], pairs[:, 1]
                sampled = jax.vmap(
                    lambda k, l, t: jax.random.categorical(k, l / jnp.maximum(t, 1e-6))
                )(keys, lg, temps).astype(jnp.int32)
                nxt = jnp.where(temps > 0.0, sampled, greedy)
                return (updated["cache"], nxt, rngs), nxt

            (cache, tok, rngs), toks = jax.lax.scan(
                one, (cache, tok, rngs), None, length=chunk)
            return cache, tok, rngs, jnp.moveaxis(toks, 0, 1)  # [slots, chunk]

        return step

    def _build_spec_step(self):
        """One speculative round: the draft model greedily proposes
        ``spec_k`` tokens (``spec_k - 1`` of them verifiable), the target
        verifies all positions in ONE seg_len=spec_k forward, and both
        caches roll their cursors back to the accepted frontier.

        Accept-prefix semantics (greedy slots): emitted tokens are
        ``t_1 .. t_m`` with ``m = 1 + (leading draft/target matches)`` —
        exactly the tokens plain greedy decode would emit, because each
        ``t_j`` is conditioned only on accepted history. Position ``C+j``
        of both caches holds the KV of a matched (= accepted) token for
        every ``j < m``, so rollback to ``C + m`` leaves both caches
        bit-identical to a plain decode that emitted the same tokens; the
        stale KV above the frontier is overwritten before it is ever
        unmasked. Sampled slots accept exactly one token per round, drawn
        from the verify logits at position 0 (one key split per round).
        """
        model, draft_model = self.model, self._draft_model
        k = self.spec_k
        paged = self.paged

        def _rollback(cache, delta):
            out = {}
            for name, layer in cache.items():
                att = dict(layer["attention"])
                att["cursors"] = att["cursors"] - delta
                out[name] = {"attention": att}
            return out

        @functools.partial(jax.jit, donate_argnums=(2, 3, 4, 6))
        def spec(params, dparams, cache, dcache, tok, temps, rngs, *tables):
            def draft_one(carry, _):
                dcache, tok = carry
                logits, updated = draft_model.apply(
                    {"params": dparams, "cache": dcache}, tok[:, None],
                    mutable=["cache"])
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return (updated["cache"], nxt), nxt

            # k draft steps: writes the draft KV for tok and d_1..d_{k-1}
            # (so a fully accepted round leaves the draft cache complete
            # after rollback); d_k itself is never verified
            (dcache, _), drafts = jax.lax.scan(
                draft_one, (dcache, tok), None, length=k)
            drafts = jnp.moveaxis(drafts, 0, 1)                  # [S, k]
            seg = jnp.concatenate([tok[:, None], drafts[:, :k - 1]], axis=1)
            kwargs = {"block_tables": tables[0]} if paged else {}
            logits, updated = model.apply(
                {"params": params, "cache": cache}, seg,
                mutable=["cache"], **kwargs)
            cache = updated["cache"]
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, k]
            pairs = jax.vmap(jax.random.split)(rngs)
            rngs, keys = pairs[:, 0], pairs[:, 1]
            sampled = jax.vmap(
                lambda k_, l, t: jax.random.categorical(
                    k_, l / jnp.maximum(t, 1e-6))
            )(keys, logits[:, 0], temps).astype(jnp.int32)
            match = (drafts[:, :k - 1] == greedy[:, :k - 1]).astype(jnp.int32)
            m_greedy = 1 + jnp.sum(jnp.cumprod(match, axis=1), axis=1)
            m = jnp.where(temps > 0.0, 1, m_greedy).astype(jnp.int32)  # [S]
            toks = jnp.where(temps[:, None] > 0.0,
                             jnp.concatenate([sampled[:, None], greedy[:, 1:]],
                                             axis=1),
                             greedy)                             # [S, k]
            cache = _rollback(cache, k - m)
            dcache = _rollback(dcache, k - m)
            last = jnp.take_along_axis(toks, (m - 1)[:, None], axis=1)[:, 0]
            return cache, dcache, last, rngs, toks, m

        return spec

    def _build_adopt(self):
        if self.paged:
            bt = self.kv_block_t
            quant = self.kv_dtype == "int8"

            @functools.partial(jax.jit, donate_argnums=(0, 5, 6, 7))
            def paged_adopt(cache, small, block_ids, slots, true_lens,
                            last_tok, temps, rngs, first_toks, temperatures,
                            slot_rngs):
                """Paged adoption: scatter each prefill row's first ``L``
                positions (``L = block_ids.shape[1] * block_t`` — the
                prompt bucket or the chunked-prefill span, both whole
                blocks by construction) into the arena rows named by
                ``block_ids``. Rows' trailing entries are the trash block,
                so bucket padding past the granted blocks lands in trash;
                padding inside the last granted block sits above the
                cursor, which the mask hides until decode overwrites it.
                int8 arenas quantize here with the SAME quantize_kv the KV
                wire exporter uses — a moved and a never-moved request land
                byte-identical int8 blocks."""
                from ..ops.kv_cache import quantize_kv

                n = slots.shape[0]
                nb = block_ids.shape[1]
                ids = block_ids.reshape(-1)
                out = {}
                for name, layer in cache.items():
                    att, small_att = layer["attention"], small[name]["attention"]
                    shape = small_att["k"].shape                 # [n_pad, max_seq, h, d]
                    seg_k = small_att["k"][:n, :nb * bt].reshape(
                        n * nb, bt, shape[2], shape[3])
                    seg_v = small_att["v"][:n, :nb * bt].reshape(
                        n * nb, bt, shape[2], shape[3])
                    upd = {"cursors": att["cursors"].at[slots].set(true_lens)}
                    if quant:
                        kq, ks = quantize_kv(seg_k)
                        vq, vs = quantize_kv(seg_v)
                        upd["k_arena"] = att["k_arena"].at[ids].set(kq)
                        upd["v_arena"] = att["v_arena"].at[ids].set(vq)
                        upd["k_scale"] = att["k_scale"].at[ids].set(ks)
                        upd["v_scale"] = att["v_scale"].at[ids].set(vs)
                    else:
                        upd["k_arena"] = att["k_arena"].at[ids].set(
                            seg_k.astype(att["k_arena"].dtype))
                        upd["v_arena"] = att["v_arena"].at[ids].set(
                            seg_v.astype(att["v_arena"].dtype))
                    out[name] = {"attention": upd}
                return (out, last_tok.at[slots].set(first_toks),
                        temps.at[slots].set(temperatures),
                        rngs.at[slots].set(slot_rngs))

            return paged_adopt

        @functools.partial(jax.jit, donate_argnums=(0, 4, 5, 6))
        def adopt(cache, small, slots, true_lens, last_tok, temps, rngs,
                  first_toks, temperatures, slot_rngs):
            """Splice prefill-cache rows ``0..n-1`` of ``small`` (padded to
            a group bucket — padding rows beyond n are ignored) into cache
            rows ``slots[0..n-1]`` and reset those cursors to the TRUE
            prompt lengths (bucket padding beyond them stays invisible and
            is overwritten by the next decode steps). Also installs each
            slot's sampling state. The group size n rides the arg shapes
            (jit retraces per size); the per-row dynamic_update_slice chain
            stays in place under donation — no full-cache pass."""
            n = slots.shape[0]
            out = {}
            for name, layer in cache.items():
                att, small_att = layer["attention"], small[name]["attention"]
                k, v = att["k"], att["v"]
                for i in range(n):
                    k = jax.lax.dynamic_update_slice(
                        k, small_att["k"][i:i + 1], (slots[i], 0, 0, 0))
                    v = jax.lax.dynamic_update_slice(
                        v, small_att["v"][i:i + 1], (slots[i], 0, 0, 0))
                cursors = att["cursors"].at[slots].set(true_lens)
                out[name] = {"attention": {"k": k, "v": v, "cursors": cursors}}
            return (out, last_tok.at[slots].set(first_toks),
                    temps.at[slots].set(temperatures),
                    rngs.at[slots].set(slot_rngs))

        return adopt

    def _build_draft_adopt(self):
        @functools.partial(jax.jit, donate_argnums=(0,))
        def draft_adopt(dcache, small, slots, true_lens):
            """Splice draft-prefill rows into the (contiguous) draft cache
            — the sampling state lives with the target adopt; the draft
            only needs KV + cursors."""
            n = slots.shape[0]
            out = {}
            for name, layer in dcache.items():
                att, small_att = layer["attention"], small[name]["attention"]
                k, v = att["k"], att["v"]
                for i in range(n):
                    k = jax.lax.dynamic_update_slice(
                        k, small_att["k"][i:i + 1], (slots[i], 0, 0, 0))
                    v = jax.lax.dynamic_update_slice(
                        v, small_att["v"][i:i + 1], (slots[i], 0, 0, 0))
                cursors = att["cursors"].at[slots].set(true_lens)
                out[name] = {"attention": {"k": k, "v": v, "cursors": cursors}}
            return out

        return draft_adopt

    def _build_import(self):
        """Jitted KV-wire import (decode role): scatter one request's
        pre-filled blocks — [nb, block_t, h, d] per layer, plus the f32
        scale blocks when int8 — into the arena rows just granted to it,
        and install cursor/sampling state exactly as adoption would. One
        retrace per distinct block count (shape-keyed under jit), same as
        the prompt-bucketed adopt."""
        quant = self.kv_dtype == "int8"

        @functools.partial(jax.jit, donate_argnums=(0, 3, 4, 5))
        def import_kv(cache, wire, block_ids, last_tok, temps, rngs,
                      slot, true_len, first_tok, temperature, key):
            out = {}
            for name, layer in cache.items():
                att = layer["attention"]
                w = wire[name]
                upd = {
                    "k_arena": att["k_arena"].at[block_ids].set(
                        w["k"].astype(att["k_arena"].dtype)),
                    "v_arena": att["v_arena"].at[block_ids].set(
                        w["v"].astype(att["v_arena"].dtype)),
                    "cursors": att["cursors"].at[slot].set(true_len),
                }
                if quant:
                    upd["k_scale"] = att["k_scale"].at[block_ids].set(
                        w["k_scale"])
                    upd["v_scale"] = att["v_scale"].at[block_ids].set(
                        w["v_scale"])
                out[name] = {"attention": upd}
            return (out, last_tok.at[slot].set(first_tok),
                    temps.at[slot].set(temperature),
                    rngs.at[slot].set(key))

        return import_kv

    def _prefill_group(self, prompts: Sequence[np.ndarray],
                       temperatures: Sequence[float], keys,
                       draft: bool = False) -> Tuple[Any, Any]:
        """ONE batched prefill for a same-length-bucket admission group:
        [n_pad, bucket] prompt forward on a reused zero [n_pad, max_seq]
        cache (shared cursor 0 — every row starts at position 0), padded
        to the engine's single fixed group size so every group reuses one
        compilation and one template. Returns (small cache, first token
        per row). Round 4 measured ~141 ms of mostly fixed dispatch cost
        PER single-prompt admission; batching amortizes that over up to
        ``n_pad`` arrivals."""
        n = len(prompts)
        bucket = _bucket_for(max(len(p) for p in prompts))
        n_pad = self._group_pad
        if n > n_pad:
            raise ValueError(f"admission group of {n} exceeds pad {n_pad}")
        if (bucket, n_pad, draft) not in self._prefill_fns:
            model = self._draft_prefill_model if draft else self._prefill_model

            @jax.jit
            def prefill(params, cache, ids, true_lens, temperatures, keys):
                logits, updated = model.apply(
                    {"params": params, "cache": cache}, ids, mutable=["cache"]
                )
                # each row's first generated token comes from ITS true last
                # prompt position, not the padded bucket end
                lg = jnp.take_along_axis(
                    logits, (true_lens - 1)[:, None, None], axis=1)[:, 0]
                greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                sampled = jax.vmap(
                    lambda k_, l, t: jax.random.categorical(
                        k_, l / jnp.maximum(t, 1e-6))
                )(keys, lg, temperatures).astype(jnp.int32)
                first = jnp.where(temperatures > 0.0, sampled, greedy)
                return updated["cache"], first

            self._prefill_fns[(bucket, n_pad, draft)] = prefill
        cfg = self._draft_cfg if draft else self.cfg
        if (n_pad, draft) not in self._zero_small:
            kv = (n_pad, cfg.max_seq, cfg.n_heads, cfg.head_dim)
            self._zero_small[(n_pad, draft)] = {
                f"block_{i}": {"attention": {
                    "k": jnp.zeros(kv, cfg.dtype),
                    "v": jnp.zeros(kv, cfg.dtype),
                    "cursor": jnp.zeros((), jnp.int32),
                }}
                for i in range(cfg.n_layers)
            }
        small = self._zero_small[(n_pad, draft)]
        ids = np.zeros((n_pad, bucket), np.int32)
        true_lens = np.ones((n_pad,), np.int32)
        temps = np.zeros((n_pad,), np.float32)
        for i, p in enumerate(prompts):
            ids[i, : len(p)] = p
            true_lens[i] = len(p)
            temps[i] = temperatures[i]
        if keys.shape[0] != n_pad:  # pad the key rows (unused rows ignored)
            keys = jnp.concatenate(
                [keys, jnp.zeros((n_pad - n, 2), keys.dtype)], axis=0)
        return self._prefill_fns[(bucket, n_pad, draft)](
            self._draft_params if draft else self.params, small,
            jnp.asarray(ids), jnp.asarray(true_lens),
            jnp.asarray(temps), keys)

    # -- public API ----------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int,
               eos_id: Optional[int] = None,
               temperature: float = 0.0,
               traceparent: Optional[str] = None,
               deadline: Optional[float] = None,
               priority: str = "interactive",
               on_done: Optional[Callable[[_Request], None]] = None) -> _Request:
        """``traceparent`` (W3C header value) parents the request's span to
        the caller's trace — the HTTP predict handler passes its own so a
        scraped trace shows the handler as root over submit→retire.

        ``deadline`` is an ABSOLUTE ``time.monotonic()`` instant: a request
        whose deadline passes while queued fails fast with
        :class:`DeadlineExceeded` (never occupies a slot); one that expires
        mid-decode frees its slot within ~one decode chunk and completes
        with the partial tokens. An already-expired deadline fails the
        returned future immediately — no exception from submit itself, so
        the fleet's retry path can't mistake it for a dead replica."""
        if priority not in PRIORITIES:
            raise ValueError(f"priority {priority!r}; expected one of {PRIORITIES}")
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if len(prompt) + max_new_tokens > self.cfg.max_seq:
            raise ValueError("prompt + budget exceeds max_seq")
        if self.paged:
            need = self._alloc.blocks_for(len(prompt) + max_new_tokens)
            if need > self._alloc.n_blocks:
                # waiting can never help — fail fast instead of pending
                # forever behind an arena that is too small by construction
                raise ValueError(
                    f"prompt + budget needs {need} KV blocks; the arena has "
                    f"{self._alloc.n_blocks} (raise kv_blocks)")
        req = _Request(prompt, max_new_tokens, eos_id=eos_id,
                       temperature=float(temperature),
                       deadline=deadline, priority=priority, on_done=on_done,
                       model_id=self.model_id)
        req.span = TRACER.start_span(
            "serving.request", traceparent=traceparent,
            **{"prompt_tokens": int(len(prompt)),
               "max_new_tokens": int(max_new_tokens),
               "priority": priority,
               # federated queries isolate one fleet replica's decode path
               # by this label (the /debug/traces?service= counterpart)
               "replica": self.engine_id})
        req.submit_at = time.perf_counter()
        _ev(req, "enqueued")
        METRICS.counter("serving_tokens_in_total").inc(len(prompt))
        if req.expired():  # dead on arrival: shed before it costs anything
            METRICS.counter("serving_deadline_expired_total",
                            stage="queued").inc()
            _ev(req, "deadline_expired", stage="queued")
            req.finish_reason = "deadline"
            # pre-admission expiry says nothing about THIS replica's health:
            # suppress the fleet's breaker callback
            req.on_done = None
            _fail(req, DeadlineExceeded("deadline already expired at submit"))
            return req
        # closed-check and enqueue under one lock: a put racing close()
        # could otherwise land AFTER the shutdown sentinel and hang its
        # caller forever (the worker stops at the sentinel)
        with self._lock:
            if self._closed:
                _fail(req, EngineClosed("batcher closed"))
                raise EngineClosed("batcher closed")
            self._queue.put([req])
        return req

    def submit_handoff(self, req: _Request, blob: bytes) -> _Request:
        """Accept a request prefilled ELSEWHERE (decode role, ISSUE 18):
        ``blob`` is the KV wire export from a prefill-pool replica. The
        manifest and per-layer crc32s are verified here, synchronously —
        a corrupt or mismatched blob must fail on the caller's thread
        (where the fleet can still retry another replica), never poison
        the decode loop. The SAME request object continues: its future,
        span, and deadline all carry over, so TTFT measures the true
        submit→first-token path across both replicas."""
        if self.role == "prefill":
            raise ValueError("prefill-role engines cannot import KV")
        if not self.paged:
            raise ValueError("KV import requires the paged arena layout")
        from .kv_wire import unpack_kv

        manifest, arrays = unpack_kv(blob)
        if manifest.get("kv_dtype") != self.kv_dtype:
            raise ValueError(
                f"wire kv_dtype {manifest.get('kv_dtype')!r} != engine "
                f"{self.kv_dtype!r}")
        if int(manifest.get("block_t", 0)) != self.kv_block_t:
            raise ValueError(
                f"wire block_t {manifest.get('block_t')} != engine "
                f"{self.kv_block_t}")
        if manifest.get("model_id", "") != self.model_id:
            raise ValueError(
                f"wire model {manifest.get('model_id')!r} != replica model "
                f"{self.model_id!r}")
        if int(manifest.get("prompt_len", -1)) != len(req.prompt):
            raise ValueError("wire prompt_len disagrees with the request")
        need = self._alloc.blocks_for(len(req.prompt) + req.max_new_tokens)
        if need > self._alloc.n_blocks:
            raise ValueError(
                f"prompt + budget needs {need} KV blocks; the arena has "
                f"{self._alloc.n_blocks} (raise kv_blocks)")
        req.kv_blob = blob
        imp = _Import(req=req, manifest=manifest, arrays=arrays)
        with self._lock:
            if self._closed:
                raise EngineClosed("batcher closed")
            self._queue.put(imp)
        return req

    def cancel_requests(self, n: int = 1) -> int:
        """Abandon up to ``n`` in-flight or queued requests (the chaos
        harness's client-disconnect simulation; also the ops hook for
        evicting stuck work). Returns how many were marked — the worker
        reaps each within ~one decode chunk."""
        marked = 0
        for _ in range(3):
            try:
                reqs = list(self._active.values()) + list(self._pending)
                break
            except RuntimeError:
                continue  # worker resized a container mid-copy; retry
        else:
            return 0
        for req in reqs:
            if marked >= n:
                break
            if req.cancel():
                marked += 1
        return marked

    def prewarm(self, prompt_len: int,
                group_sizes: Optional[Sequence[int]] = None,
                timeout: float = 600.0) -> None:
        """Compile the engine's programs outside any latency-sensitive
        window: for each admission-group size, a wave of dummy requests is
        pushed as ONE queue item so the worker admits them together —
        exercising the (prompt-bucket, group-bucket) prefill, the exact-n
        adopt, and (for the largest wave) the chunked decode step, all
        through the production path. Compilations land in the persistent
        JAX cache when one is configured. ``timeout`` becomes each dummy
        request's deadline, so a wedged compile surfaces as
        :class:`DeadlineExceeded` instead of an 1800 s magic wait."""
        deadline = time.monotonic() + timeout
        # default: EVERY group size 1.._group_pad — the adopt program is
        # traced per exact group size (admission chunks larger waves to
        # _group_pad), so a size first seen mid-run would compile inside
        # somebody's latency window
        sizes = sorted({min(s, self._group_pad) for s in
                        (group_sizes if group_sizes is not None
                         else range(1, self._group_pad + 1))})
        for idx, n in enumerate(sizes):
            # waves run SEQUENTIALLY (each fully retired before the next is
            # enqueued) so the worker sees exactly one n-sized admission —
            # concurrent waves would coalesce in the pending queue
            budget = self.chunk + 1 if idx == len(sizes) - 1 else 1
            wave = [_Request(np.zeros((prompt_len,), np.int32), budget,
                             deadline=deadline)
                    for _ in range(n)]
            with self._lock:
                if self._closed:
                    raise EngineClosed("batcher closed")
                self._queue.put(wave)
            for req in wave:
                # the wait derives from the request's own deadline (plus a
                # grace period for the worker to reap+fail it) — the worker
                # raises DeadlineExceeded through result() at expiry
                req.result(timeout=max(0.0, deadline - time.monotonic()) + 5.0)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._queue.put(None)
        self._worker.join(timeout=30)

    def drain(self, timeout: float = 600.0) -> List[_Request]:
        """Graceful shutdown, distinct from ``close()``: stop admission,
        let the in-flight slots run to completion, then return the
        unserved requests (queued waves + pending) with their futures
        still open so a fleet can re-submit them to a surviving replica.
        ``close()`` after a drain is a no-op; submit() raises once the
        drain begins. Idempotent — a second call returns the same
        handoff list."""
        with self._lock:
            already = self._closed
            self._closed = True
            if not already:
                self._queue.put(_DRAIN)
        self._worker.join(timeout=timeout)
        return list(self._handoff)

    # -- engine loop ---------------------------------------------------------
    def _admit_wave(self, reqs: List[_Request]) -> List[Tuple[str, Any, Any]]:
        """Admit up to ``len(self._free)`` requests together: one batched
        prefill + one adopt per same-prompt-bucket group instead of the
        round-4 per-request dispatch chain (~141 ms each). Fully async —
        the first tokens stay on device (the adopt consumes them there) and
        are fetched lazily via the returned ``('first', toks, pairs)``
        events, so an admission adds NO host round trip to the dispatch
        chain."""
        events: List[Tuple[str, Any, Any]] = []
        by_bucket: Dict[int, List[Tuple[_Request, Any]]] = {}
        back: List[_Request] = []  # re-queued (chunked busy / arena full)
        for req in reqs:
            # fresh sampling key per admission (distinct stream per request)
            self._rng_counter += 1
            key = jax.random.fold_in(self._base_rng, self._rng_counter)
            if self.prefill_chunk and len(req.prompt) > self.prefill_chunk:
                # long prompt → chunked prefill. One in flight at a time:
                # it holds a slot from its first chunk, and serializing
                # keeps prefill compute from flooding the decode stream.
                if self._chunked is not None or not self._free:
                    back.append(req)
                elif not self._start_chunked(req, key):
                    back.append(req)
                continue
            try:
                bucket = _bucket_for(len(req.prompt))
            except Exception as e:  # bad request fails alone, takes no slot
                _fail(req, e)
                continue
            by_bucket.setdefault(bucket, []).append((req, key))
        groups = [chunk[i:i + self._group_pad]
                  for chunk in by_bucket.values()
                  for i in range(0, len(chunk), self._group_pad)]
        for group in groups:
            if self.role == "prefill":
                # prefill specialist: ONE batched prefill, then export each
                # row's KV blocks + first token over the wire — no slot, no
                # arena reservation, no decode. Ownership moves to the
                # handoff sink (the fleet routes it to a decode replica).
                try:
                    keys = jnp.stack([k for _, k in group])
                    t0 = time.perf_counter()
                    small, first = self._prefill_group(
                        [r.prompt for r, _ in group],
                        [r.temperature for r, _ in group], keys)
                except Exception as e:
                    for req, _ in group:
                        _fail(req, e)
                    continue
                METRICS.histogram(
                    "serving_prefill_seconds", buckets=PREFILL_BUCKETS_S
                ).observe(time.perf_counter() - t0,
                          trace_id=_trace_id(group[0][0]))
                self._export_group(group, small, first)
                continue
            reserved: List[KVReservation] = []
            if self.paged:
                # reserve worst-case blocks BEFORE spending prefill compute;
                # exhaustion is back-pressure (the request stays pending and
                # retries as retirements free blocks), not an error
                admit: List[Tuple[_Request, Any]] = []
                for req, key in group:
                    blocks = self._alloc.blocks_for(
                        len(req.prompt) + req.max_new_tokens)
                    try:
                        res = self._alloc.reserve(blocks)
                    except FleetSaturated:
                        back.append(req)
                        continue
                    except Exception as e:
                        _fail(req, e)
                        continue
                    admit.append((req, key))
                    reserved.append(res)
                group = admit
                if not group:
                    continue
            try:
                keys = jnp.stack([k for _, k in group])
                t0 = time.perf_counter()
                small, first = self._prefill_group(
                    [r.prompt for r, _ in group],
                    [r.temperature for r, _ in group], keys)
            except Exception as e:  # whole-group failure takes no slots
                for res in reserved:
                    self._alloc.release(res)
                for req, _ in group:
                    _fail(req, e)
                continue
            # dispatch wall time of ONE batched group prefill (the tokens
            # surface later via the pipelined 'first' event)
            METRICS.histogram(
                "serving_prefill_seconds", buckets=PREFILL_BUCKETS_S
            ).observe(time.perf_counter() - t0,
                      trace_id=_trace_id(group[0][0]))
            n = len(group)
            slots = [self._free.pop() for _ in range(n)]
            slots_arr = jnp.asarray(slots, dtype=jnp.int32)
            true_lens_arr = jnp.asarray(
                [len(r.prompt) for r, _ in group], dtype=jnp.int32)
            try:
                # drop the scalar cursor — adopt() resets the row cursors itself
                small = {nm: {"attention": {"k": l["attention"]["k"],
                                            "v": l["attention"]["v"]}}
                         for nm, l in small.items()}
                first_n = first[:n]
                adopt_args = (self.last_tok, self.temps, self.rngs, first_n,
                              jnp.asarray([r.temperature for r, _ in group],
                                          dtype=jnp.float32),
                              jnp.stack([jax.random.fold_in(k, 1)
                                         for _, k in group]))
                if self.paged:
                    # grant each row the blocks its PROMPT needs (decode
                    # grants the rest as cursors advance) and point its
                    # table at them — BEFORE the adopt dispatch snapshots
                    # the block ids
                    bucket = _bucket_for(max(len(r.prompt) for r, _ in group))
                    nb = bucket // self.kv_block_t
                    block_ids = np.full((n, nb), self._alloc.trash, np.int32)
                    for i, ((req, _), slot, res) in enumerate(
                            zip(group, slots, reserved)):
                        self._alloc.grant(
                            res, self._alloc.blocks_for(len(req.prompt)))
                        block_ids[i, :len(res.granted)] = res.granted
                        self._tables[slot, :len(res.granted)] = res.granted
                        self._slot_res[slot] = res
                        self._ub_cursor[slot] = len(req.prompt)
                    self.cache, self.last_tok, self.temps, self.rngs = \
                        self._adopt_fn(self.cache, small,
                                       jnp.asarray(block_ids), slots_arr,
                                       true_lens_arr, *adopt_args)
                else:
                    self.cache, self.last_tok, self.temps, self.rngs = \
                        self._adopt_fn(self.cache, small, slots_arr,
                                       true_lens_arr, *adopt_args)
                if self.spec_k:
                    # the draft must adopt the same prompts before any spec
                    # round includes these rows; a failure here is engine
                    # state corruption, so it propagates to the loop's
                    # catch-all (fail everything, close) rather than being
                    # swallowed per-group
                    dsmall, _ = self._prefill_group(
                        [r.prompt for r, _ in group],
                        [r.temperature for r, _ in group], keys, draft=True)
                    dsmall = {nm: {"attention": {"k": l["attention"]["k"],
                                                 "v": l["attention"]["v"]}}
                              for nm, l in dsmall.items()}
                    self.draft_cache = self._draft_adopt_fn(
                        self.draft_cache, dsmall, slots_arr, true_lens_arr)
            except Exception as e:
                # Adopt failed AFTER the slots were popped: these requests
                # are in neither _active nor the pending queue, so _shutdown
                # could never fail them — callers would block until their
                # result() timeout. Restore the slots and fail the group now.
                self._free.extend(slots)
                if self.paged:
                    for slot, res in zip(slots, reserved):
                        self._tables[slot, :] = self._alloc.trash
                        self._slot_res.pop(slot, None)
                        self._ub_cursor[slot] = 0
                        self._alloc.release(res)
                for req, _ in group:
                    _fail(req, e)
                continue
            try:
                first_n.copy_to_host_async()
            except Exception:
                pass
            # activate NOW (before the first-token value is on host): the
            # next chunk dispatch must include these rows in its snapshot
            now = time.perf_counter()
            for (req, _), slot in zip(group, slots):
                self._active[slot] = req
                if req.submit_at is not None:
                    METRICS.histogram(
                        "serving_queue_wait_seconds",
                        buckets=QUEUE_WAIT_BUCKETS,
                    ).observe(now - req.submit_at, trace_id=_trace_id(req))
                _ev(req, "admitted", slot=slot)
                _ev(req, "prefill_done")
            events.append(("first", first_n,
                           [(req, slot) for (req, _), slot in zip(group, slots)],
                           now))
        if back:
            # requeue at the FRONT in arrival order: these requests lost no
            # place in line — they only wait for arena blocks or for the
            # (serialized) chunked-prefill lane to free up
            for r in reversed(back):
                self._pending.appendleft(r)
            self._set_queue_gauge()
        self._set_occupancy()
        return events

    # -- KV handoff: prefill-role export (ISSUE 18) --------------------------
    def _export_group(self, group, small, first) -> None:
        """Fetch a prefill group's cache rows + first tokens to host and
        ship each request over the wire. The host fetch is a deliberate
        synchronous round trip: a prefill specialist has no decode lane to
        starve, and the wire serialization needs the bytes anyway."""
        first_host = np.asarray(first)
        host = {nm: {"k": np.asarray(l["attention"]["k"]),
                     "v": np.asarray(l["attention"]["v"])}
                for nm, l in small.items()}
        for i, (req, _) in enumerate(group):
            self._ship(req,
                       {nm: {"k": d["k"][i], "v": d["v"][i]}
                        for nm, d in host.items()},
                       int(first_host[i]))

    def _ship(self, req: _Request, row_cache: Dict[str, Any],
              first_token: int) -> None:
        """Export ONE prefilled request ([max_seq, h, d] contiguous rows
        per layer) to the KV wire format and hand it to the sink. The sink
        call is synchronous — when it returns without raising, ownership
        has transferred (a decode replica holds the import); any failure
        fails the request here, where its future still has an owner."""
        from .kv_wire import export_kv

        sink = self.handoff_sink
        if sink is None:
            _fail(req, RuntimeError(
                "prefill engine has no handoff_sink — a prefill-role "
                "replica cannot serve decode itself"))
            return
        try:
            t0 = time.perf_counter()
            blob = export_kv(
                row_cache, prompt_len=len(req.prompt),
                block_t=self.kv_block_t, kv_dtype=self.kv_dtype,
                first_token=first_token, model_id=self.model_id)
            req.kv_blob = blob
            sink(req, blob)
        except Exception as e:
            _fail(req, e)
            return
        dt = time.perf_counter() - t0
        METRICS.counter("serving_kv_handoff_total").inc()
        METRICS.histogram("serving_kv_handoff_bytes",
                          buckets=HANDOFF_BYTES_BUCKETS).observe(
            float(len(blob)))
        METRICS.histogram("serving_kv_handoff_seconds",
                          buckets=PREFILL_BUCKETS_S).observe(
            dt, trace_id=_trace_id(req))
        _ev(req, "kv_handoff", bytes=len(blob))

    def _build_draft_full_prefill(self):
        dmodel = self._draft_prefill_model

        @jax.jit
        def draft_full(params, cache, ids):
            _, updated = dmodel.apply(
                {"params": params, "cache": cache}, ids,
                mutable=["cache"])
            return updated["cache"]

        return draft_full

    # -- chunked prefill (ISSUE 12) ------------------------------------------
    def _build_chunk_prefill(self):
        model = self._prefill_model

        @functools.partial(jax.jit, donate_argnums=(1,))
        def chunk_prefill(params, cache, ids, first_idx, temperature, key):
            logits, updated = model.apply(
                {"params": params, "cache": cache}, ids, mutable=["cache"])
            # only the LAST chunk's call reads a real token (first_idx =
            # the prompt's true last position inside that chunk); earlier
            # chunks pass 0 and discard the result
            lg = logits[0, first_idx]
            greedy = jnp.argmax(lg).astype(jnp.int32)
            sampled = jax.random.categorical(
                key, lg / jnp.maximum(temperature, 1e-6)).astype(jnp.int32)
            return updated["cache"], jnp.where(
                temperature > 0.0, sampled, greedy)

        return chunk_prefill

    def _start_chunked(self, req: _Request, key) -> bool:
        """Claim a slot (and, paged, the worst-case block reservation) for
        one long prompt and install it as THE in-flight chunked prefill —
        the actual chunk dispatches happen one per engine iteration from
        :meth:`_advance_chunked` so decode keeps ticking in between.
        Returns False when the arena cannot reserve yet (caller requeues);
        a structurally impossible request fails and returns True."""
        res = None
        # a prefill specialist never decodes: no arena reservation — the
        # decode replica that imports the wire blob reserves there
        if self.paged and self.role != "prefill":
            blocks = self._alloc.blocks_for(len(req.prompt) + req.max_new_tokens)
            try:
                res = self._alloc.reserve(blocks)
            except FleetSaturated:
                return False
            except Exception as e:
                _fail(req, e)
                return True
        cfg = self.cfg
        kv = (1, cfg.max_seq, cfg.n_heads, cfg.head_dim)
        cache = {
            f"block_{i}": {"attention": {
                "k": jnp.zeros(kv, cfg.dtype),
                "v": jnp.zeros(kv, cfg.dtype),
                "cursor": jnp.zeros((), jnp.int32),
            }}
            for i in range(cfg.n_layers)
        }
        slot = self._free.pop()
        self._chunked = _ChunkedPrefill(req=req, slot=slot, cache=cache,
                                        key=key, res=res)
        _ev(req, "chunked_prefill_start", slot=slot,
            chunks=-(-len(req.prompt) // self.prefill_chunk))
        return True

    def _abort_chunked(self, cp: _ChunkedPrefill) -> None:
        """Release a mid-prefill request's slot and (paged) blocks; the
        caller completes/fails the request itself. Retire ordering applies
        here too: the table row goes to trash before the blocks return."""
        if self.paged:
            self._tables[cp.slot, :] = self._alloc.trash
            self._slot_res.pop(cp.slot, None)
            self._ub_cursor[cp.slot] = 0
            if cp.res is not None:
                self._alloc.release(cp.res)
        self._free.append(cp.slot)
        self._chunked = None

    def _advance_chunked(self) -> List[Tuple[str, Any, Any, float]]:
        """Dispatch ONE prefill chunk for the in-flight long prompt; on the
        last chunk, adopt into the shared cache and activate the slot.
        Returns the pipelined 'first' event when the adoption happens."""
        cp = self._chunked
        req = cp.req
        if req.done.is_set():  # failed/completed elsewhere; just clean up
            self._abort_chunked(cp)
            return []
        if req.cancel_requested:
            req.finish_reason = "cancelled"
            METRICS.counter("serving_cancelled_total").inc()
            _ev(req, "cancelled", stage="prefill")
            self._abort_chunked(cp)
            _fail(req, RequestCancelled("cancelled during chunked prefill"))
            return []
        if req.expired():
            req.finish_reason = "deadline"
            METRICS.counter("serving_deadline_expired_total",
                            stage="prefill").inc()
            _ev(req, "deadline_expired", stage="prefill")
            self._abort_chunked(cp)
            _fail(req, DeadlineExceeded(
                "deadline expired during chunked prefill"))
            return []
        if self._chunk_prefill_fn is None:
            self._chunk_prefill_fn = self._build_chunk_prefill()
        n = len(req.prompt)
        c = self.prefill_chunk
        start = cp.pos
        seg = req.prompt[start:start + c]
        ids = np.zeros((1, c), np.int32)
        ids[0, :len(seg)] = seg
        last = start + c >= n
        # padding past the prompt (final chunk only) writes garbage KV at
        # positions >= n; adoption sets the cursor to n, so the mask hides
        # it until decode overwrites position n onward
        first_idx = (n - 1) - start if last else 0
        cp.cache, first = self._chunk_prefill_fn(
            self.params, cp.cache, jnp.asarray(ids),
            jnp.asarray(first_idx, jnp.int32),
            jnp.asarray(req.temperature, jnp.float32), cp.key)
        cp.pos = start + c
        METRICS.counter("serving_prefill_chunks_total").inc()
        _ev(req, "prefill_chunk", start=start)
        if not last:
            return []
        if self.role == "prefill":
            # last chunk of a long prompt on a prefill specialist: export
            # instead of adopting — the decode replica owns it from here
            host = {nm: {"k": np.asarray(l["attention"]["k"])[0],
                         "v": np.asarray(l["attention"]["v"])[0]}
                    for nm, l in cp.cache.items()}
            tok = int(np.asarray(first))
            self._abort_chunked(cp)
            self._ship(req, host, tok)
            return []
        # -- last chunk: adopt + activate -----------------------------------
        slot = cp.slot
        first_arr = first[None]
        small = {nm: {"attention": {"k": l["attention"]["k"],
                                    "v": l["attention"]["v"]}}
                 for nm, l in cp.cache.items()}
        slots_arr = jnp.asarray([slot], jnp.int32)
        true_lens_arr = jnp.asarray([n], jnp.int32)
        adopt_args = (self.last_tok, self.temps, self.rngs, first_arr,
                      jnp.asarray([req.temperature], jnp.float32),
                      jax.random.fold_in(cp.key, 1)[None])
        if self.paged:
            nb = cp.pos // self.kv_block_t  # whole blocks: bt | chunk
            block_ids = np.full((1, nb), self._alloc.trash, np.int32)
            self._alloc.grant(cp.res, self._alloc.blocks_for(n))
            block_ids[0, :len(cp.res.granted)] = cp.res.granted
            self._tables[slot, :len(cp.res.granted)] = cp.res.granted
            self._slot_res[slot] = cp.res
            self._ub_cursor[slot] = n
            self.cache, self.last_tok, self.temps, self.rngs = self._adopt_fn(
                self.cache, small, jnp.asarray(block_ids), slots_arr,
                true_lens_arr, *adopt_args)
        else:
            self.cache, self.last_tok, self.temps, self.rngs = self._adopt_fn(
                self.cache, small, slots_arr, true_lens_arr, *adopt_args)
        if self.spec_k:
            # the draft adopts the full prompt in one forward (its whole
            # point is being small; chunking IT would serialize more
            # dispatches for no decode-lane benefit)
            dcfg = self._draft_cfg
            kv = (1, dcfg.max_seq, dcfg.n_heads, dcfg.head_dim)
            dzero = {
                f"block_{i}": {"attention": {
                    "k": jnp.zeros(kv, dcfg.dtype),
                    "v": jnp.zeros(kv, dcfg.dtype),
                    "cursor": jnp.zeros((), jnp.int32),
                }}
                for i in range(dcfg.n_layers)
            }
            if self._draft_full_prefill_fn is None:
                self._draft_full_prefill_fn = self._build_draft_full_prefill()
            dids = np.zeros((1, cp.pos), np.int32)
            dids[0, :n] = req.prompt
            dsmall = self._draft_full_prefill_fn(
                self._draft_params, dzero, jnp.asarray(dids))
            dsmall = {nm: {"attention": {"k": l["attention"]["k"],
                                         "v": l["attention"]["v"]}}
                      for nm, l in dsmall.items()}
            self.draft_cache = self._draft_adopt_fn(
                self.draft_cache, dsmall, slots_arr, true_lens_arr)
        try:
            first_arr.copy_to_host_async()
        except Exception:
            pass
        now = time.perf_counter()
        self._active[slot] = req
        if req.submit_at is not None:
            METRICS.histogram(
                "serving_queue_wait_seconds", buckets=QUEUE_WAIT_BUCKETS,
            ).observe(now - req.submit_at, trace_id=_trace_id(req))
        _ev(req, "admitted", slot=slot)
        _ev(req, "prefill_done")
        self._chunked = None
        self._set_occupancy()
        return [("first", first_arr, [(req, slot)], now)]

    # -- KV handoff: decode-role import (ISSUE 18) ---------------------------
    def _admit_imports(self) -> List[Tuple[str, Any, Any, float]]:
        """Admit queued KV-wire imports into free slots: reserve arena
        blocks (normal back-pressure — an exhausted arena leaves the import
        queued and retries as retirements free blocks), grant the prompt's
        blocks, scatter the wire payload in one jitted call, and activate.
        The 'first' event carries the PREFILL replica's first token so the
        decode side emits it through the standard event path (TTFT from
        the original submit instant — handoff latency is inside it)."""
        events: List[Tuple[str, Any, Any, float]] = []
        quant = self.kv_dtype == "int8"
        while self._imports and self._free:
            imp = self._imports[0]
            req = imp.req
            if req.done.is_set():
                self._imports.popleft()
                continue
            if req.cancel_requested:
                self._imports.popleft()
                req.finish_reason = "cancelled"
                METRICS.counter("serving_cancelled_total").inc()
                _ev(req, "cancelled", stage="import")
                _fail(req, RequestCancelled("cancelled before KV import"))
                continue
            if req.expired():
                self._imports.popleft()
                req.finish_reason = "deadline"
                METRICS.counter("serving_deadline_expired_total",
                                stage="queued").inc()
                _ev(req, "deadline_expired", stage="import")
                _fail(req, DeadlineExceeded(
                    "deadline expired before KV import"))
                continue
            n = len(req.prompt)
            try:
                res = self._alloc.reserve(
                    self._alloc.blocks_for(n + req.max_new_tokens))
            except FleetSaturated:
                break  # no blocks yet; the import keeps its place in line
            except Exception as e:
                self._imports.popleft()
                _fail(req, e)
                continue
            self._imports.popleft()
            slot = self._free.pop()
            try:
                nb = self._alloc.blocks_for(n)
                self._alloc.grant(res, nb)
                block_ids = np.asarray(res.granted, np.int32)
                if any(a.shape[0] != nb for a in imp.arrays.values()):
                    raise ValueError(
                        f"wire carries a block count != {nb} for "
                        f"prompt_len {n}")
                self._tables[slot, :nb] = block_ids
                self._slot_res[slot] = res
                self._ub_cursor[slot] = n
                wire = {}
                for i in range(self.cfg.n_layers):
                    nm = f"block_{i}"
                    entry = {"k": jnp.asarray(imp.arrays[f"{nm}/k"]),
                             "v": jnp.asarray(imp.arrays[f"{nm}/v"])}
                    if quant:
                        entry["k_scale"] = jnp.asarray(
                            imp.arrays[f"{nm}/k_scale"])
                        entry["v_scale"] = jnp.asarray(
                            imp.arrays[f"{nm}/v_scale"])
                    wire[nm] = entry
                self._rng_counter += 1
                key = jax.random.fold_in(self._base_rng, self._rng_counter)
                (self.cache, self.last_tok, self.temps, self.rngs) = \
                    self._import_fn(
                        self.cache, wire, jnp.asarray(block_ids),
                        self.last_tok, self.temps, self.rngs,
                        jnp.asarray(slot, jnp.int32),
                        jnp.asarray(n, jnp.int32),
                        jnp.asarray(int(imp.manifest["first_token"]),
                                    jnp.int32),
                        jnp.asarray(req.temperature, jnp.float32),
                        jax.random.fold_in(key, 1))
                if self.spec_k:
                    # the wire carries no draft KV: the draft re-prefills
                    # the prompt locally in one forward (it is small by
                    # construction — that is the draft's whole point)
                    dcfg = self._draft_cfg
                    kv = (1, dcfg.max_seq, dcfg.n_heads, dcfg.head_dim)
                    dzero = {
                        f"block_{i}": {"attention": {
                            "k": jnp.zeros(kv, dcfg.dtype),
                            "v": jnp.zeros(kv, dcfg.dtype),
                            "cursor": jnp.zeros((), jnp.int32),
                        }}
                        for i in range(dcfg.n_layers)
                    }
                    if self._draft_full_prefill_fn is None:
                        self._draft_full_prefill_fn = \
                            self._build_draft_full_prefill()
                    pad = nb * self.kv_block_t
                    dids = np.zeros((1, pad), np.int32)
                    dids[0, :n] = req.prompt
                    dsmall = self._draft_full_prefill_fn(
                        self._draft_params, dzero, jnp.asarray(dids))
                    dsmall = {nm: {"attention": {
                        "k": l["attention"]["k"],
                        "v": l["attention"]["v"]}}
                        for nm, l in dsmall.items()}
                    self.draft_cache = self._draft_adopt_fn(
                        self.draft_cache, dsmall,
                        jnp.asarray([slot], jnp.int32),
                        jnp.asarray([n], jnp.int32))
            except Exception as e:
                self._free.append(slot)
                self._tables[slot, :] = self._alloc.trash
                self._slot_res.pop(slot, None)
                self._ub_cursor[slot] = 0
                self._alloc.release(res)
                _fail(req, e)
                continue
            now = time.perf_counter()
            self._active[slot] = req
            if req.submit_at is not None:
                METRICS.histogram(
                    "serving_queue_wait_seconds", buckets=QUEUE_WAIT_BUCKETS,
                ).observe(now - req.submit_at, trace_id=_trace_id(req))
            METRICS.counter("serving_kv_import_total").inc()
            _ev(req, "admitted", slot=slot)
            _ev(req, "kv_import", blocks=int(nb))
            events.append(("first",
                           np.asarray([imp.manifest["first_token"]], np.int32),
                           [(req, slot)], now))
        self._set_occupancy()
        return events

    def _grant_active(self, tokens: int) -> None:
        """Advance every active slot's cursor upper bound by the tokens the
        next dispatch may write and grant the blocks that frontier needs —
        BEFORE the dispatch snapshots the table. The bound (not the exact
        data-dependent cursor, which spec rounds make device-resident)
        drives granting; positions past ``res.total`` stay on trash, which
        only retired-but-undrained rows can reach."""
        if not self.paged:
            return
        max_seq = self.cfg.max_seq
        for slot in self._active:
            res = self._slot_res.get(slot)
            if res is None:
                continue
            ub = min(int(self._ub_cursor[slot]) + tokens, max_seq)
            self._ub_cursor[slot] = ub
            base = len(res.granted)
            for off, blk in enumerate(
                    self._alloc.grant(res, self._alloc.blocks_for(ub))):
                self._tables[slot, base + off] = blk

    def _set_occupancy(self) -> None:
        active = len(self._active)
        METRICS.gauge("serving_continuous_active_slots",
                      replica=self.engine_id).set(active)
        METRICS.gauge("serving_slot_occupancy", replica=self.engine_id).set(
            active / self.slots if self.slots else 0.0)

    def _retire(self, slot: int) -> None:
        req = self._active.pop(slot)
        self._free.append(slot)
        if self.paged:
            # retire-ordering invariant: redirect the table row to TRASH
            # before the blocks return to the free list. Later dispatches
            # snapshot the trashed table, so a block re-granted to another
            # slot can only be written by (a) dispatches issued before this
            # retire — which execute before the new slot's adopt overwrites
            # the block (device streams run in issue order) — or (b) the
            # new slot itself. Never a corrupting interleave.
            self._tables[slot, :] = self._alloc.trash
            res = self._slot_res.pop(slot, None)
            if res is not None:
                self._alloc.release(res)
            self._ub_cursor[slot] = 0
        req.done_at = time.perf_counter()
        if req.finish_reason is None:
            req.finish_reason = "ok"
        if req.submit_at is not None:
            METRICS.histogram("serving_request_seconds").observe(
                req.done_at - req.submit_at, trace_id=_trace_id(req))
        if req.span is not None:
            _ev(req, "retired", slot=slot)
            req.span.set("generated_tokens", len(req.tokens))
            req.span.set("finish_reason", req.finish_reason)
            TRACER.end_span(req.span)
            req.span = None
        req.done.set()
        req._notify()
        METRICS.counter("serving_continuous_requests_total").inc()
        self._set_occupancy()

    def _set_queue_gauge(self) -> None:
        # every _pending mutation must republish the depth: the router's
        # least-loaded policy reads this gauge, and a stale value after a
        # reap leaves a healthy replica advertising phantom load (so no
        # breaker probe ever routes back to it)
        METRICS.gauge("serving_queue_depth",
                      replica=self.engine_id).set(len(self._pending))

    def _reap_pending(self) -> None:
        """Shed queued requests that will never need a slot: expired
        deadlines fail fast with DeadlineExceeded, abandoned clients with
        RequestCancelled — neither ever occupies a decode row."""
        if not self._pending:
            return
        kept: "collections.deque[_Request]" = collections.deque()
        for req in self._pending:
            if req.cancel_requested:
                METRICS.counter("serving_cancelled_total").inc()
                _ev(req, "cancelled", stage="queued")
                req.finish_reason = "cancelled"
                _fail(req, RequestCancelled("cancelled while queued"))
            elif req.expired():
                METRICS.counter("serving_deadline_expired_total",
                                stage="queued").inc()
                _ev(req, "deadline_expired", stage="queued")
                req.finish_reason = "deadline"
                _fail(req, DeadlineExceeded(
                    "deadline expired while queued (never admitted)"))
            else:
                kept.append(req)
        self._pending = kept
        self._set_queue_gauge()

    def _reap_active(self) -> None:
        """Free the slot of any in-flight request whose deadline expired
        or whose future was abandoned — within ONE loop iteration (≤ one
        decode chunk) of the event. The request completes with its partial
        tokens (done, no error); tokens the pipeline already dispatched
        for the row are counted as wasted when their events surface."""
        for slot, req in list(self._active.items()):
            if req.cancel_requested:
                req.finish_reason = "cancelled"
                METRICS.counter("serving_cancelled_total").inc()
                _ev(req, "cancelled", stage="decoding",
                    partial_tokens=len(req.tokens))
                self._retire(slot)
            elif req.expired():
                req.finish_reason = "deadline"
                METRICS.counter("serving_deadline_expired_total",
                                stage="decoding").inc()
                _ev(req, "deadline_expired", stage="decoding",
                    partial_tokens=len(req.tokens))
                self._retire(slot)

    @property
    def _batch_cap(self) -> int:
        """Queue depth at which BATCH requests shed; interactive keeps the
        full ``max_pending`` — the reserved fraction."""
        return max(1, int(self.max_pending * (1.0 - self.interactive_reserve)))

    def _enqueue_pendings(self, reqs: List[_Request]) -> None:
        for req in reqs:
            if self.max_pending:
                depth = len(self._pending)
                cap = (self._batch_cap if req.priority == "batch"
                       else self.max_pending)
                if depth >= cap:
                    METRICS.counter("serving_shed_total",
                                    priority=req.priority).inc()
                    _ev(req, "shed", priority=req.priority, depth=depth)
                    _fail(req, FleetSaturated(
                        f"engine queue full ({depth} >= {cap} "
                        f"for priority={req.priority})"))
                    continue
            self._pending.append(req)

    def _next_wave(self, n: int) -> List[_Request]:
        """Interactive-first admission: fill up to ``n`` free slots from
        the interactive pendings before any batch request is considered,
        so a batch backlog cannot starve interactive TTFT."""
        if len(self._pending) <= n:
            wave = list(self._pending)
            self._pending.clear()
            return wave
        wave = [r for r in self._pending if r.priority != "batch"][:n]
        if len(wave) < n:
            wave.extend([r for r in self._pending
                         if r.priority == "batch"][: n - len(wave)])
        for r in wave:
            self._pending.remove(r)
        return wave

    def _shutdown(self, cause: str) -> None:
        """Fail everything in flight, pending, and still queued — all with
        the SAME cause, so a device failure is debuggable from any failed
        caller, not only the in-flight ones."""
        if self._chunked is not None:
            # mid-prefill request: in neither _active nor _pending — it
            # would hang its caller if this path forgot it
            cp = self._chunked
            self._abort_chunked(cp)
            _fail(cp.req, EngineClosed(cause))
        for req in self._active.values():
            _fail(req, EngineClosed(cause))
        self._active.clear()
        while self._pending:
            _fail(self._pending.popleft(), EngineClosed(cause))
        while self._imports:
            _fail(self._imports.popleft().req, EngineClosed(cause))
        self._set_queue_gauge()
        while True:
            try:
                rest = self._queue.get_nowait()
            except queue.Empty:
                return
            if isinstance(rest, _Import):
                _fail(rest.req, EngineClosed(cause))
            elif rest is not None and rest is not _DRAIN:
                for req in rest:
                    _fail(req, EngineClosed(cause))

    def _process_event(self, event: Tuple[str, Any, Any, float]) -> None:
        """Consume one pipelined event in dispatch order. ``first``: fetch
        an admission group's first tokens (appended before any of that
        request's chunk tokens — FIFO order guarantees it). ``chunk``:
        fetch a token block and retire against the DISPATCH-TIME snapshot —
        a row whose request finished in an earlier event is a discarded
        tail; a row adopted after the dispatch is not in the snapshot."""
        kind, dev, meta, dispatched_at = event
        widths = None
        if kind == "spec":
            # one speculative round: [slots, spec_k] candidate tokens plus
            # the per-slot accepted width m (1..spec_k) — only the first
            # m are real, the rest were refuted by the verify forward
            toks_dev, acc_dev = dev
            block = np.asarray(toks_dev)
            widths = np.asarray(acc_dev)
        else:
            block = np.asarray(dev)  # host fetch (async copy started at dispatch)
        now = time.perf_counter()
        if kind == "first":
            for (req, slot), tok in zip(meta, block):
                if req.done.is_set():
                    # reaped (deadline/cancel) between admission and this
                    # event — its prefill token was computed for nobody
                    if req.finish_reason in ("deadline", "cancelled"):
                        METRICS.counter(
                            "serving_wasted_decode_tokens_total").inc()
                    continue
                req.tokens.append(int(tok))
                req.first_token_at = req.last_token_at = now
                METRICS.counter("serving_tokens_out_total").inc()
                if req.submit_at is not None:
                    METRICS.histogram(
                        "serving_ttft_seconds", buckets=TTFT_BUCKETS
                    ).observe(now - req.submit_at, trace_id=_trace_id(req))
                _ev(req, "first_token")
                hit_eos = req.eos_id is not None and req.tokens[-1] == req.eos_id
                if req.max_new_tokens <= 1 or hit_eos:
                    # the slot was activated at admission, so the normal
                    # retirement path applies
                    self._retire(slot)
            return
        # dispatch→fetch-complete latency of one pipelined decode chunk
        METRICS.histogram(
            "serving_decode_chunk_seconds", buckets=DECODE_CHUNK_BUCKETS
        ).observe(now - dispatched_at)
        for slot, req in meta.items():
            # usable tokens this row produced: the whole chunk, or the
            # accepted prefix of a speculative round
            width = int(widths[slot]) if widths is not None else block.shape[1]
            if widths is not None and not req.done.is_set():
                # accept-rate numerators: spec_k - 1 verifiable drafts per
                # round; width - 1 of them accepted (the +1 is the target's
                # own token, drafted or not)
                METRICS.counter("serving_spec_tokens_drafted_total").inc(
                    self.spec_k - 1)
                if width > 1:
                    METRICS.counter("serving_spec_tokens_accepted_total").inc(
                        width - 1)
            if req.done.is_set():
                # retired in an earlier event; this row's whole block was
                # computed for nobody — the engine's "preempted work" cost
                METRICS.counter("serving_discarded_tail_tokens_total").inc(
                    width)
                if req.finish_reason in ("deadline", "cancelled"):
                    # tokens generated past an expired deadline / abandoned
                    # future — the goodput-loss counter, rolled up with the
                    # shed/expiry waste into the serving token-goodput view
                    # (monitoring/goodput.serving_goodput_view, surfaced at
                    # GET /debug/goodput and in the dashboard)
                    METRICS.counter("serving_wasted_decode_tokens_total").inc(
                        width)
                continue
            appended = 0
            for j in range(width):
                tok = int(block[slot, j])
                req.tokens.append(tok)
                appended += 1
                hit_eos = req.eos_id is not None and tok == req.eos_id
                if len(req.tokens) >= req.max_new_tokens or hit_eos:
                    # inter-token latency amortized over the block BEFORE
                    # _retire closes the span (one observe, count=n — the
                    # per-token path must not pay per-token metric calls)
                    self._note_tokens(req, appended, now)
                    self._retire(slot)
                    METRICS.counter(
                        "serving_discarded_tail_tokens_total"
                    ).inc(width - j - 1)
                    appended = 0
                    break
            if appended:
                self._note_tokens(req, appended, now)

    def _note_tokens(self, req: _Request, n: int, now: float) -> None:
        METRICS.counter("serving_tokens_out_total").inc(n)
        if req.last_token_at is not None:
            METRICS.histogram(
                "serving_inter_token_seconds", buckets=ITL_BUCKETS
            ).observe((now - req.last_token_at) / n, count=n,
                      trace_id=_trace_id(req))
        req.last_token_at = now

    def _loop(self) -> None:
        events: "collections.deque[Tuple[str, Any, Any, float]]" = collections.deque()

        def chunk_depth() -> int:
            return sum(1 for kind, _, _, _ in events
                       if kind in ("chunk", "spec"))

        while True:
            # drain arrivals into the pending deque; block only when fully
            # idle (no busy-wait). Coalescing the drain is what lets a burst
            # of single submits admit as ONE batched prefill.
            try:
                timeout = (None if not (self._active or self._pending
                                        or events or self._draining
                                        or self._chunked or self._imports)
                           else 0.0)
                while True:
                    item = self._queue.get(timeout=timeout) if timeout is None \
                        else self._queue.get_nowait()
                    if item is None:
                        self._shutdown("batcher closed mid-flight")
                        return
                    if item is _DRAIN:
                        # submits racing the drain land BEFORE the sentinel
                        # (submit checks _closed under the lock that also
                        # enqueues it), so everything still queued here is
                        # part of the handoff set
                        self._draining = True
                    elif isinstance(item, _Import):
                        self._imports.append(item)
                    else:
                        self._enqueue_pendings(item)
                    timeout = 0.0
            except queue.Empty:
                pass
            self._set_queue_gauge()
            try:
                if self.fail_next_step:
                    # chaos crash_replica_mid_decode: poison the iteration;
                    # the handler below fails everything and closes the
                    # engine, exactly like a real device/RPC death
                    self.fail_next_step = False
                    raise RuntimeError("chaos: replica crashed mid-decode")
                if self.step_delay_s > 0:
                    # chaos slow_replica: stall the dispatch loop so
                    # deadlines expire and the fleet's breaker sees a
                    # slow replica
                    time.sleep(min(self.step_delay_s, 5.0))
                # reap BEFORE admission: an expired queued request must
                # never take a slot, and an expired/abandoned in-flight
                # one frees its slot for this very wave
                self._reap_pending()
                self._reap_active()
                dispatched = False
                if self._imports and self._free and not self._draining:
                    # wire imports admit before fresh prompts: their
                    # prefill compute is already spent — leaving them
                    # queued behind new admissions would waste it twice
                    events.extend(self._admit_imports())
                    dispatched = True
                if self._free and self._pending and not self._draining:
                    wave = self._next_wave(len(self._free))
                    self._set_queue_gauge()
                    events.extend(self._admit_wave(wave))
                    dispatched = True
                if self._chunked is not None:
                    # ONE prefill chunk per iteration, interleaved between
                    # decode dispatches — TTFT of the chatty slots stops
                    # being hostage to the longest prompt (drain included:
                    # the mid-prefill request is in-flight work)
                    events.extend(self._advance_chunked())
                    dispatched = True
                if self._active:
                    # one CHUNK of decode steps (or one speculative round)
                    # for every slot (inactive rows compute too — static
                    # shapes are the TPU contract; their outputs are
                    # discarded when processed against the snapshot)
                    self._grant_active(self.spec_k if self.spec_k
                                       else self.chunk)
                    extra = ((jnp.asarray(self._tables),)
                             if self.paged else ())
                    if self.spec_k:
                        (self.cache, self.draft_cache, self.last_tok,
                         self.rngs, toks, acc) = self._spec_fn(
                            self.params, self._draft_params, self.cache,
                            self.draft_cache, self.last_tok, self.temps,
                            self.rngs, *extra)
                        try:
                            toks.copy_to_host_async()
                            acc.copy_to_host_async()
                        except Exception:
                            pass
                        events.append(("spec", (toks, acc),
                                       dict(self._active),
                                       time.perf_counter()))
                    else:
                        self.cache, self.last_tok, self.rngs, toks = \
                            self._step_fn(self.params, self.cache,
                                          self.last_tok, self.temps,
                                          self.rngs, *extra)
                        try:
                            toks.copy_to_host_async()
                        except Exception:
                            pass
                        events.append(("chunk", toks, dict(self._active),
                                       time.perf_counter()))
                    dispatched = True
                # keep the dispatch frontier at most ``pipeline`` chunks
                # ahead of the processed state; when nothing new could be
                # dispatched, drain one event so the pipeline empties
                while chunk_depth() > self.pipeline:
                    self._process_event(events.popleft())
                if not dispatched and events:
                    self._process_event(events.popleft())
                if (self._draining and not self._active and not events
                        and self._chunked is None):
                    # drain complete: every in-flight slot ran to its
                    # budget/EOS; park the unserved pendings (futures still
                    # open) for the caller and zero this replica's gauges.
                    # Unadmitted KV imports park too — their ``kv_blob`` is
                    # set, so the fleet re-imports them on a surviving
                    # decode replica instead of re-running prefill.
                    self._handoff.extend(self._pending)
                    self._pending.clear()
                    self._handoff.extend(imp.req for imp in self._imports
                                         if not imp.req.done.is_set())
                    self._imports.clear()
                    self._set_queue_gauge()
                    self._set_occupancy()
                    return
            except Exception as e:
                # a device/RPC failure must not wedge the engine silently:
                # fail everything in flight, pending, and queued; refuse
                # new work
                with self._lock:
                    self._closed = True
                self._shutdown(f"engine step failed: {e}")
                return
