"""SLO-driven fleet autoscaler (ISSUE 6).

Closes the loop the observability plane opened: the engine exports
``serving_ttft_seconds`` / ``serving_queue_wait_seconds`` histograms
(PR 4); this module turns their tail quantiles into replica-count
decisions with enough hysteresis that a boundary-riding quantile cannot
flap the fleet.

Windowed quantiles, not lifetime ones: the registry's histograms are
cumulative, so one historic breach would otherwise hold the p99 above
the SLO forever and the fleet could never scale back down. Each
``tick()`` snapshots the aggregated bucket counts
(``MetricsRegistry.histogram_counts``) and quantiles the DELTA since the
previous tick — the same ``rate()``-window trick PromQL recording rules
use, done in-process.

Hysteresis (all tunable on :class:`AutoscalerConfig`):

- scale UP only after ``breach_ticks`` consecutive windows whose p-``q``
  exceeds the SLO,
- scale DOWN only after ``idle_ticks`` consecutive windows that are
  either traffic-free or comfortably below ``scale_down_margin * SLO``,
- the band between ``margin*SLO`` and ``SLO`` holds (both streaks
  reset) — a quantile sitting on the boundary moves nothing,
- ``cooldown_ticks`` after any action before the next one (scaling has
  real cost: a new replica compiles; a drain moves requests).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..runtime.metrics import METRICS, quantile_from_counts

TTFT_METRIC = "serving_ttft_seconds"
QUEUE_WAIT_METRIC = "serving_queue_wait_seconds"
INTER_TOKEN_METRIC = "serving_inter_token_seconds"


@dataclass
class AutoscalerConfig:
    ttft_slo: float = 1.0          # p-q TTFT ceiling (seconds)
    queue_wait_slo: float = 0.5    # p-q queue-wait ceiling (seconds)
    #: p-q inter-token gap ceiling — the DECODE pool's SLO on a
    #: disaggregated fleet (TTFT belongs to the prefill pool there)
    inter_token_slo: float = 0.1
    quantile: float = 0.99
    scale_down_margin: float = 0.5  # idle iff p-q < margin * SLO (or no traffic)
    breach_ticks: int = 2
    idle_ticks: int = 3
    cooldown_ticks: int = 2


@dataclass
class _Window:
    """One tick's view of one SLO histogram. ``stale`` means the source
    could not produce a TRUSTWORTHY window — no fresh federated series, a
    frozen timestamp (scrape gap), a counter reset — which is categorically
    different from ``value is None`` with fresh data (genuinely no traffic):
    stale holds the fleet, no-traffic counts toward scale-down."""
    value: Optional[float]  # windowed quantile; None with no traffic/window
    samples: int
    stale: bool = False


class RegistryWindowSource:
    """The original in-process source: snapshot the registry's cumulative
    bucket counts each tick and quantile the delta since the previous one."""

    name = "registry"

    def __init__(self, registry=METRICS):
        self._registry = registry
        self._prev: Dict[str, Tuple[List[int], int]] = {}

    def window(self, metric: str, q: float) -> _Window:
        snap = self._registry.histogram_counts(metric)
        if snap is None:
            return _Window(None, 0)
        buckets, counts, total = snap
        prev = self._prev.get(metric)
        self._prev[metric] = (counts, total)
        if prev is None:
            return _Window(None, 0)  # first sight: no window yet
        dcounts = [c - p for c, p in zip(counts, prev[0])]
        dtotal = total - prev[1]
        if dtotal <= 0:
            return _Window(None, 0)
        return _Window(quantile_from_counts(buckets, dcounts, dtotal, q), dtotal)


class FederatedWindowSource:
    """Scrape-backed source: quantile the FLEET-WIDE histograms out of the
    monitoring plane's TSDB instead of whatever registry happens to share
    the autoscaler's process. Sums the latest fresh ``<metric>_bucket``
    value per ``le`` across instances and windows the delta between ticks.

    Staleness is first-class: when the scraper stopped delivering (no fresh
    series, or the newest sample timestamp did not advance since the last
    tick), the window reports ``stale=True`` and the autoscaler HOLDS — a
    scrape gap must never read as "the fleet went idle" (the no-flap
    regression in tests/test_monitoring.py)."""

    name = "federated"

    def __init__(self, tsdb, matchers: Optional[Dict] = None):
        self.tsdb = tsdb
        self.matchers = matchers
        #: metric → (per-le cumulative sums, newest sample ts)
        self._prev: Dict[str, Tuple[Dict[float, float], float]] = {}

    def _cumulative(self, metric: str) -> Tuple[Dict[float, float], Optional[float]]:
        per_le: Dict[float, float] = {}
        newest: Optional[float] = None
        for labels, ts, value in self.tsdb.latest(f"{metric}_bucket", self.matchers):
            le_raw = labels.get("le")
            if le_raw is None:
                continue
            le = float("inf") if le_raw in ("+Inf", "inf") else float(le_raw)
            per_le[le] = per_le.get(le, 0.0) + value
            newest = ts if newest is None else max(newest, ts)
        return per_le, newest

    def window(self, metric: str, q: float) -> _Window:
        per_le, newest = self._cumulative(metric)
        prev = self._prev.get(metric)
        if not per_le or newest is None:
            # nothing fresh in the TSDB: scrape gap, not idleness
            return _Window(None, 0, stale=True)
        self._prev[metric] = (per_le, newest)
        if prev is None:
            return _Window(None, 0, stale=True)  # first sight: no window yet
        prev_le, prev_ts = prev
        if newest <= prev_ts:
            # every series is frozen since last tick — the target set went
            # dark between scrapes; frozen counts must not quantile to
            # "no traffic"
            return _Window(None, 0, stale=True)
        deltas = {le: v - prev_le.get(le, 0.0) for le, v in per_le.items()}
        if any(d < 0 for d in deltas.values()) or float("inf") not in deltas:
            # counter reset (replica restart) — skip one window
            return _Window(None, 0, stale=True)
        finite = sorted(le for le in deltas if le != float("inf"))
        total = int(round(deltas[float("inf")]))
        if total <= 0:
            return _Window(None, 0)  # fresh data, zero traffic: genuine idle
        counts: List[int] = []
        prev_cum = 0.0
        for le in finite:
            counts.append(int(round(deltas[le] - prev_cum)))
            prev_cum = deltas[le]
        counts.append(int(round(deltas[float("inf")] - prev_cum)))
        return _Window(quantile_from_counts(tuple(finite), counts, total, q), total)


class SLOAutoscaler:
    """Drives ``fleet.scale_to`` from windowed SLO quantiles.

    Deterministic by construction: ``tick()`` does one evaluation (tests
    and the e2e driver call it directly); ``start(interval)`` runs it on
    a timer thread for real deployments. ``source`` selects where the
    quantiles come from: the in-process registry (default) or a
    :class:`FederatedWindowSource` over the monitoring plane's TSDB.
    """

    def __init__(self, fleet, config: Optional[AutoscalerConfig] = None,
                 registry=METRICS, source=None):
        self.fleet = fleet
        self.config = config or AutoscalerConfig()
        self._registry = registry
        self._source = source if source is not None else RegistryWindowSource(registry)
        #: per-pool hysteresis state ("unified" for a homogeneous fleet;
        #: "prefill"/"decode" each keep their OWN streaks and cooldown on a
        #: disaggregated one — a prefill burst must not cool down a decode
        #: decision, and vice versa)
        self._pool_state: Dict[str, Dict[str, int]] = {}
        self._ticks = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        #: last tick's evaluation, surfaced in /debug/fleet
        self.last: Dict = {}

    # -- windowed quantile ---------------------------------------------------
    def _window(self, name: str) -> _Window:
        return self._source.window(name, self.config.quantile)

    # -- one evaluation ------------------------------------------------------
    def _evaluate(self, pool: str, windows: List[Tuple[_Window, float]],
                  size: int, lo: int, hi: int,
                  pass_pool: bool) -> Tuple[Optional[str], Dict]:
        """Run one pool's hysteresis state machine over its (window, SLO)
        pairs; scales the fleet and returns ``(decision, debug_state)``.
        The staleness-holds-streaks discipline (PR 10) applies per pool."""
        cfg = self.config
        st = self._pool_state.setdefault(
            pool, {"breach": 0, "idle": 0, "cooldown": 0})
        stale = any(w.stale for w, _ in windows)
        breach = (not stale
                  and any(w.value is not None and w.value > slo
                          for w, slo in windows))
        idle = (not stale and not breach
                and all(w.value is None
                        or w.value < cfg.scale_down_margin * slo
                        for w, slo in windows))
        if stale:
            # an untrustworthy window (scrape gap / frozen series) HOLDS:
            # both streaks reset, no decision — staleness is not idleness
            st["breach"] = st["idle"] = 0
        elif breach:
            st["breach"] += 1
            st["idle"] = 0
        elif idle:
            st["idle"] += 1
            st["breach"] = 0
        else:  # hysteresis band between margin*SLO and SLO: hold
            st["breach"] = st["idle"] = 0
        if st["cooldown"] > 0:
            st["cooldown"] -= 1

        decision: Optional[str] = None
        reason = ""
        if (st["breach"] >= cfg.breach_ticks and st["cooldown"] == 0
                and size < hi):
            reason = "slo_breach"
            decision = "up"
        elif (st["idle"] >= cfg.idle_ticks and st["cooldown"] == 0
              and size > lo):
            reason = "idle"
            decision = "down"
        if decision is not None:
            target = size + 1 if decision == "up" else size - 1
            if pass_pool:
                self.fleet.scale_to(target, reason=reason, pool=pool)
            else:
                self.fleet.scale_to(target, reason=reason)
            st["breach"] = st["idle"] = 0
            st["cooldown"] = cfg.cooldown_ticks
            METRICS.counter("fleet_autoscale_total", direction=decision,
                            reason=reason, pool=pool).inc()
        state = {"stale": stale, "breach_streak": st["breach"],
                 "idle_streak": st["idle"], "cooldown": st["cooldown"],
                 "decision": decision}
        return decision, state

    def tick(self) -> Optional[str]:
        """Evaluate one window; returns ``"up"``/``"down"``/None (on a
        disaggregated fleet: the prefill decision if any, else decode's).

        A unified fleet scales off TTFT + queue-wait as before. A
        disaggregated fleet (``fleet.pools``) evaluates each pool against
        the signal that pool actually owns: prefill off the TTFT p-q
        (prefill compute IS time-to-first-token), decode off the
        inter-token p-q (decode slot contention stretches the gap between
        tokens) — each with independent streaks and cooldown."""
        cfg = self.config
        self._ticks += 1
        pools = getattr(self.fleet, "pools", None)
        if pools:
            ttft = self._window(TTFT_METRIC)
            itl = self._window(INTER_TOKEN_METRIC)
            dp, sp = self._evaluate(
                "prefill", [(ttft, cfg.ttft_slo)],
                self.fleet.pool_size("prefill"), 1,
                self.fleet.max_replicas, pass_pool=True)
            dd, sd = self._evaluate(
                "decode", [(itl, cfg.inter_token_slo)],
                self.fleet.pool_size("decode"), 1,
                self.fleet.max_replicas, pass_pool=True)
            decision = dp or dd
            self.last = {
                "tick": self._ticks,
                "source": self._source.name,
                "ttft_p": ttft.value, "ttft_samples": ttft.samples,
                "inter_token_p": itl.value, "inter_token_samples": itl.samples,
                "prefill": dict(sp, replicas=self.fleet.pool_size("prefill")),
                "decode": dict(sd, replicas=self.fleet.pool_size("decode")),
                "decision": decision,
            }
            return decision
        ttft = self._window(TTFT_METRIC)
        qwait = self._window(QUEUE_WAIT_METRIC)
        decision, st = self._evaluate(
            "unified", [(ttft, cfg.ttft_slo), (qwait, cfg.queue_wait_slo)],
            self.fleet.desired_replicas, self.fleet.min_replicas,
            self.fleet.max_replicas, pass_pool=False)
        self.last = {
            "tick": self._ticks,
            "source": self._source.name,
            "stale": st["stale"],
            "ttft_p": ttft.value, "ttft_samples": ttft.samples,
            "queue_wait_p": qwait.value, "queue_wait_samples": qwait.samples,
            "breach_streak": st["breach_streak"],
            "idle_streak": st["idle_streak"],
            "cooldown": st["cooldown"],
            "replicas": self.fleet.desired_replicas,
            "decision": decision,
        }
        return decision

    # -- background mode -----------------------------------------------------
    def start(self, interval: float = 5.0) -> None:
        if self._thread is not None:
            return

        def loop() -> None:
            while not self._stop.wait(interval):
                try:
                    self.tick()
                except Exception:
                    # an autoscaler bug must degrade to "fleet stays at its
                    # current size", never take the serving path down
                    pass

        self._thread = threading.Thread(target=loop, name="slo-autoscaler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
