"""Serving-path error taxonomy (ISSUE 9).

Every class subclasses :class:`RuntimeError` on purpose: the pre-existing
contract is "engine trouble surfaces as RuntimeError → the HTTP layer's
503", and callers (GenerativeModel.predict, EngineFleet.submit, tests)
match on that. The subclasses let the overload plane distinguish *why* a
request died — queue shed vs deadline vs shutdown — without breaking any
``except RuntimeError`` handler that predates them.

Kept dependency-free (no jax, no metrics) so the fleet/router layers can
import it without pulling the engine's heavy imports.
"""

from __future__ import annotations

from typing import Optional


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before it produced a full result.

    Raised from ``result()`` when the deadline expired while the request
    was still QUEUED (it never occupied a slot — fail fast). A deadline
    expiring MID-DECODE does not raise: the engine frees the slot and the
    request completes with its partial tokens.
    """


class RequestCancelled(RuntimeError):
    """The client abandoned the request (``cancel()`` / disconnect) while
    it was still queued. In-flight cancellations complete with partial
    tokens instead."""


class EngineClosed(RuntimeError):
    """The engine shut down (close(), drain, or a fatal device error)
    with this request still unserved. Distinct from a per-request timeout:
    retrying the same engine is pointless, retry another replica."""


class FleetSaturated(RuntimeError):
    """Every admissible replica is at capacity — shed load.

    ``retry_after_s`` is the router's queue-drain estimate, surfaced by
    the HTTP layer as a ``Retry-After`` header on the 503 so well-behaved
    clients back off for roughly one drain interval instead of hammering.
    """

    def __init__(self, message: str, retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s
