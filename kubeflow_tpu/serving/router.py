"""Prefix-aware fleet router (ISSUE 6).

Sits between the HTTP predict dispatch and the engine replicas. Two
policies, in order:

- ``prefix``: requests whose prompt prefix hashes to a prefix a replica
  served recently go back to THAT replica — its per-slot KV cache rows
  (and, for repeated prompts, the XLA-compiled prefill for the bucket)
  are warm, so TTFT skips the cold path. "Evaluating Kubernetes
  Performance for GenAI Inference" (PAPERS.md) measures exactly this
  affinity/locality effect dominating LLM tail latency on K8s.
- ``least_loaded``: otherwise (or when the prefix owner is saturated)
  pick the ready replica with the lowest live load score, read straight
  off the ``serving_queue_depth`` / ``serving_slot_occupancy`` gauges
  each engine publishes under its ``replica`` label — the router trusts
  the observability plane rather than keeping shadow accounting.

When EVERY ready replica is saturated (queue depth at or past
``max_queue_depth``) the router refuses with :class:`FleetSaturated`
rather than piling onto a queue that already blows the SLO — the HTTP
layer maps it to 503 and the autoscaler's breach streak takes it from
there.
"""

from __future__ import annotations

import zlib
from collections import OrderedDict
from typing import Optional, Sequence, Tuple

import numpy as np

from ..runtime.metrics import METRICS
from .errors import FleetSaturated  # noqa: F401 — re-export, historic home

#: tokens hashed into the affinity key — long enough to separate real
#: system prompts, short enough that "same instruction, different tail"
#: still lands on the warm replica
DEFAULT_PREFIX_LEN = 16

#: per-replica LRU of prefix keys assumed warm; bounded so a long-lived
#: replica doesn't accrete an unbounded claim on every prefix ever seen
PREFIX_CACHE_SIZE = 512

#: Retry-After hint bounds: never tell a client "0" (it would hammer) and
#: never more than a minute (the fleet autoscaler acts well before that)
RETRY_AFTER_MIN_S = 0.5
RETRY_AFTER_MAX_S = 60.0


def prefix_key(prompt_ids: Sequence[int], prefix_len: int = DEFAULT_PREFIX_LEN,
               model_id: str = "") -> int:
    """Stable hash of ``model_id || first prefix_len token ids`` (crc32 of
    the int32 bytes seeded with the model id's crc — deterministic across
    processes, unlike ``hash()``). Keying per model means multiplexed
    models can never collide on prefix hash and poison each other's cache
    affinity; ``model_id=""`` reduces to the historic single-model key."""
    head = np.asarray(prompt_ids, np.int32).reshape(-1)[:prefix_len]
    return zlib.crc32(head.tobytes(), zlib.crc32(model_id.encode("utf-8")))


class PrefixRouter:
    """Pure routing policy over the fleet's replica handles.

    The fleet calls ``route(handles, prompt_ids)`` under its own lock and
    gets back ``(handle, policy)``. Handles must expose ``gauge_id`` (the
    ``replica`` gauge label), ``state`` and ``prefixes`` (an OrderedDict
    LRU this router owns the contents of).
    """

    def __init__(self, prefix_len: int = DEFAULT_PREFIX_LEN,
                 max_queue_depth: int = 32,
                 prefix_cache_size: int = PREFIX_CACHE_SIZE,
                 interactive_reserve: float = 0.25,
                 registry=METRICS):
        self.prefix_len = int(prefix_len)
        self.max_queue_depth = int(max_queue_depth)
        self.prefix_cache_size = int(prefix_cache_size)
        # batch requests saturate at (1 - reserve) * max_queue_depth so
        # interactive always has queue headroom a batch flood can't take
        self.interactive_reserve = min(max(float(interactive_reserve), 0.0), 1.0)
        self._registry = registry

    def depth_limit(self, priority: str) -> int:
        if priority == "batch":
            return max(1, int(self.max_queue_depth
                              * (1.0 - self.interactive_reserve)))
        return self.max_queue_depth

    def retry_after_hint(self, handles: Sequence) -> float:
        """Seconds until the least-loaded queue plausibly drains: its depth
        times the observed mean request latency (the queue-drain rate the
        engines actually sustain), clamped to a sane window. Emitted as the
        ``Retry-After`` header on saturation 503s."""
        depth = min((self.queue_depth(h) for h in handles),
                    default=float(self.max_queue_depth))
        mean_s = self._registry.histogram("serving_request_seconds").mean
        if mean_s <= 0.0:
            mean_s = 0.5  # no completions observed yet — guess, don't say 0
        return min(RETRY_AFTER_MAX_S,
                   max(RETRY_AFTER_MIN_S, depth * mean_s))

    # -- live load, straight from the gauges --------------------------------
    def queue_depth(self, handle) -> float:
        return self._registry.value("serving_queue_depth",
                                    replica=handle.gauge_id)

    def load_score(self, handle) -> float:
        """Queued requests plus fractional slot occupancy: queue depth
        dominates (each unit is a whole parked request), occupancy breaks
        ties between empty-queue replicas."""
        return self.queue_depth(handle) + self._registry.value(
            "serving_slot_occupancy", replica=handle.gauge_id)

    def route(self, handles: Sequence, prompt_ids: Sequence[int],
              exclude: Optional[str] = None,
              priority: str = "interactive",
              model_id: str = "") -> Tuple[object, str]:
        """Pick a replica for ``prompt_ids``; returns ``(handle, policy)``.

        ``exclude`` drops one replica id from consideration (re-queueing a
        drained replica's pendings must not route them back to it).
        ``priority`` shapes the saturation threshold: batch requests shed
        at the reserved-fraction depth, interactive at the full depth.
        ``model_id`` scopes BOTH policies to one multiplexed model: the
        prefix key is salted with it, and least-loaded scoring only ever
        sees same-model replicas (handles carrying a different
        ``model_id`` are dropped here even if the fleet passed them)."""
        ready = [h for h in handles
                 if h.state == "ready" and h.id != exclude
                 and getattr(h, "model_id", "") == model_id]
        if not ready:
            raise FleetSaturated("no ready replicas in the fleet")
        limit = self.depth_limit(priority)
        key = prefix_key(prompt_ids, self.prefix_len, model_id)
        owner = next((h for h in ready if key in h.prefixes), None)
        if owner is not None and self.queue_depth(owner) < limit:
            policy = "prefix"
            chosen = owner
            METRICS.counter("fleet_prefix_hits_total").inc()
        else:
            candidates = [h for h in ready
                          if self.queue_depth(h) < limit]
            if not candidates:
                METRICS.counter("fleet_saturated_total").inc()
                METRICS.counter("serving_shed_total", priority=priority).inc()
                raise FleetSaturated(
                    f"all {len(ready)} ready replicas at max queue depth "
                    f"{limit} for priority={priority}",
                    retry_after_s=self.retry_after_hint(ready))
            # owner existed but was saturated → distinct policy label so
            # the miss is visible next to the hit counter
            policy = "prefix_spill" if owner is not None else "least_loaded"
            chosen = min(candidates, key=self.load_score)
        self._note_prefix(chosen, key)
        METRICS.counter("fleet_routed_total", policy=policy).inc()
        return chosen, policy

    def note_prefix(self, handle, prompt_ids: Sequence[int],
                    model_id: str = "") -> None:
        """Record warm-prefix ownership outside :meth:`route` — the fleet
        calls this when a KV handoff moves a request's warm state to a
        decode replica the router never picked itself."""
        self._note_prefix(handle, prefix_key(prompt_ids, self.prefix_len,
                                             model_id))

    def _note_prefix(self, handle, key: int) -> None:
        """Record that ``handle`` now holds the warm state for ``key``
        (LRU, bounded)."""
        cache: "OrderedDict[int, None]" = handle.prefixes
        if key in cache:
            cache.move_to_end(key)
        else:
            cache[key] = None
            while len(cache) > self.prefix_cache_size:
                cache.popitem(last=False)
