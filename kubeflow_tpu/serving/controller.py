"""InferenceService controller: serving workloads as CRs.

Control-plane half of the serving stack: ``InferenceService`` CR
(spec: model name/image/replicas + optional ``tpu`` block) → Deployment +
Service + VirtualService, the same materialization pattern as the
tensorboard controller (reference analog: the TF Serving Deployment the
e2e expects at a stable Service address — testing/test_tf_serving.py reads
the Service cluster IP and POSTs :8500).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

from ..api import meta as apimeta
from ..apiserver.client import Client
from ..runtime.manager import Reconciler, Request, Result
from ..runtime import reconcile as rh
from ..tpu.topology import parse_topology

SERVING_API = "serving.kubeflow.org/v1alpha1"
SERVING_PORT = 8500


@dataclass
class ServingConfig:
    use_istio: bool = True
    istio_gateway: str = "kubeflow/kubeflow-gateway"
    cluster_domain: str = "cluster.local"
    default_image: str = "kubeflow-tpu/jax-serving:latest"


class InferenceServiceReconciler(Reconciler):
    FOR = (SERVING_API, "InferenceService")
    OWNS = [
        ("apps/v1", "Deployment"),
        ("v1", "Service"),
        ("networking.istio.io/v1beta1", "VirtualService"),
    ]

    def __init__(self, config: Optional[ServingConfig] = None):
        self.config = config or ServingConfig()

    def reconcile(self, client: Client, req: Request) -> Result:
        isvc = client.get_opt(*self.FOR, req.name, req.namespace)
        if isvc is None:
            return Result()
        try:
            dep = self._generate_deployment(isvc)
        except (ValueError, KeyError, TypeError) as e:
            fresh = apimeta.deepcopy(isvc)
            fresh["status"] = {
                "conditions": [
                    {"type": "Failed", "status": "True", "reason": "InvalidSpec", "message": str(e)}
                ]
            }
            client.update_status(fresh)
            return Result()
        rh.reconcile_object(client, dep, isvc)
        rh.reconcile_object(client, self._generate_service(isvc), isvc)
        if self.config.use_istio:
            rh.reconcile_object(client, self._generate_virtual_service(isvc), isvc)
        self._update_status(client, isvc)
        return Result()

    def _generate_deployment(self, isvc: Dict[str, Any]) -> Dict[str, Any]:
        name, ns = apimeta.name_of(isvc), apimeta.namespace_of(isvc)
        spec = isvc.get("spec", {})
        model = spec.get("model") or name
        replicas = int(spec.get("replicas", 1))
        labels = {"app": "inference", "isvc-name": name}

        container: Dict[str, Any] = {
            "name": "server",
            "image": spec.get("image", self.config.default_image),
            # spec.replicas reaches the fleet INSIDE each server process:
            # serving/server.py main() sizes its EngineFleet from it
            "args": [f"--model={model}", f"--port={SERVING_PORT}",
                     f"--replicas={replicas}"],
            "ports": [{"containerPort": SERVING_PORT, "name": "http-serving"}],
            "env": [{"name": "MODEL_NAME", "value": model},
                    {"name": "FLEET_REPLICAS", "value": str(replicas)}],
            "readinessProbe": {"httpGet": {"path": "/healthz", "port": SERVING_PORT}},
        }
        pod_spec: Dict[str, Any] = {"containers": [container]}
        tpu = spec.get("tpu")
        if tpu:
            topo = parse_topology(tpu["generation"], tpu["topology"])
            if topo.is_multi_host:
                raise ValueError(
                    "inference deployments are single-host; use topology "
                    f"<= {topo.accelerator.chips_per_host} chips"
                )
            container.setdefault("resources", {})["limits"] = topo.resource_limits()
            pod_spec["nodeSelector"] = topo.node_selector()
            container["env"].append({"name": "JAX_PLATFORMS", "value": "tpu"})

        return apimeta.new_object(
            "apps/v1",
            "Deployment",
            name,
            ns,
            spec={
                "replicas": replicas,
                "selector": {"matchLabels": labels},
                "template": {"metadata": {"labels": labels}, "spec": pod_spec},
            },
        )

    def _generate_service(self, isvc: Dict[str, Any]) -> Dict[str, Any]:
        name, ns = apimeta.name_of(isvc), apimeta.namespace_of(isvc)
        return apimeta.new_object(
            "v1",
            "Service",
            name,
            ns,
            spec={
                "selector": {"app": "inference", "isvc-name": name},
                "ports": [
                    {"name": f"http-{name}", "port": SERVING_PORT, "targetPort": SERVING_PORT}
                ],
            },
        )

    def _generate_virtual_service(self, isvc: Dict[str, Any]) -> Dict[str, Any]:
        name, ns = apimeta.name_of(isvc), apimeta.namespace_of(isvc)
        prefix = f"/serving/{ns}/{name}/"
        return apimeta.new_object(
            "networking.istio.io/v1beta1",
            "VirtualService",
            f"serving-{ns}-{name}",
            ns,
            spec={
                "hosts": ["*"],
                "gateways": [self.config.istio_gateway],
                "http": [
                    {
                        "match": [{"uri": {"prefix": prefix}}],
                        "rewrite": {"uri": "/"},
                        "route": [
                            {
                                "destination": {
                                    "host": f"{name}.{ns}.svc.{self.config.cluster_domain}",
                                    "port": {"number": SERVING_PORT},
                                }
                            }
                        ],
                    }
                ],
            },
        )

    def _update_status(self, client: Client, isvc: Dict[str, Any]) -> None:
        name, ns = apimeta.name_of(isvc), apimeta.namespace_of(isvc)
        dep = client.get_opt("apps/v1", "Deployment", name, ns)
        ready = (dep or {}).get("status", {}).get("readyReplicas", 0)
        desired = int(isvc.get("spec", {}).get("replicas", 1))
        status = {
            "replicas": desired,
            "readyReplicas": ready,
            "url": f"http://{name}.{ns}.svc.{self.config.cluster_domain}:{SERVING_PORT}/v1/models/"
            + (isvc.get("spec", {}).get("model") or name),
            "conditions": [{
                "type": "Ready",
                "status": "True" if ready > 0 else "False",
                "reason": "ReplicasReady" if ready > 0 else "AwaitingReplicas",
                "message": f"{ready}/{desired} replicas ready",
            }],
        }
        if isvc.get("status") != status:
            fresh = apimeta.deepcopy(isvc)
            fresh["status"] = status
            client.update_status(fresh)

def main() -> None:  # python -m kubeflow_tpu.serving.controller
    from ..runtime.bootstrap import run_role

    run_role("serving-controller", InferenceServiceReconciler())


if __name__ == "__main__":
    main()
