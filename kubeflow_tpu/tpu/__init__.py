from .topology import (  # noqa: F401
    ACCELERATORS,
    AcceleratorType,
    SliceTopology,
    parse_topology,
)
from .env import jax_worker_env, coordinator_address  # noqa: F401
