"""TPU accelerator catalog and slice-topology math.

This module replaces the reference's GPU vendor mechanism (spawner
``gpus.vendors`` list + ``nvidia.com/gpu`` limits injection — reference:
crud-web-apps/jupyter/backend/apps/common/form.py:262-287 and
spawner_ui_config.yaml:141-154) with first-class TPU pod-slice topology:
an accelerator catalog (v4/v5e/v5p/v6e), ``AxB[xC]`` topology parsing, and
the host/chip math every other layer consumes:

- the notebook controller sizes StatefulSets as ``replicas = num_hosts``,
- the admission webhook injects ``google.com/tpu: chips_per_host`` limits and
  GKE nodeSelectors,
- the spawner validates user-picked topologies,
- profile quotas count ``requests.google.com/tpu`` in chips.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

RESOURCE_TPU = "google.com/tpu"
NODE_LABEL_ACCELERATOR = "cloud.google.com/gke-tpu-accelerator"
NODE_LABEL_TOPOLOGY = "cloud.google.com/gke-tpu-topology"


@dataclass(frozen=True)
class AcceleratorType:
    """One TPU generation as exposed by GKE node pools."""

    generation: str           # "v5e"
    gke_name: str             # value of cloud.google.com/gke-tpu-accelerator
    dims: int                 # 2 = 2D torus slice topologies, 3 = 3D
    chips_per_host: int       # chips visible to one pod/host in a multi-host slice
    bf16_tflops_per_chip: float   # peak dense bf16 TFLOP/s (MFU denominators)
    hbm_gib_per_chip: int
    max_chips: int            # largest slice
    hbm_gbps_per_chip: float = 0.0  # peak HBM bandwidth GB/s (roofline denominators)

    def topologies(self) -> List["SliceTopology"]:
        return [t for t in _KNOWN_TOPOLOGIES.get(self.generation, [])]


ACCELERATORS: Dict[str, AcceleratorType] = {
    a.generation: a
    for a in [
        AcceleratorType("v4", "tpu-v4-podslice", 3, 4, 275.0, 32, 4096, 1228.0),
        AcceleratorType("v5e", "tpu-v5-lite-podslice", 2, 4, 197.0, 16, 256, 819.0),
        AcceleratorType("v5p", "tpu-v5p-slice", 3, 4, 459.0, 95, 8960, 2765.0),
        AcceleratorType("v6e", "tpu-v6e-slice", 2, 4, 918.0, 32, 256, 1640.0),
    ]
}


@dataclass(frozen=True)
class SliceTopology:
    generation: str
    dims: Tuple[int, ...]

    @property
    def accelerator(self) -> AcceleratorType:
        return ACCELERATORS[self.generation]

    @property
    def label(self) -> str:
        return "x".join(str(d) for d in self.dims)

    @property
    def num_chips(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    @property
    def num_hosts(self) -> int:
        """Pods (= TPU VM hosts) needed for this slice.

        Single-host slices expose all their chips to one pod; multi-host
        slices expose ``chips_per_host`` chips per pod.
        """
        cph = self.accelerator.chips_per_host
        if self.num_chips <= cph:
            return 1
        if self.num_chips % cph:
            raise ValueError(f"{self.generation} {self.label}: {self.num_chips} chips not divisible by {cph}")
        return self.num_chips // cph

    @property
    def chips_per_pod(self) -> int:
        return self.num_chips if self.num_hosts == 1 else self.accelerator.chips_per_host

    @property
    def is_multi_host(self) -> bool:
        return self.num_hosts > 1

    def node_selector(self) -> Dict[str, str]:
        return {
            NODE_LABEL_ACCELERATOR: self.accelerator.gke_name,
            NODE_LABEL_TOPOLOGY: self.label,
        }

    def resource_limits(self) -> Dict[str, str]:
        return {RESOURCE_TPU: str(self.chips_per_pod)}

    def peak_bf16_tflops(self) -> float:
        return self.num_chips * self.accelerator.bf16_tflops_per_chip


def parse_topology(generation: str, label: str) -> SliceTopology:
    """Parse e.g. ``("v5e", "4x8")`` or ``("v4", "2x2x4")`` with validation."""
    if generation not in ACCELERATORS:
        raise ValueError(f"unknown TPU generation {generation!r}; known: {sorted(ACCELERATORS)}")
    acc = ACCELERATORS[generation]
    try:
        dims = tuple(int(p) for p in label.lower().split("x"))
    except ValueError:
        raise ValueError(f"bad topology {label!r}: expected AxB[xC]") from None
    if any(d < 1 for d in dims):
        raise ValueError(f"bad topology {label!r}: dimensions must be >= 1")
    if len(dims) != acc.dims:
        raise ValueError(f"{generation} topologies are {acc.dims}D; got {label!r}")
    topo = SliceTopology(generation, dims)
    if topo.num_chips > acc.max_chips:
        raise ValueError(f"{generation} {label}: {topo.num_chips} chips exceeds max {acc.max_chips}")
    topo.num_hosts  # validates divisibility
    return topo


_KNOWN_TOPOLOGIES: Dict[str, List[SliceTopology]] = {
    "v5e": [
        SliceTopology("v5e", d)
        for d in [(1, 1), (2, 2), (2, 4), (4, 4), (4, 8), (8, 8), (8, 16), (16, 16)]
    ],
    "v6e": [
        SliceTopology("v6e", d)
        for d in [(1, 1), (2, 2), (2, 4), (4, 4), (4, 8), (8, 8), (8, 16), (16, 16)]
    ],
    "v4": [
        SliceTopology("v4", d)
        for d in [(2, 2, 1), (2, 2, 2), (2, 2, 4), (2, 4, 4), (4, 4, 4), (4, 4, 8), (4, 8, 8), (8, 8, 8)]
    ],
    "v5p": [
        SliceTopology("v5p", d)
        for d in [(2, 2, 1), (2, 2, 2), (2, 2, 4), (2, 4, 4), (4, 4, 4), (4, 4, 8), (4, 8, 8), (8, 8, 8)]
    ],
}


def chips_in_quota(quantity: str) -> int:
    """Parse a quota quantity for google.com/tpu (always integral chips)."""
    return int(str(quantity))


def pod_tpu_chips(pod: Dict) -> int:
    """Chips a pod holds against node capacity/quota: the sum of its
    containers' ``google.com/tpu`` limits — zero once the pod is terminal
    (kube-scheduler excludes Succeeded/Failed pods from resource
    accounting). The single accounting predicate shared by the scheduler
    and the dashboard metrics, so they can never disagree."""
    if (pod.get("status") or {}).get("phase") in ("Succeeded", "Failed"):
        return 0
    total = 0
    for c in (pod.get("spec") or {}).get("containers", []) or []:
        total += int(((c.get("resources") or {}).get("limits") or {}).get(RESOURCE_TPU, 0))
    return total
