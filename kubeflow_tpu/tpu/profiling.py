"""JAX profiler integration — the TPU answer to the reference's CUPTI
plumbing (jupyter-tensorflow/cuda.Dockerfile:61-71 LD_LIBRARY_PATH surgery;
on TPU the profiler ships with JAX and needs wiring, not drivers).

Used by the notebook/serving images (images/jupyter-jax-tpu exposes :9999)
and by bench/perf work: start a profile server for TensorBoard's profile
plugin to connect to, or capture a step trace programmatically and read
back where the time went.
"""

from __future__ import annotations

import glob
import os
import threading
from contextlib import contextmanager
from typing import Any, Callable, Dict, Optional

PROFILE_PORT = 9999

_server_lock = threading.Lock()
_server_started_port: Optional[int] = None


def start_profile_server(port: int = PROFILE_PORT) -> int:
    """Start the in-process profiler gRPC server (idempotent). TensorBoard's
    profile plugin captures from it: tensorboard --logdir=... then
    'capture profile' at <pod-dns>:<port> — reachable through the headless
    service the notebook controller creates."""
    global _server_started_port
    import jax

    with _server_lock:
        if _server_started_port is not None:
            if _server_started_port != port:
                raise RuntimeError(
                    f"profiler server already on port {_server_started_port}; "
                    f"cannot also serve {port} (one server per process)"
                )
            return _server_started_port
        jax.profiler.start_server(port)
        _server_started_port = port
        return port


@contextmanager
def step_trace(logdir: str, name: str = "step"):
    """Capture a programmatic trace into ``logdir`` (xplane protos readable
    by TensorBoard/XProf). Use around a handful of steps, not whole runs."""
    import jax

    with jax.profiler.trace(logdir):
        with jax.profiler.TraceAnnotation(name):
            yield


def annotate(name: str):
    """Named region inside a trace (shows as a range in the timeline)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


def profile_step(
    fn: Callable[..., Any], *args: Any, logdir: str, iters: int = 3, **kwargs: Any
) -> Dict[str, Any]:
    """Run ``fn`` under the profiler (after one untraced warmup for compile)
    and return {result, trace_files}. The capture covers ``iters`` steps so
    steady-state behavior dominates over first-step noise."""
    import jax

    result = fn(*args, **kwargs)  # warmup/compile outside the trace
    jax.block_until_ready(result)
    with step_trace(logdir):
        for _ in range(iters):
            result = fn(*args, **kwargs)
        jax.block_until_ready(result)
    traces = sorted(
        glob.glob(os.path.join(logdir, "**", "*.xplane.pb"), recursive=True)
    )
    return {"result": result, "trace_files": traces}
