"""JAX profiler integration — the TPU answer to the reference's CUPTI
plumbing (jupyter-tensorflow/cuda.Dockerfile:61-71 LD_LIBRARY_PATH surgery;
on TPU the profiler ships with JAX and needs wiring, not drivers).

Used by the notebook/serving images (images/jupyter-jax-tpu exposes :9999)
and by bench/perf work: start a profile server for TensorBoard's profile
plugin to connect to, or capture a step trace programmatically and read
back where the time went.
"""

from __future__ import annotations

import collections
import glob
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Deque, Dict, List, Optional

PROFILE_PORT = 9999

_server_lock = threading.Lock()
_server_started_port: Optional[int] = None


def start_profile_server(port: int = PROFILE_PORT) -> int:
    """Start the in-process profiler gRPC server (idempotent). TensorBoard's
    profile plugin captures from it: tensorboard --logdir=... then
    'capture profile' at <pod-dns>:<port> — reachable through the headless
    service the notebook controller creates."""
    global _server_started_port
    import jax

    with _server_lock:
        if _server_started_port is not None:
            if _server_started_port != port:
                raise RuntimeError(
                    f"profiler server already on port {_server_started_port}; "
                    f"cannot also serve {port} (one server per process)"
                )
            return _server_started_port
        jax.profiler.start_server(port)
        _server_started_port = port
        return port


@contextmanager
def step_trace(logdir: str, name: str = "step"):
    """Capture a programmatic trace into ``logdir`` (xplane protos readable
    by TensorBoard/XProf). Use around a handful of steps, not whole runs."""
    import jax

    with jax.profiler.trace(logdir):
        with jax.profiler.TraceAnnotation(name):
            yield


def annotate(name: str):
    """Named region inside a trace (shows as a range in the timeline)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


class StepClock:
    """Wall-clock step breakdown for training/bench loops.

    The profiler trace (above) answers "where did the time go" offline; the
    clock answers it live, per step, with host-side timers cheap enough to
    leave on: wrap each phase of the loop body and ``end_step()`` at the
    bottom. The canonical phases:

        with clock.compile(): compiled = step_fn.lower(...).compile()
        for batch in data:                # via device_prefetch(clock=clock)
            with clock.compute(): out = compiled(state, batch)
            with clock.fetch():   loss = float(out["loss"])   # D2H sync
            clock.end_step()

    Each record holds the measured phases plus ``total`` (wall since the
    previous ``end_step``) and ``other`` (total minus measured — dispatch
    overhead, Python, logging). Compile time accumulates separately and is
    never charged to a step, so the first-step XLA compile can't masquerade
    as slow data loading (the classic misread this exists to kill). With a
    ``metrics`` namespace (``METRICS.namespace("train")``) every phase also
    lands in ``<ns>_step_<phase>_seconds`` histograms for ``/metrics``.
    With a ``tracer`` (``runtime.tracing.TRACER``) every ``end_step()``
    additionally emits one ``span_name`` span covering the step, its phases
    attached as events — so a bench/dryrun's training timeline shows up in
    ``/debug/traces`` next to the serving requests.

    Phase events are always retained per step in a bounded ring
    (``keep_steps``, default 512) so the timeline survives without a
    tracer: ``to_chrome_trace()`` renders the recorded steps as a
    Chrome-trace-event document (the ``trace.json`` Perfetto and
    chrome://tracing load), and ``register_profile_clock()`` publishes it
    at ``GET /debug/profile`` on every observability-mounted server.
    """

    def __init__(self, metrics: Optional[Any] = None,
                 tracer: Optional[Any] = None,
                 span_name: str = "train.step",
                 keep_steps: int = 512) -> None:
        self._metrics = metrics
        self._tracer = tracer
        self._span_name = span_name
        self.compile_s = 0.0
        self.steps: List[Dict[str, float]] = []
        self.notes: Dict[str, float] = {}
        self._current: Dict[str, float] = {}
        self._anchor = time.perf_counter()
        self._step_start_ns = time.time_ns()
        self._events: List[Dict[str, Any]] = []
        #: per-step phase-event history for to_chrome_trace(): bounded so a
        #: long training run can't grow host memory without limit
        self._step_records: Deque[Dict[str, Any]] = collections.deque(
            maxlen=keep_steps)

    def note(self, key: str, value: float) -> None:
        """Attach a derived scalar (analytic comm bytes, bubble fraction —
        things computed about the step rather than timed in it) so it rides
        along in ``summary()``/metrics next to the measured phases."""
        self.notes[key] = float(value)
        if self._metrics is not None:
            self._metrics.gauge(key).set(float(value))

    @contextmanager
    def phase(self, name: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - start
            self._current[name] = self._current.get(name, 0.0) + dt
            if self._metrics is not None:
                self._metrics.histogram(f"step_{name}_seconds").observe(dt)
            # always recorded (span-event shape; start derives from end −
            # seconds): the chrome-trace timeline must not require a tracer
            self._events.append({"name": name,
                                 "timeUnixNano": time.time_ns(),
                                 "attributes": {"seconds": dt}})

    # The canonical phases as methods so call sites stay greppable.
    def data_wait(self):
        """Host blocked waiting on the input pipeline (H2D not yet hidden)."""
        return self.phase("data_wait")

    def compute(self):
        """Dispatch + device execution (through ``block_until_ready``)."""
        return self.phase("compute")

    def fetch(self):
        """D2H readback of step outputs (loss/metrics scalars)."""
        return self.phase("fetch")

    def collective(self):
        """Host blocked on cross-worker synchronization (barriers, collective
        dispatch waits) — the straggler plane's skew signal: one slow worker
        inflates every peer's collective_wait, not their compute."""
        return self.phase("collective_wait")

    @contextmanager
    def compile(self):
        """XLA compile — accumulated separately, never charged to a step."""
        start = time.perf_counter()
        try:
            yield
        finally:
            self.compile_s += time.perf_counter() - start
            if self._metrics is not None:
                self._metrics.gauge("compile_seconds").set(self.compile_s)
            # Reset the anchors ONLY. Clearing self._events here silently
            # dropped phase events recorded earlier in the same step (a
            # data_wait timed before a mid-loop recompile vanished from the
            # step span); already-recorded events must survive.
            self._anchor = time.perf_counter()
            if not self._events:
                self._step_start_ns = time.time_ns()

    def mark(self) -> None:
        """Reset the wall anchor without recording — call after untimed
        work between steps (warmup executions, logging) so the next step's
        ``total``/``other`` doesn't absorb it. Phase events already recorded
        in the open step are preserved (see ``compile()``)."""
        self._anchor = time.perf_counter()
        if not self._events:
            self._step_start_ns = time.time_ns()

    def end_step(self) -> Dict[str, float]:
        now = time.perf_counter()
        now_ns = time.time_ns()
        rec = dict(self._current)
        rec["total"] = now - self._anchor
        rec["other"] = max(0.0, rec["total"] - sum(self._current.values()))
        self.steps.append(rec)
        if self._metrics is not None:
            for k, v in rec.items():
                self._metrics.gauge("step_phase_seconds", phase=k).set(v)
        self._step_records.append({
            "step": len(self.steps),
            "start_ns": self._step_start_ns,
            "end_ns": now_ns,
            "phases": list(self._events),
            "rec": rec,
        })
        if self._tracer is not None:
            self._tracer.emit_span(
                self._span_name, self._step_start_ns, now_ns,
                events=self._events,
                **{"step": len(self.steps),
                   **{f"phase.{k}": round(v, 6) for k, v in rec.items()}})
        self._step_start_ns = now_ns
        self._events = []
        self._current = {}
        self._anchor = now
        return rec

    def to_chrome_trace(self, steps: Optional[int] = None,
                        tid: int = 1) -> Dict[str, Any]:
        """The last ``steps`` recorded steps (all retained when None) as a
        Chrome-trace-event document: one complete ("ph": "X") event per
        step named ``span_name`` with its phase means in ``args``, plus one
        complete event per measured phase (start derived from the phase
        event's end − duration). ``json.dumps`` of the return value is a
        ``trace.json`` Perfetto and chrome://tracing open directly."""
        records = list(self._step_records)
        if steps is not None:
            records = records[-max(0, steps):]
        pid = os.getpid()
        events: List[Dict[str, Any]] = []
        for r in records:
            events.append({
                "name": self._span_name,
                "cat": "step",
                "ph": "X",
                "ts": r["start_ns"] / 1e3,
                "dur": max(0.0, (r["end_ns"] - r["start_ns"]) / 1e3),
                "pid": pid,
                "tid": tid,
                "args": {"step": r["step"],
                         **{k: round(v, 6) for k, v in r["rec"].items()}},
            })
            for ev in r["phases"]:
                dur_us = float(ev["attributes"].get("seconds", 0.0)) * 1e6
                events.append({
                    "name": ev["name"],
                    "cat": "phase",
                    "ph": "X",
                    "ts": ev["timeUnixNano"] / 1e3 - dur_us,
                    "dur": dur_us,
                    "pid": pid,
                    "tid": tid,
                    "args": {"step": r["step"]},
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def summary(self) -> Dict[str, float]:
        """Per-phase mean seconds across recorded steps, plus ``compile_s``
        and the step count — the dict bench.py emits as ``step_breakdown``."""
        out: Dict[str, float] = {}
        if self.steps:
            keys = sorted(set().union(*self.steps))
            n = len(self.steps)
            for k in keys:
                out[k] = sum(s.get(k, 0.0) for s in self.steps) / n
        out.update(self.notes)
        out["compile_s"] = self.compile_s
        out["steps"] = float(len(self.steps))
        return out


def profile_step(
    fn: Callable[..., Any], *args: Any, logdir: str, iters: int = 3, **kwargs: Any
) -> Dict[str, Any]:
    """Run ``fn`` under the profiler (after one untraced warmup for compile)
    and return {result, trace_files}. The capture covers ``iters`` steps so
    steady-state behavior dominates over first-step noise."""
    import jax

    result = fn(*args, **kwargs)  # warmup/compile outside the trace
    jax.block_until_ready(result)
    with step_trace(logdir):
        for _ in range(iters):
            result = fn(*args, **kwargs)
        jax.block_until_ready(result)
    traces = sorted(
        glob.glob(os.path.join(logdir, "**", "*.xplane.pb"), recursive=True)
    )
    return {"result": result, "trace_files": traces}


# -- /debug/profile: on-demand step capture over HTTP -------------------------
#
# A training/bench loop registers its StepClock once; every server that
# mounts observability (ops server, apiserver, ModelServer) then serves the
# loop's live timeline as Perfetto-loadable Chrome-trace JSON — the
# "download trace.json from the running job" workflow without a TensorBoard
# deployment in the loop.

#: registered clocks by name; last registration per name wins (what
#: per-incarnation ElasticTrainer restarts and per-test clocks need)
_PROFILE_CLOCKS: Dict[str, "StepClock"] = {}


def register_profile_clock(clock: "StepClock", name: str = "train") -> "StepClock":
    """Publish ``clock`` at ``GET /debug/profile`` (query: ``?steps=N`` last
    N steps, ``?clock=<name>`` one clock, ``?timeout=S`` wait up to S
    seconds for N *fresh* steps — the on-demand capture). Returns the clock
    so call sites can register at construction."""
    from kubeflow_tpu.runtime import obs  # lazy: profiling must not drag HTTP in

    _PROFILE_CLOCKS[name] = clock
    obs.register_debug_source("profile", _profile_debug_source)
    return clock


def _profile_debug_source(req: Any) -> Dict[str, Any]:
    from kubeflow_tpu.web.http import HttpError

    try:
        steps = int(req.query1("steps", "16"))
        timeout = float(req.query1("timeout", "0"))
    except ValueError:
        raise HttpError(400, "steps/timeout must be numeric") from None
    name = req.query1("clock") or None
    if name is not None and name not in _PROFILE_CLOCKS:
        raise HttpError(
            404, f"unknown clock {name!r}; registered: {sorted(_PROFILE_CLOCKS)}")
    selected = {name: _PROFILE_CLOCKS[name]} if name else dict(_PROFILE_CLOCKS)
    if timeout > 0:
        # capture-on-demand: wait for `steps` steps recorded AFTER the
        # request, so the trace answers "what is the loop doing right now"
        deadline = time.monotonic() + timeout
        baselines = {n: len(c.steps) for n, c in selected.items()}
        while time.monotonic() < deadline:
            if all(len(c.steps) >= baselines[n] + steps
                   for n, c in selected.items()):
                break
            time.sleep(0.02)
    events: List[Dict[str, Any]] = []
    for tid, (_n, clock) in enumerate(sorted(selected.items()), start=1):
        events.extend(clock.to_chrome_trace(steps=steps, tid=tid)["traceEvents"])
    return {"traceEvents": events, "displayTimeUnit": "ms"}
