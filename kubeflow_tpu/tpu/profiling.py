"""JAX profiler integration — the TPU answer to the reference's CUPTI
plumbing (jupyter-tensorflow/cuda.Dockerfile:61-71 LD_LIBRARY_PATH surgery;
on TPU the profiler ships with JAX and needs wiring, not drivers).

Used by the notebook/serving images (images/jupyter-jax-tpu exposes :9999)
and by bench/perf work: start a profile server for TensorBoard's profile
plugin to connect to, or capture a step trace programmatically and read
back where the time went.
"""

from __future__ import annotations

import glob
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional

PROFILE_PORT = 9999

_server_lock = threading.Lock()
_server_started_port: Optional[int] = None


def start_profile_server(port: int = PROFILE_PORT) -> int:
    """Start the in-process profiler gRPC server (idempotent). TensorBoard's
    profile plugin captures from it: tensorboard --logdir=... then
    'capture profile' at <pod-dns>:<port> — reachable through the headless
    service the notebook controller creates."""
    global _server_started_port
    import jax

    with _server_lock:
        if _server_started_port is not None:
            if _server_started_port != port:
                raise RuntimeError(
                    f"profiler server already on port {_server_started_port}; "
                    f"cannot also serve {port} (one server per process)"
                )
            return _server_started_port
        jax.profiler.start_server(port)
        _server_started_port = port
        return port


@contextmanager
def step_trace(logdir: str, name: str = "step"):
    """Capture a programmatic trace into ``logdir`` (xplane protos readable
    by TensorBoard/XProf). Use around a handful of steps, not whole runs."""
    import jax

    with jax.profiler.trace(logdir):
        with jax.profiler.TraceAnnotation(name):
            yield


def annotate(name: str):
    """Named region inside a trace (shows as a range in the timeline)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


class StepClock:
    """Wall-clock step breakdown for training/bench loops.

    The profiler trace (above) answers "where did the time go" offline; the
    clock answers it live, per step, with host-side timers cheap enough to
    leave on: wrap each phase of the loop body and ``end_step()`` at the
    bottom. The canonical phases:

        with clock.compile(): compiled = step_fn.lower(...).compile()
        for batch in data:                # via device_prefetch(clock=clock)
            with clock.compute(): out = compiled(state, batch)
            with clock.fetch():   loss = float(out["loss"])   # D2H sync
            clock.end_step()

    Each record holds the measured phases plus ``total`` (wall since the
    previous ``end_step``) and ``other`` (total minus measured — dispatch
    overhead, Python, logging). Compile time accumulates separately and is
    never charged to a step, so the first-step XLA compile can't masquerade
    as slow data loading (the classic misread this exists to kill). With a
    ``metrics`` namespace (``METRICS.namespace("train")``) every phase also
    lands in ``<ns>_step_<phase>_seconds`` histograms for ``/metrics``.
    With a ``tracer`` (``runtime.tracing.TRACER``) every ``end_step()``
    additionally emits one ``span_name`` span covering the step, its phases
    attached as events — so a bench/dryrun's training timeline shows up in
    ``/debug/traces`` next to the serving requests.
    """

    def __init__(self, metrics: Optional[Any] = None,
                 tracer: Optional[Any] = None,
                 span_name: str = "train.step") -> None:
        self._metrics = metrics
        self._tracer = tracer
        self._span_name = span_name
        self.compile_s = 0.0
        self.steps: List[Dict[str, float]] = []
        self.notes: Dict[str, float] = {}
        self._current: Dict[str, float] = {}
        self._anchor = time.perf_counter()
        self._step_start_ns = time.time_ns()
        self._events: List[Dict[str, Any]] = []

    def note(self, key: str, value: float) -> None:
        """Attach a derived scalar (analytic comm bytes, bubble fraction —
        things computed about the step rather than timed in it) so it rides
        along in ``summary()``/metrics next to the measured phases."""
        self.notes[key] = float(value)
        if self._metrics is not None:
            self._metrics.gauge(key).set(float(value))

    @contextmanager
    def phase(self, name: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - start
            self._current[name] = self._current.get(name, 0.0) + dt
            if self._metrics is not None:
                self._metrics.histogram(f"step_{name}_seconds").observe(dt)
            if self._tracer is not None:
                self._events.append({"name": name,
                                     "timeUnixNano": time.time_ns(),
                                     "attributes": {"seconds": dt}})

    # The canonical phases as methods so call sites stay greppable.
    def data_wait(self):
        """Host blocked waiting on the input pipeline (H2D not yet hidden)."""
        return self.phase("data_wait")

    def compute(self):
        """Dispatch + device execution (through ``block_until_ready``)."""
        return self.phase("compute")

    def fetch(self):
        """D2H readback of step outputs (loss/metrics scalars)."""
        return self.phase("fetch")

    @contextmanager
    def compile(self):
        """XLA compile — accumulated separately, never charged to a step."""
        start = time.perf_counter()
        try:
            yield
        finally:
            self.compile_s += time.perf_counter() - start
            if self._metrics is not None:
                self._metrics.gauge("compile_seconds").set(self.compile_s)
            self._anchor = time.perf_counter()
            self._step_start_ns = time.time_ns()
            self._events = []

    def mark(self) -> None:
        """Reset the wall anchor without recording — call after untimed
        work between steps (warmup executions, logging) so the next step's
        ``total``/``other`` doesn't absorb it."""
        self._anchor = time.perf_counter()
        self._step_start_ns = time.time_ns()
        self._events = []

    def end_step(self) -> Dict[str, float]:
        now = time.perf_counter()
        rec = dict(self._current)
        rec["total"] = now - self._anchor
        rec["other"] = max(0.0, rec["total"] - sum(self._current.values()))
        self.steps.append(rec)
        if self._tracer is not None:
            now_ns = time.time_ns()
            self._tracer.emit_span(
                self._span_name, self._step_start_ns, now_ns,
                events=self._events,
                **{"step": len(self.steps),
                   **{f"phase.{k}": round(v, 6) for k, v in rec.items()}})
            self._step_start_ns = now_ns
            self._events = []
        self._current = {}
        self._anchor = now
        return rec

    def summary(self) -> Dict[str, float]:
        """Per-phase mean seconds across recorded steps, plus ``compile_s``
        and the step count — the dict bench.py emits as ``step_breakdown``."""
        out: Dict[str, float] = {}
        if self.steps:
            keys = sorted(set().union(*self.steps))
            n = len(self.steps)
            for k in keys:
                out[k] = sum(s.get(k, 0.0) for s in self.steps) / n
        out.update(self.notes)
        out["compile_s"] = self.compile_s
        out["steps"] = float(len(self.steps))
        return out


def profile_step(
    fn: Callable[..., Any], *args: Any, logdir: str, iters: int = 3, **kwargs: Any
) -> Dict[str, Any]:
    """Run ``fn`` under the profiler (after one untraced warmup for compile)
    and return {result, trace_files}. The capture covers ``iters`` steps so
    steady-state behavior dominates over first-step noise."""
    import jax

    result = fn(*args, **kwargs)  # warmup/compile outside the trace
    jax.block_until_ready(result)
    with step_trace(logdir):
        for _ in range(iters):
            result = fn(*args, **kwargs)
        jax.block_until_ready(result)
    traces = sorted(
        glob.glob(os.path.join(logdir, "**", "*.xplane.pb"), recursive=True)
    )
    return {"result": result, "trace_files": traces}
