"""JAX/TPU environment generation for multi-host pod slices.

The reference era injected free-form GPU env (``NVIDIA_VISIBLE_DEVICES``,
NCCL vars via images — example-notebook-servers/jupyter-pytorch/cuda.Dockerfile).
Here the coordinator bootstrap is *deterministic and computable at admission
time*: worker 0's address is the pod-0 DNS name of the workload's headless
Service (the same service-DNS scheme the reference culler uses to reach
notebooks — notebook-controller/pkg/culler/culler.go:138-144), and each
worker derives its process id from its StatefulSet ordinal at runtime.
Determinism matters because the PodDefault webhook rejects conflicting env
(reference: admission-webhook/main.go:152-187) — regenerating the same env
twice must be a no-op.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .topology import SliceTopology

JAX_COORDINATOR_PORT = 8476  # jax.distributed default
ENV_COORDINATOR_ADDRESS = "JAX_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "JAX_NUM_PROCESSES"
ENV_PROCESS_ID = "TPU_WORKER_ID"
ENV_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"


def coordinator_address(
    workload_name: str, namespace: str, cluster_domain: str = "cluster.local", port: int = JAX_COORDINATOR_PORT
) -> str:
    """pod-0 of the headless Service: <name>-0.<name>.<ns>.svc.<domain>:<port>."""
    return f"{workload_name}-0.{workload_name}.{namespace}.svc.{cluster_domain}:{port}"


def worker_hostnames(workload_name: str, namespace: str, num_hosts: int, cluster_domain: str = "cluster.local") -> str:
    return ",".join(
        f"{workload_name}-{i}.{workload_name}.{namespace}.svc.{cluster_domain}" for i in range(num_hosts)
    )


def jax_worker_env(
    topology: SliceTopology,
    workload_name: str,
    namespace: str,
    cluster_domain: str = "cluster.local",
    extra: Optional[Dict[str, str]] = None,
) -> List[Dict[str, str]]:
    """Env var list (pod-spec shape) making a pod a JAX TPU slice worker.

    ``TPU_WORKER_ID`` is left to runtime derivation from the StatefulSet
    ordinal (hostname suffix) by ``kubeflow_tpu.parallel.distributed`` —
    identical env on every pod keeps webhook injection deterministic.
    """
    env = {
        "JAX_PLATFORMS": "tpu",
        ENV_COORDINATOR_ADDRESS: coordinator_address(workload_name, namespace, cluster_domain),
        ENV_NUM_PROCESSES: str(topology.num_hosts),
        ENV_WORKER_HOSTNAMES: worker_hostnames(workload_name, namespace, topology.num_hosts, cluster_domain),
        "TPU_ACCELERATOR_TYPE": topology.accelerator.gke_name,
        "TPU_TOPOLOGY": topology.label,
        "TPU_CHIPS_PER_HOST": str(topology.chips_per_pod),
        "TPU_RUNTIME_METRICS_PORTS": "8431",
    }
    if extra:
        env.update(extra)
    return [{"name": k, "value": v} for k, v in sorted(env.items())]


def env_list_to_dict(env: List[Dict[str, str]]) -> Dict[str, str]:
    return {e["name"]: e.get("value", "") for e in env}
