"""Environment-variable parsing shared by every role's config surface."""

from __future__ import annotations

import os

_TRUTHY = ("1", "true", "yes", "on")


def env_flag(name: str, default: bool = False) -> bool:
    """Boolean env knob with the platform-wide truthy set. One definition —
    every config (controllers, web auth, bootstrap) must agree on what
    counts as 'true'."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() in _TRUTHY
