"""Small shared utilities."""

from .env import env_flag

__all__ = ["env_flag"]
