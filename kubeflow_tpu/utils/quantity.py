"""Kubernetes resource-quantity parsing (the apimachinery `resource.Quantity`
subset the platform needs: PVC capacities, memory requests).

Reference semantics (apimachinery/pkg/api/resource): binary suffixes
Ki/Mi/Gi/Ti/Pi/Ei (1024-based), decimal k/M/G/T/P/E (1000-based), bare
numbers, and decimal fractions ("1.5Gi", "0.5"). Milli ("500m") supported
for completeness. Unparseable input returns None — callers sort/display
raw strings in that case rather than crash a list endpoint."""

from __future__ import annotations

import re
from typing import Optional

_SUFFIX = {
    "Ki": 1024, "Mi": 1024**2, "Gi": 1024**3, "Ti": 1024**4,
    "Pi": 1024**5, "Ei": 1024**6,
    "k": 1000, "M": 1000**2, "G": 1000**3, "T": 1000**4,
    "P": 1000**5, "E": 1000**6,
    "m": 1e-3, "": 1,
}

_RX = re.compile(r"^\s*([0-9]+(?:\.[0-9]+)?)\s*(Ki|Mi|Gi|Ti|Pi|Ei|k|M|G|T|P|E|m)?\s*$")


def parse_quantity(s: object) -> Optional[float]:
    """'20Gi' -> 21474836480.0; '500m' -> 0.5; garbage -> None."""
    if isinstance(s, (int, float)):
        return float(s)
    if not isinstance(s, str):
        return None
    m = _RX.match(s)
    if not m:
        return None
    return float(m.group(1)) * _SUFFIX[m.group(2) or ""]
