"""kubeflow_tpu — a TPU-pod-native ML platform control plane.

A from-scratch rebuild of the capabilities of the Kubeflow control plane
(reference: equinor/kubeflow) re-targeted at Google Cloud TPU pod slices:

- CRD controllers (Notebook, Profile, Tensorboard) that materialize multi-host
  TPU workloads as StatefulSets whose workers rendezvous over ICI/DCN,
- a PodDefault mutating admission webhook that injects ``google.com/tpu``
  slice resources and JAX coordinator/worker environment,
- access management (KFAM), dashboard and CRUD web APIs,
- a JAX/XLA workload layer (models, parallelism, Pallas ops, serving, Katib HPO)
  replacing the reference's delegated CUDA/NCCL stack.

The control-plane substrate (API machinery, MVCC store with watch streams,
controller runtime) is implemented in-tree so the whole platform runs and is
testable without an external Kubernetes cluster, while speaking the same REST
and reconcile semantics as one.
"""

__version__ = "0.1.0"
