"""ResNet for TPU: the platform's flagship/MFU-benchmark model.

TPU-first choices:
- NHWC layout throughout (XLA's native conv layout on TPU; MXU-friendly),
- bf16 activations/compute with f32 parameters and f32 BatchNorm statistics
  (bf16 matmul/conv inputs hit the MXU at full rate; f32 running stats keep
  train/eval parity),
- static shapes only; no Python control flow in the forward pass, so the
  whole step compiles to one XLA program.

Reference context: the reference's only "model" content is CUDA notebook
images (example-notebook-servers/jupyter-pytorch/cuda.Dockerfile); the
BASELINE north-star is ResNet-50 ≥60% MFU on a v5e slice.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut when needed."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides, name="conv2")(y)
        y = self.norm(name="bn2")(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1), name="conv3")(y)
        # Zero-init the last BN's scale: identity-ish residual at init
        # (standard ResNet-v1.5 trick; improves large-batch training).
        y = self.norm(name="bn3", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="bn_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides, name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), name="conv2")(y)
        y = self.norm(name="bn2", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="bn_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        act = nn.relu

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=act,
                    name=f"stage{i + 1}_block{j + 1}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        # Final classifier in f32: logits feed a softmax cross-entropy that is
        # numerically touchy in bf16.
        x = nn.Dense(self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32, name="classifier")(
            x.astype(jnp.float32)
        )
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)
