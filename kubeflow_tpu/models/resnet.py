"""ResNet for TPU: the platform's flagship/MFU-benchmark model.

TPU-first choices:
- NHWC layout throughout (XLA's native conv layout on TPU; MXU-friendly),
- bf16 activations/compute with f32 parameters and f32 BatchNorm statistics
  (bf16 matmul/conv inputs hit the MXU at full rate; f32 running stats keep
  train/eval parity),
- static shapes only; no Python control flow in the forward pass, so the
  whole step compiles to one XLA program.

Reference context: the reference's only "model" content is CUDA notebook
images (example-notebook-servers/jupyter-pytorch/cuda.Dockerfile); the
BASELINE north-star is ResNet-50 ≥60% MFU on a v5e slice.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class _ConvKernel(nn.Module):
    """Parameter holder with ``nn.Conv``'s exact tree ({kernel}) — the fused
    block reads the weight directly instead of applying the conv, while the
    checkpoint layout stays interchangeable with the unfused path."""

    shape: Tuple[int, ...]

    @nn.compact
    def __call__(self):
        return self.param("kernel", nn.initializers.lecun_normal(), self.shape,
                          jnp.float32)


class _FoldedNorm(nn.Module):
    """Parameter/stat holder with ``nn.BatchNorm``'s exact tree (params
    {scale, bias}, batch_stats {mean, var}); returns the inference-form norm
    folded to a single (scale, bias) affine: y*s + b == (y - mean)/sqrt(var
    + eps) * gamma + beta."""

    features: int
    epsilon: float = 1e-5
    scale_init: Callable = nn.initializers.ones

    @nn.compact
    def __call__(self):
        scale = self.param("scale", self.scale_init, (self.features,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (self.features,), jnp.float32)
        mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((self.features,), jnp.float32)
        )
        var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((self.features,), jnp.float32)
        )
        inv = scale * jax.lax.rsqrt(var.value + self.epsilon)
        return inv, bias - mean.value * inv


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut when needed.

    ``fused=True`` routes every square-input application through a Pallas
    kernel: identity-shortcut blocks through ``fused_bottleneck`` (any
    spatial size — non-8-aligned rows go through sublane-padded dots) and
    the stage heads (stride-2 and/or projection shortcut) through
    ``fused_transition``. The whole block runs as MXU matmuls with
    activations resident in VMEM, norms folded from the running statistics
    ("frozen norm" — matches the unfused path exactly in eval mode; in
    train mode fused blocks normalize by running stats instead of batch
    stats and do not update them). Backward stays XLA
    (ops.fused_bottleneck_block / fused_transition_block). The rare
    leftover shapes (non-square, odd strided inputs) take the epilogue-
    fused XLA ``folded_bottleneck`` path and tick
    ``ops_fused_fallback_total``; all paths declare an identical variable
    tree, so checkpoints move freely between fused and unfused models.
    """

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    fused: bool = False

    def _fusable(self, x) -> bool:
        """Identity-shortcut Pallas kernel eligibility (stride 1, square)."""
        return (
            self.strides == (1, 1)
            and x.ndim == 4
            and x.shape[-1] == self.filters * 4
            and x.shape[1] == x.shape[2]
            and x.shape[1] >= 4
        )

    def _fusable_transition(self, x) -> bool:
        """Transition-block Pallas kernel eligibility: a stage head (needs a
        projection shortcut for channels and/or stride), square input,
        stride in {1, 2}; stride 2 needs an even spatial dim (SAME pad is
        then (0, 1), which the kernel's strided im2col reproduces)."""
        if not (x.ndim == 4 and x.shape[1] == x.shape[2] and x.shape[1] >= 4):
            return False
        if self.strides == (1, 1):
            return x.shape[-1] != self.filters * 4  # stride-1 channel head
        return self.strides == (2, 2) and x.shape[1] % 2 == 0

    def _fused_params(self, cin: int, cmid: int, cout: int, proj: bool):
        w1 = _ConvKernel((1, 1, cin, cmid), name="conv1")()
        s1, b1 = _FoldedNorm(cmid, name="bn1")()
        w2 = _ConvKernel((3, 3, cmid, cmid), name="conv2")()
        s2, b2 = _FoldedNorm(cmid, name="bn2")()
        w3 = _ConvKernel((1, 1, cmid, cout), name="conv3")()
        # Zero-init bn3's scale, mirroring the unfused path below.
        s3, b3 = _FoldedNorm(cout, scale_init=nn.initializers.zeros, name="bn3")()
        main = (w1[0, 0], s1, b1, w2, s2, b2, w3[0, 0], s3, b3)
        if not proj:
            return main, None
        wp = _ConvKernel((1, 1, cin, cout), name="conv_proj")()
        sp, bp = _FoldedNorm(cout, name="bn_proj")()
        return main, (wp[0, 0], sp, bp)

    @nn.compact
    def __call__(self, x):
        if self.fused and self._fusable(x):
            from kubeflow_tpu.ops.fused_bottleneck import fused_bottleneck_block

            cin, cmid = self.filters * 4, self.filters
            main, _ = self._fused_params(cin, cmid, cin, proj=False)
            return fused_bottleneck_block(x, *main)
        if self.fused and self._fusable_transition(x):
            from kubeflow_tpu.ops.fused_bottleneck import fused_transition_block

            cin, cmid, cout = x.shape[-1], self.filters, self.filters * 4
            main, proj = self._fused_params(cin, cmid, cout, proj=True)
            return fused_transition_block(
                x, *main, *proj, stride=self.strides[0])
        if self.fused and x.ndim == 4:
            # Neither kernel takes this shape: keep the folded-norm math in
            # an epilogue-fused XLA composite so the variable tree (and the
            # frozen-norm semantics of fused=True) stay uniform, and make
            # the kernel miss visible.
            from kubeflow_tpu.ops.fallback import record_fallback
            from kubeflow_tpu.ops.fused_bottleneck import folded_bottleneck

            record_fallback(
                "fused_bottleneck",
                f"input shape {tuple(x.shape)} with strides "
                f"{tuple(self.strides)} is not kernel-fusable; using the "
                "epilogue-fused XLA path")
            cin, cmid, cout = x.shape[-1], self.filters, self.filters * 4
            out_hw = tuple(
                -(-d // s) for d, s in zip(x.shape[1:3], self.strides))
            needs_proj = cin != cout or out_hw != tuple(x.shape[1:3])
            main, proj = self._fused_params(cin, cmid, cout, proj=needs_proj)
            return folded_bottleneck(
                x, *main, strides=self.strides, proj=proj)
        residual = x
        y = self.conv(self.filters, (1, 1), name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides, name="conv2")(y)
        y = self.norm(name="bn2")(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1), name="conv3")(y)
        # Zero-init the last BN's scale: identity-ish residual at init
        # (standard ResNet-v1.5 trick; improves large-batch training).
        y = self.norm(name="bn3", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="bn_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides, name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), name="conv2")(y)
        y = self.norm(name="bn2", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="bn_proj")(residual)
        return self.act(residual + y)


def space_to_depth(x: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """[N, H, W, C] -> [N, H/b, W/b, b*b*C]: 2x2 pixel blocks folded into
    channels. A pure reshape/transpose — XLA compiles it to a cheap copy."""
    n, h, w, c = x.shape
    x = x.reshape(n, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, block * block * c)


class ResNet(nn.Module):
    """stem="s2d" folds the input 2x2 space-to-depth and runs the stem as a
    4x4/1 conv on 12 channels instead of 7x7/2 on 3 — the same receptive
    field (the 7x7 kernel zero-padded to 8x8 and regrouped onto the
    half-res grid), but with 4x the channels feeding the MXU. Measured on
    v5e (e2e/conv_experiments.py): the 3-channel 7x7 sustains 5.7 TF/s in
    isolation vs 44.1 for the s2d form; in the full train step the win is
    ~1% (XLA already treats the in-model stem better than the standalone
    probe suggested — BASELINE.md round-4 notes). Default stays "conv7x7":
    the s2d stem renames/reshapes conv_init in the param tree, which would
    silently break existing checkpoints and torchvision weight-shape
    parity; perf-sensitive callers (bench.py) opt in explicitly."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    stem: str = "conv7x7"  # "s2d" | "conv7x7"
    # fused_blocks: route bottlenecks through the Pallas fused kernels
    # (ops/fused_bottleneck.py) — identity blocks AND the stage heads, so
    # all 16 of ResNet-50's blocks fuse at 224x224. Same variable tree as
    # the unfused model; frozen-norm semantics in those blocks (see
    # BottleneckBlock). Opt-in like the s2d stem; bench.py decides per
    # backend.
    fused_blocks: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        act = nn.relu

        x = x.astype(self.dtype)
        if self.stem == "s2d" and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
            x = space_to_depth(x, 2)
            # padding (2,1): the s2d window spans cells i-2..i+1, covering
            # the 7x7/2 receptive field (rows 2i-4..2i+3 vs 2i-3..2i+3).
            x = conv(self.num_filters, (4, 4), (1, 1),
                     padding=[(2, 1), (2, 1)], name="conv_init_s2d")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        # BasicBlock has no fused kernel; the flag only reaches bottlenecks.
        fused_kw = (
            {"fused": True}
            if self.fused_blocks and self.block_cls is BottleneckBlock
            else {}
        )
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=act,
                    name=f"stage{i + 1}_block{j + 1}",
                    **fused_kw,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        # Final classifier in f32: logits feed a softmax cross-entropy that is
        # numerically touchy in bf16.
        x = nn.Dense(self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32, name="classifier")(
            x.astype(jnp.float32)
        )
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)
