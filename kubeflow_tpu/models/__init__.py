"""Model zoo for the platform's workload side.

The reference platform ships no models — training is delegated to workload
pods (SURVEY.md §2.10). BASELINE.json's north-star configs name three:
ResNet-50 (the MFU benchmark), BERT-base (the serving path), and MNIST (the
Katib HPO trial). All are flax modules with bf16 compute / f32 params and
parameter names chosen to match ``kubeflow_tpu.parallel.sharding``'s logical
axis heuristics, so the same model runs replicated, fsdp, or tensor-parallel
by swapping rule tables.
"""

from kubeflow_tpu.models.resnet import ResNet50, ResNet18  # noqa: F401
from kubeflow_tpu.models.bert import BertConfig, BertEncoder, BertForMaskedLM  # noqa: F401
from kubeflow_tpu.models.gpt import GptConfig, GptLM, causal_lm_loss, generate  # noqa: F401
from kubeflow_tpu.models.mnist import MnistCNN  # noqa: F401
