"""MNIST CNN: the Katib HPO trial workload.

The reference's Katib e2e launches an MNIST StudyJob and only asserts the CR
reaches Running (testing/katib_studyjob_test.py:128-193). Here the trial is a
real JAX model small enough for CPU CI, with the hyperparameters Katib-style
suggestions tune (lr, dropout, width) exposed as constructor fields.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    width: int = 32
    dropout_rate: float = 0.1
    num_classes: int = 10
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (3, 3), dtype=self.dtype, param_dtype=jnp.float32, name="conv1")(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(self.width * 2, (3, 3), dtype=self.dtype, param_dtype=jnp.float32, name="conv2")(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype, param_dtype=jnp.float32, name="dense")(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32, name="classifier")(
            x.astype(jnp.float32)
        )
        return x
