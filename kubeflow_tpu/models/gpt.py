"""GPT-style decoder-only causal LM — the long-context flagship.

The platform's transformer training family (BASELINE's BERT covers the
serving/MLM path; this covers autoregressive training at long sequence
lengths). TPU-first choices:

- attention runs the Pallas flash kernel (ops/flash_attention) by default —
  fused, O(L) memory, causal masking inside the kernel; the attention fn is
  injectable so ring attention (parallel/ring_attention) drops in for
  sequence parallelism over the ``seq`` mesh axis,
- rotary position embeddings (no learned position table to shard),
- pre-LN blocks, bf16 activations / f32 params + norms,
- parameter names follow kubeflow_tpu.parallel.sharding's logical-axis
  conventions (query/key/value → heads, up_proj/down_proj → mlp,
  embedding → vocab/embed), so dp/fsdp/tp placement is a rules swap,
- optional MoE FFN (parallel/moe) for expert parallelism,
- optional per-block remat (``jax.checkpoint``) — trade recompute for HBM
  at long context.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubeflow_tpu.ops.flash_attention import flash_attention


@dataclass(frozen=True)
class GptConfig:
    vocab_size: int = 32000
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    remat: bool = False
    # MoE: num_experts=0 = dense FFN; >0 replaces the MLP every block.
    num_experts: int = 0
    moe_k: int = 2

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @classmethod
    def tiny(cls) -> "GptConfig":
        return cls(vocab_size=512, d_model=64, n_layers=2, n_heads=4, d_ff=128, max_seq=128)

    @classmethod
    def small(cls) -> "GptConfig":
        return cls(d_model=768, n_layers=12, n_heads=12, d_ff=3072)  # ~GPT-2 124M

    @classmethod
    def base(cls) -> "GptConfig":
        return cls(d_model=1024, n_layers=24, n_heads=16, d_ff=4096)  # ~GPT-2 medium


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: [b, L, heads, head_dim]; positions: [L]."""
    half = x.shape[-1] // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [L, half]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return rotated.astype(x.dtype)


def causal_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    return flash_attention(q, k, v, causal=True)


class GptAttention(nn.Module):
    cfg: GptConfig
    attention_fn: Callable = causal_flash_attention

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array) -> jax.Array:
        cfg = self.cfg
        dense = functools.partial(
            nn.DenseGeneral,
            features=(cfg.n_heads, cfg.head_dim),
            axis=-1,
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            use_bias=False,
        )
        q = rope(dense(name="query")(x), positions, cfg.rope_theta)
        k = rope(dense(name="key")(x), positions, cfg.rope_theta)
        v = dense(name="value")(x)
        ctx = self.attention_fn(q, k, v)  # [b, L, heads, head_dim]
        return nn.DenseGeneral(
            features=cfg.d_model,
            axis=(-2, -1),
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            use_bias=False,
            name="out_proj",
        )(ctx)


class GptMlp(nn.Module):
    cfg: GptConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        h = nn.Dense(cfg.d_ff, dtype=cfg.dtype, param_dtype=jnp.float32,
                     use_bias=False, name="up_proj")(x)
        h = nn.gelu(h)
        return nn.Dense(cfg.d_model, dtype=cfg.dtype, param_dtype=jnp.float32,
                        use_bias=False, name="down_proj")(h)


class GptBlock(nn.Module):
    cfg: GptConfig
    attention_fn: Callable = causal_flash_attention
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array) -> jax.Array:
        cfg = self.cfg
        ln = functools.partial(nn.LayerNorm, dtype=jnp.float32, param_dtype=jnp.float32)
        x = x + GptAttention(cfg, self.attention_fn, name="attention")(
            ln(name="ln_attn")(x).astype(cfg.dtype), positions
        )
        normed = ln(name="ln_mlp")(x).astype(cfg.dtype)
        if cfg.num_experts > 0:
            from kubeflow_tpu.parallel.moe import MoEMlp

            ffn = MoEMlp(
                num_experts=cfg.num_experts,
                d_ff=cfg.d_ff,
                k=cfg.moe_k,
                mesh=self.mesh,
                dtype=cfg.dtype,
                name="moe",
            )(normed)
        else:
            ffn = GptMlp(cfg, name="mlp")(normed)
        return x + ffn


class GptLM(nn.Module):
    """Decoder-only LM. input_ids [b, L] -> logits [b, L, vocab] (f32).

    The output projection ties to the input embedding (standard GPT-2
    weight tying — halves the largest parameter and its gradient traffic).
    """

    cfg: GptConfig
    attention_fn: Callable = causal_flash_attention
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, input_ids: jax.Array) -> jax.Array:
        cfg = self.cfg
        embed = nn.Embed(
            cfg.vocab_size,
            cfg.d_model,
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            name="embedding",
        )
        x = embed(input_ids)
        positions = jnp.arange(input_ids.shape[1])
        block = GptBlock
        if cfg.remat:
            block = nn.remat(GptBlock, static_argnums=())
        for i in range(cfg.n_layers):
            x = block(cfg, self.attention_fn, self.mesh, name=f"block_{i}")(x, positions)
        x = nn.LayerNorm(dtype=jnp.float32, param_dtype=jnp.float32, name="ln_final")(x)
        # tied LM head in f32 (embed.attend would compute in the module's
        # bf16 dtype; the final softmax wants full precision)
        logits = x.astype(jnp.float32) @ embed.embedding.T.astype(jnp.float32)
        return logits


def causal_lm_loss(logits: jax.Array, input_ids: jax.Array) -> jax.Array:
    """Next-token cross entropy; position t predicts token t+1."""
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32))
    targets = input_ids[:, 1:]
    picked = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(picked)
