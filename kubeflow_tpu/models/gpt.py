"""GPT-style decoder-only causal LM — the long-context flagship.

The platform's transformer training family (BASELINE's BERT covers the
serving/MLM path; this covers autoregressive training at long sequence
lengths). TPU-first choices:

- attention runs the Pallas flash kernel (ops/flash_attention) by default —
  fused, O(L) memory, causal masking inside the kernel; the attention fn is
  injectable so ring attention (parallel/ring_attention) drops in for
  sequence parallelism over the ``seq`` mesh axis,
- rotary position embeddings (no learned position table to shard),
- pre-LN blocks, bf16 activations / f32 params + norms,
- parameter names follow kubeflow_tpu.parallel.sharding's logical-axis
  conventions (query/key/value → heads, up_proj/down_proj → mlp,
  embedding → vocab/embed), so dp/fsdp/tp placement is a rules swap,
- optional MoE FFN (parallel/moe) for expert parallelism,
- optional per-block remat (``jax.checkpoint``) — trade recompute for HBM
  at long context.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubeflow_tpu.ops.flash_attention import flash_attention


@dataclass(frozen=True)
class GptConfig:
    vocab_size: int = 32000
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    remat: bool = False
    # MoE: num_experts=0 = dense FFN; >0 replaces the MLP every block.
    num_experts: int = 0
    moe_k: int = 2
    # scan_blocks: stack the transformer blocks as ONE ``nn.scan`` over
    # layer-stacked params instead of n_layers unrolled calls — compile
    # time and program size stop growing with depth (the 24-layer bench
    # config traces one block). Param tree changes from ``block_{i}/...``
    # to ``blocks/...`` with a leading layer axis; ``stack_block_params``
    # converts. Training/forward only — the decode path keeps the unrolled
    # layout its per-layer cache naming depends on.
    scan_blocks: bool = False

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @classmethod
    def tiny(cls) -> "GptConfig":
        return cls(vocab_size=512, d_model=64, n_layers=2, n_heads=4, d_ff=128, max_seq=128)

    @classmethod
    def small(cls) -> "GptConfig":
        return cls(d_model=768, n_layers=12, n_heads=12, d_ff=3072)  # ~GPT-2 124M

    @classmethod
    def base(cls) -> "GptConfig":
        return cls(d_model=1024, n_layers=24, n_heads=16, d_ff=4096)  # ~GPT-2 medium


def _kv_kernel_enabled() -> bool:
    """``KUBEFLOW_TPU_KV_KERNEL=1`` routes per-slot KV writes through the
    Pallas row-update kernel (ops/kv_cache.py); default is the whole-cache
    where-select. Measured on the round-5 dev backend
    (e2e/kv_update_probe.py): the two are within noise in-model (3.58 vs
    3.66 ms/token at depth-3 pipelining) because the dispatch round trip,
    not the on-device write, dominates — the kernel's 44x cache-traffic
    saving is kept opt-in for direct-attached deployments where HBM
    traffic is the decode bound."""
    import os

    return os.environ.get("KUBEFLOW_TPU_KV_KERNEL", "0") == "1"


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: [b, L, heads, head_dim]; positions: [L] (shared
    across the batch) or [b, L] (per-row — continuous batching, where each
    slot sits at its own sequence position)."""
    half = x.shape[-1] // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [..., L, half]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    if positions.ndim == 1:
        cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    else:  # [b, L, half] -> broadcast over heads
        cos, sin = cos[:, :, None, :], sin[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return rotated.astype(x.dtype)


def causal_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    import os

    # Experiment knobs for full-step tiling sweeps (BASELINE methodology:
    # only the full-step bench decides — isolated probes mispredicted three
    # times in round 4). Unset = the kernel's measured auto-tiling.
    bq = int(os.environ.get("GPT_FLASH_BLOCK_Q", "0")) or None
    bk = int(os.environ.get("GPT_FLASH_BLOCK_K", "0")) or None
    if os.environ.get("GPT_ATTN_BYPASS") == "1":
        # Diagnostic only: attention out = v isolates the NON-attention
        # step cost (all of which is per-token, so a bypassed step must
        # time identically across seq lengths at equal token count).
        return v
    return flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk)


class GptAttention(nn.Module):
    cfg: GptConfig
    attention_fn: Callable = causal_flash_attention
    decode: bool = False
    per_slot: bool = False  # per-row cache cursors (continuous batching)
    # kv_kernel: route per-slot decode KV writes through the Pallas
    # row-update kernel (ops/kv_cache.py). None defers to the
    # KUBEFLOW_TPU_KV_KERNEL env flag (deployment-wide default); True/False
    # pin it per model instance so the fast path is testable in-process.
    kv_kernel: Optional[bool] = None
    # paged: per-slot decode against a shared block arena + per-call block
    # tables instead of a contiguous [b, max_seq] cache (ISSUE 12). The
    # cache collection holds "k_arena"/"v_arena" [kv_blocks, kv_block_t,
    # h, d] (last row = trash block) and "cursors" [b]; the caller passes
    # the [b, max_blocks] table each apply.
    paged: bool = False
    kv_blocks: int = 0
    kv_block_t: int = 16
    # kv_dtype: arena storage precision (ISSUE 18). "bf16" stores cfg.dtype
    # directly (bit-parity ground truth); "int8" stores symmetric
    # per-(row, head) quantized values with an f32 scale arena alongside
    # ("k_scale"/"v_scale" [kv_blocks, kv_block_t, h, 1]) — 2x KV positions
    # per HBM byte, dequantized to f32 at the attention read.
    kv_dtype: str = "bf16"

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array,
                 block_tables: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.cfg
        if self.kv_dtype not in ("bf16", "int8"):
            raise ValueError(f"kv_dtype {self.kv_dtype!r}: expected bf16|int8")
        if self.kv_dtype == "int8" and self.decode and not self.paged:
            raise ValueError("int8 KV cache requires the paged arena layout")
        dense = functools.partial(
            nn.DenseGeneral,
            features=(cfg.n_heads, cfg.head_dim),
            axis=-1,
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            use_bias=False,
        )
        if self.decode:
            if self.paged:
                if not self.per_slot:
                    raise ValueError("paged KV decode requires per_slot=True")
                return self._paged_decode_attention(x, dense, block_tables)
            return self._decode_attention(x, dense)
        q = rope(dense(name="query")(x), positions, cfg.rope_theta)
        k = rope(dense(name="key")(x), positions, cfg.rope_theta)
        v = dense(name="value")(x)
        ctx = self.attention_fn(q, k, v)  # [b, L, heads, head_dim]
        return self._out_proj(ctx)

    def _out_proj(self, ctx: jax.Array) -> jax.Array:
        return nn.DenseGeneral(
            features=self.cfg.d_model,
            axis=(-2, -1),
            dtype=self.cfg.dtype,
            param_dtype=jnp.float32,
            use_bias=False,
            name="out_proj",
        )(ctx)

    def _decode_attention(self, x: jax.Array, dense) -> jax.Array:
        """Incremental attention against a KV cache (prefill: L>1 from
        position 0; decode steps: L==1 appended at the cache cursor).
        Static shapes throughout — the cache is [b, max_seq, h, d] and the
        validity mask makes unwritten slots invisible.

        ``per_slot=True`` keeps a cursor PER ROW (``cursors`` [b]) so every
        batch slot sits at its own sequence position — the cache layout
        continuous batching needs (serving/continuous.py): sequences join
        and leave the running batch without touching other rows.
        """
        cfg = self.cfg
        b, seg_len = x.shape[0], x.shape[1]
        cache_k = self.variable(
            "cache", "k", jnp.zeros, (b, cfg.max_seq, cfg.n_heads, cfg.head_dim), cfg.dtype
        )
        cache_v = self.variable(
            "cache", "v", jnp.zeros, (b, cfg.max_seq, cfg.n_heads, cfg.head_dim), cfg.dtype
        )
        if self.per_slot:
            cursors = self.variable("cache", "cursors", lambda: jnp.zeros((b,), jnp.int32))
            start = cursors.value                                   # [b]
            seg_positions = start[:, None] + jnp.arange(seg_len)    # [b, L]
            q = rope(dense(name="query")(x), seg_positions, cfg.rope_theta)
            k = rope(dense(name="key")(x), seg_positions, cfg.rope_theta)
            v = dense(name="value")(x)
            use_kernel = (
                _kv_kernel_enabled() if self.kv_kernel is None else self.kv_kernel
            )
            if seg_len == 1:
                if use_kernel:
                    # Pallas row-update kernel: touches ONE [1,8,h,d] tile
                    # per row instead of a full-cache pass per layer
                    # (ops/kv_cache.py; the where-select below reads+writes
                    # the whole [b,max,h,d] cache every layer — round-4's
                    # measured 8.2 vs 3.3 ms/step gap)
                    from ..ops.kv_cache import kv_row_update

                    keys = kv_row_update(cache_k.value, k[:, 0], start)
                    values = kv_row_update(cache_v.value, v[:, 0], start)
                else:
                    # broadcast-select instead of vmapped dynamic_update_slice:
                    # the vmap form lowers to a scatter (measured ~3x slower
                    # per decode step); a where over the cache fuses into one
                    # elementwise pass
                    at = (jnp.arange(cfg.max_seq)[None, :, None, None]
                          == start[:, None, None, None])            # [b,max,1,1]
                    keys = jnp.where(at, k, cache_k.value)
                    values = jnp.where(at, v, cache_v.value)
            else:
                upd = jax.vmap(
                    lambda cache_row, seg, s: jax.lax.dynamic_update_slice(
                        cache_row, seg, (s, 0, 0))
                )
                keys = upd(cache_k.value, k, start)
                values = upd(cache_v.value, v, start)
            mask = (jnp.arange(cfg.max_seq)[None, None, None, :]
                    <= seg_positions[:, None, :, None])             # [b,1,L,max]
        else:
            cursor = self.variable("cache", "cursor", lambda: jnp.zeros((), jnp.int32))
            start = cursor.value
            seg_positions = start + jnp.arange(seg_len)
            q = rope(dense(name="query")(x), seg_positions, cfg.rope_theta)
            k = rope(dense(name="key")(x), seg_positions, cfg.rope_theta)
            v = dense(name="value")(x)
            keys = jax.lax.dynamic_update_slice(cache_k.value, k, (0, start, 0, 0))
            values = jax.lax.dynamic_update_slice(cache_v.value, v, (0, start, 0, 0))
            mask = (jnp.arange(cfg.max_seq)[None, None, None, :]
                    <= seg_positions[None, None, :, None])
        # flax init runs the forward once for shapes/params — the cache must
        # not advance then, or the first real prefill starts mid-cache.
        if not self.is_initializing():
            cache_k.value = keys
            cache_v.value = values
            if self.per_slot:
                cursors.value = start + seg_len
            else:
                cursor.value = start + seg_len

        scale = cfg.head_dim**-0.5
        scores = (
            jnp.einsum(
                "bqhd,bkhd->bhqk",
                q.astype(jnp.float32),
                keys.astype(jnp.float32),
            )
            * scale
        )
        scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, values.astype(jnp.float32))
        return self._out_proj(ctx.astype(cfg.dtype))

    def _paged_decode_attention(self, x: jax.Array, dense,
                                block_tables: jax.Array) -> jax.Array:
        """Per-slot decode against the shared block arena (ISSUE 12).

        Same math as the per-slot branch of :meth:`_decode_attention`, with
        the [b, max_seq] cache replaced by an indirect view: the write goes
        through the block table (Pallas ``kv_block_update`` or the XLA
        scatter reference), and the read gathers ``arena[tables]`` back
        into a [b, max_blocks*block_t, h, d] view. When ``block_t`` divides
        ``max_seq`` (the engine enforces it) that view has exactly the
        contiguous cache's shape, so the masked softmax/einsum below is
        bit-identical to the contiguous path — the parity suite's contract.
        Rows whose table entries point at the trash block read garbage
        there, but only at positions the ``<= cursor`` mask already hides.
        """
        cfg = self.cfg
        b, seg_len = x.shape[0], x.shape[1]
        quant = self.kv_dtype == "int8"
        arena_shape = (max(self.kv_blocks, 1), self.kv_block_t,
                       cfg.n_heads, cfg.head_dim)
        arena_dtype = jnp.int8 if quant else cfg.dtype
        cache_k = self.variable("cache", "k_arena", jnp.zeros, arena_shape, arena_dtype)
        cache_v = self.variable("cache", "v_arena", jnp.zeros, arena_shape, arena_dtype)
        if quant:
            scale_shape = arena_shape[:3] + (1,)
            scale_k = self.variable("cache", "k_scale", jnp.zeros, scale_shape, jnp.float32)
            scale_v = self.variable("cache", "v_scale", jnp.zeros, scale_shape, jnp.float32)
        cursors = self.variable("cache", "cursors", lambda: jnp.zeros((b,), jnp.int32))
        if block_tables is None:
            raise ValueError("paged decode needs block_tables=[b, max_blocks]")
        start = cursors.value                                   # [b]
        seg_positions = start[:, None] + jnp.arange(seg_len)    # [b, L]
        q = rope(dense(name="query")(x), seg_positions, cfg.rope_theta)
        k = rope(dense(name="key")(x), seg_positions, cfg.rope_theta)
        v = dense(name="value")(x)
        use_kernel = (
            _kv_kernel_enabled() if self.kv_kernel is None else self.kv_kernel
        )
        from ..ops.kv_cache import (kv_block_update, kv_block_update_quant,
                                    kv_block_update_ref, quantize_kv)

        if quant:
            if seg_len == 1 and use_kernel:
                keys_arena, k_scales = kv_block_update_quant(
                    cache_k.value, scale_k.value, k[:, 0], start,
                    block_tables, max_seq=cfg.max_seq)
                vals_arena, v_scales = kv_block_update_quant(
                    cache_v.value, scale_v.value, v[:, 0], start,
                    block_tables, max_seq=cfg.max_seq)
            else:
                kq, ks = quantize_kv(k)
                vq, vs = quantize_kv(v)
                keys_arena = kv_block_update_ref(
                    cache_k.value, kq, start, block_tables, max_seq=cfg.max_seq)
                vals_arena = kv_block_update_ref(
                    cache_v.value, vq, start, block_tables, max_seq=cfg.max_seq)
                k_scales = kv_block_update_ref(
                    scale_k.value, ks, start, block_tables, max_seq=cfg.max_seq)
                v_scales = kv_block_update_ref(
                    scale_v.value, vs, start, block_tables, max_seq=cfg.max_seq)
        elif seg_len == 1 and use_kernel:
            keys_arena = kv_block_update(
                cache_k.value, k[:, 0], start, block_tables, max_seq=cfg.max_seq)
            vals_arena = kv_block_update(
                cache_v.value, v[:, 0], start, block_tables, max_seq=cfg.max_seq)
        else:
            keys_arena = kv_block_update_ref(
                cache_k.value, k, start, block_tables, max_seq=cfg.max_seq)
            vals_arena = kv_block_update_ref(
                cache_v.value, v, start, block_tables, max_seq=cfg.max_seq)
        if not self.is_initializing():
            cache_k.value = keys_arena
            cache_v.value = vals_arena
            if quant:
                scale_k.value = k_scales
                scale_v.value = v_scales
            cursors.value = start + seg_len

        bt = arena_shape[1]
        mb = block_tables.shape[1]
        view = (b, mb * bt, cfg.n_heads, cfg.head_dim)
        if quant:
            # load-dequantized read: gather values + scales through the same
            # table, dequantize to f32 (the einsum below is f32 regardless)
            sview = (b, mb * bt, cfg.n_heads, 1)
            keys = (keys_arena[block_tables].reshape(view).astype(jnp.float32)
                    * k_scales[block_tables].reshape(sview))
            values = (vals_arena[block_tables].reshape(view).astype(jnp.float32)
                      * v_scales[block_tables].reshape(sview))
        else:
            keys = keys_arena[block_tables].reshape(view)
            values = vals_arena[block_tables].reshape(view)
        mask = (jnp.arange(mb * bt)[None, None, None, :]
                <= seg_positions[:, None, :, None])             # [b,1,L,mb*bt]
        scale = cfg.head_dim**-0.5
        scores = (
            jnp.einsum(
                "bqhd,bkhd->bhqk",
                q.astype(jnp.float32),
                keys.astype(jnp.float32),
            )
            * scale
        )
        scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, values.astype(jnp.float32))
        return self._out_proj(ctx.astype(cfg.dtype))


class GptMlp(nn.Module):
    cfg: GptConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        h = nn.Dense(cfg.d_ff, dtype=cfg.dtype, param_dtype=jnp.float32,
                     use_bias=False, name="up_proj")(x)
        h = nn.gelu(h)
        return nn.Dense(cfg.d_model, dtype=cfg.dtype, param_dtype=jnp.float32,
                        use_bias=False, name="down_proj")(h)


class GptBlock(nn.Module):
    cfg: GptConfig
    attention_fn: Callable = causal_flash_attention
    mesh: Optional[Any] = None
    decode: bool = False
    per_slot: bool = False
    kv_kernel: Optional[bool] = None
    paged: bool = False
    kv_blocks: int = 0
    kv_block_t: int = 16
    kv_dtype: str = "bf16"

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array,
                 block_tables: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.cfg
        ln = functools.partial(nn.LayerNorm, dtype=jnp.float32, param_dtype=jnp.float32)
        x = x + GptAttention(cfg, self.attention_fn, self.decode, self.per_slot,
                             self.kv_kernel, self.paged, self.kv_blocks,
                             self.kv_block_t, self.kv_dtype, name="attention")(
            ln(name="ln_attn")(x).astype(cfg.dtype), positions, block_tables
        )
        normed = ln(name="ln_mlp")(x).astype(cfg.dtype)
        if cfg.num_experts > 0:
            from kubeflow_tpu.parallel.moe import MoEMlp

            ffn = MoEMlp(
                num_experts=cfg.num_experts,
                d_ff=cfg.d_ff,
                k=cfg.moe_k,
                mesh=self.mesh,
                dtype=cfg.dtype,
                name="moe",
            )(normed)
        else:
            ffn = GptMlp(cfg, name="mlp")(normed)
        return x + ffn

    def scan_body(self, x: jax.Array, positions: jax.Array):
        """(carry, ys) form of ``__call__`` for ``nn.scan`` (cfg.scan_blocks)."""
        return self(x, positions), None


class GptLM(nn.Module):
    """Decoder-only LM. input_ids [b, L] -> logits [b, L, vocab] (f32).

    The output projection ties to the input embedding (standard GPT-2
    weight tying — halves the largest parameter and its gradient traffic).
    """

    cfg: GptConfig
    attention_fn: Callable = causal_flash_attention
    mesh: Optional[Any] = None
    decode: bool = False
    per_slot: bool = False
    kv_kernel: Optional[bool] = None
    paged: bool = False
    kv_blocks: int = 0
    kv_block_t: int = 16
    kv_dtype: str = "bf16"

    @nn.compact
    def __call__(self, input_ids: jax.Array, *,
                 block_tables: Optional[jax.Array] = None,
                 return_hidden: bool = False) -> jax.Array:
        cfg = self.cfg
        embed = nn.Embed(
            cfg.vocab_size,
            cfg.d_model,
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            name="embedding",
        )
        x = embed(input_ids)
        positions = jnp.arange(input_ids.shape[1])  # decode path derives its own
        if cfg.scan_blocks and not self.decode:
            # One traced block, n_layers iterations: params stack on a
            # leading layer axis under ``blocks/``; remat wraps the body so
            # each layer's activations rematerialize in backward.
            body = GptBlock
            if cfg.remat:
                body = nn.remat(body, prevent_cse=False, methods=["scan_body"])
            stack = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=nn.broadcast,
                length=cfg.n_layers,
                methods=["scan_body"],
            )
            x, _ = stack(cfg, self.attention_fn, self.mesh,
                         name="blocks").scan_body(x, positions)
        else:
            if cfg.scan_blocks and self.decode:
                raise ValueError(
                    "scan_blocks is a training/forward layout; the decode path "
                    "needs per-layer cache naming — unstack the params "
                    "(inverse of stack_block_params) and decode with "
                    "scan_blocks=False"
                )
            block = GptBlock
            if cfg.remat:
                block = nn.remat(GptBlock, static_argnums=())
            for i in range(cfg.n_layers):
                x = block(cfg, self.attention_fn, self.mesh, self.decode,
                          self.per_slot, self.kv_kernel, self.paged,
                          self.kv_blocks, self.kv_block_t, self.kv_dtype,
                          name=f"block_{i}")(x, positions, block_tables)
        x = nn.LayerNorm(dtype=jnp.float32, param_dtype=jnp.float32, name="ln_final")(x)
        if return_hidden:
            # final hidden states for a fused loss (blockwise_causal_lm_loss)
            # — the [b, L, vocab] logits never materialize
            return x.astype(jnp.float32)
        # tied LM head in f32 (embed.attend would compute in the module's
        # bf16 dtype; the final softmax wants full precision)
        logits = x.astype(jnp.float32) @ embed.embedding.T.astype(jnp.float32)
        return logits


def causal_lm_loss(logits: jax.Array, input_ids: jax.Array) -> jax.Array:
    """Next-token cross entropy; position t predicts token t+1."""
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32))
    targets = input_ids[:, 1:]
    picked = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(picked)


def blockwise_causal_lm_loss(
    hidden: jax.Array,
    embedding: jax.Array,
    input_ids: jax.Array,
    block_size: int = 4096,
) -> jax.Array:
    """Fused next-token cross entropy over a tied LM head that never
    materializes the ``[b, L, vocab]`` f32 logits.

    Same math as ``causal_lm_loss(hidden @ embedding.T, ids)``:
    ``loss = mean(logsumexp(x·W^T) - x·W[target])``, with the logsumexp
    accumulated ONLINE over vocab chunks (running max + rescaled sum — the
    ``causal_flash_attention`` trick applied to the vocab axis). Peak
    residency is one ``[tokens, block_size]`` chunk instead of the full
    ``[b, L, vocab]`` f32 logits (1 GiB at the bench's b8/L1024/V32000,
    ~3x that through log_softmax), which is what caps the benchable batch.
    The scan body is ``jax.checkpoint``ed so backward recomputes each
    chunk's logits instead of saving them.

    ``hidden``: [b, L, d] final hidden states (``GptLM(...)(ids,
    return_hidden=True)``); ``embedding``: the [vocab, d] tied embedding
    (``params["embedding"]["embedding"]``) — gradients flow to both.
    """
    b, seq_len, d = hidden.shape
    vocab = embedding.shape[0]
    x = hidden[:, :-1].reshape(b * (seq_len - 1), d).astype(jnp.float32)
    targets = input_ids[:, 1:].reshape(-1)

    n_blocks = -(-vocab // block_size)
    padded = n_blocks * block_size
    w = embedding.astype(jnp.float32)
    if padded != vocab:
        w = jnp.pad(w, ((0, padded - vocab), (0, 0)))
    w = w.reshape(n_blocks, block_size, d)
    valid = (jnp.arange(padded) < vocab).reshape(n_blocks, block_size)

    def body(carry, wv):
        wb, valid_b = wv
        m, s = carry
        logits = jax.lax.dot_general(
            x, wb, (((1,), (1,)), ((), ())))          # [tokens, block_size]
        logits = jnp.where(valid_b[None, :], logits, -1e30)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        return (m_new, s), None

    init = (
        jnp.full((x.shape[0],), -1e30, jnp.float32),
        jnp.zeros((x.shape[0],), jnp.float32),
    )
    (m, s), _ = jax.lax.scan(jax.checkpoint(body), init, (w, valid))
    lse = m + jnp.log(s)
    # target logit via a [tokens, d] gather — never the full logits row
    target_logit = jnp.sum(x * embedding[targets].astype(jnp.float32), axis=-1)
    return jnp.mean(lse - target_logit)


def stack_block_params(params: Any, n_layers: int) -> Any:
    """Convert an unrolled-layout param tree (``block_0..block_{n-1}``) to
    the ``scan_blocks=True`` layout (``blocks`` with a leading layer axis).
    Lets loop-trained checkpoints load into the scanned model (the decode
    path keeps the unrolled layout, so serving checkpoints stay as-is)."""
    layers = [params[f"block_{i}"] for i in range(n_layers)]
    out = {k: v for k, v in params.items() if not k.startswith("block_")}
    out["blocks"] = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)
    return out


@functools.lru_cache(maxsize=64)
def _generate_fn(cfg: GptConfig, max_new_tokens: int, temperature: float):
    """One compiled decode program per (config, token budget, temperature);
    prompt shape differences re-specialize inside the same jit cache."""
    model = GptLM(cfg, decode=True)

    def sample(logits: jax.Array, key: jax.Array) -> jax.Array:
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(key, logits / temperature, axis=-1).astype(jnp.int32)

    @jax.jit
    def run(params, cache, prompt_ids, rng):
        logits, updated = model.apply(
            {"params": params, "cache": cache}, prompt_ids, mutable=["cache"]
        )
        rng, key = jax.random.split(rng)
        tok = sample(logits[:, -1], key)

        def step(carry, _):
            cache, tok, rng = carry
            logits, updated = model.apply(
                {"params": params, "cache": cache}, tok[:, None], mutable=["cache"]
            )
            rng, key = jax.random.split(rng)
            nxt = sample(logits[:, -1], key)
            return (updated["cache"], nxt, rng), tok

        (cache, last, rng), toks = jax.lax.scan(
            step, (updated["cache"], tok, rng), None, length=max_new_tokens - 1
        )
        generated = jnp.concatenate([jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)
        return jnp.concatenate([prompt_ids.astype(jnp.int32), generated], axis=1)

    return model, run


def generate(
    cfg: GptConfig,
    params: Any,
    prompt_ids: jax.Array,
    max_new_tokens: int,
    rng: Optional[jax.Array] = None,
    temperature: float = 0.0,
) -> jax.Array:
    """Autoregressive decoding with a KV cache: one prefill forward over the
    prompt, then `lax.scan` single-token steps — static shapes throughout
    (the TPU decoding recipe), with the compiled program cached across calls
    per (config, max_new_tokens, temperature, prompt shape).
    ``temperature=0`` is greedy; otherwise samples.

    Returns [batch, prompt_len + max_new_tokens] token ids (int32).
    """
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    total = prompt_ids.shape[1] + max_new_tokens
    if total > cfg.max_seq:
        raise ValueError(f"prompt+new = {total} exceeds max_seq {cfg.max_seq}")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    _, run = _generate_fn(cfg, max_new_tokens, float(temperature))
    return run(params, _fresh_cache(cfg, prompt_ids.shape[0]), prompt_ids, rng)


def _fresh_cache(cfg: GptConfig, batch: int) -> Any:
    """Zeroed KV cache in the exact structure GptLM(decode=True) owns —
    closed-form from the config, no tracing on the request path. (Module
    naming drift would break `generate` outright, which the decode tests
    catch.)"""
    kv_shape = (batch, cfg.max_seq, cfg.n_heads, cfg.head_dim)
    return {
        f"block_{i}": {
            "attention": {
                "k": jnp.zeros(kv_shape, cfg.dtype),
                "v": jnp.zeros(kv_shape, cfg.dtype),
                "cursor": jnp.zeros((), jnp.int32),
            }
        }
        for i in range(cfg.n_layers)
    }
