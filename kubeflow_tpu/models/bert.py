"""BERT encoder for TPU: serving-path model (BASELINE: BERT-base inference)
and the tensor/sequence-parallel exemplar.

TPU-first choices:
- bf16 compute / f32 params; attention softmax accumulates in f32,
- the attention primitive is injectable: ``full_attention`` (one chip,
  short sequences) or ``ring_attention`` (seq-parallel long context) from
  kubeflow_tpu.parallel.ring_attention — the module code is identical,
- parameter names (query/key/value, out_proj, mlp_wi/mlp_wo, embedding)
  line up with kubeflow_tpu.parallel.sharding's logical-axis heuristics so
  TENSOR_PARALLEL_RULES shards heads/mlp over the ``model`` mesh axis.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp

from kubeflow_tpu.parallel.ring_attention import full_attention


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def base(cls) -> "BertConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "BertConfig":
        """For tests and HPO trials on CPU."""
        return cls(vocab_size=1024, hidden_size=64, num_layers=2, num_heads=4,
                   intermediate_size=128, max_position_embeddings=128)


class BertSelfAttention(nn.Module):
    config: BertConfig
    attention_fn: Callable = full_attention

    @nn.compact
    def __call__(self, hidden, mask=None):
        cfg = self.config
        dense = lambda name: nn.DenseGeneral(
            features=(cfg.num_heads, cfg.head_dim),
            axis=-1,
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            name=name,
        )
        q, k, v = dense("query")(hidden), dense("key")(hidden), dense("value")(hidden)
        ctx = self.attention_fn(q, k, v)  # [b, L, heads, head_dim]
        out = nn.DenseGeneral(
            features=cfg.hidden_size,
            axis=(-2, -1),
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            name="out_proj",
        )(ctx)
        return out


class BertLayer(nn.Module):
    config: BertConfig
    attention_fn: Callable = full_attention

    @nn.compact
    def __call__(self, hidden, mask=None):
        cfg = self.config
        ln = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                                       param_dtype=jnp.float32, name=name)
        attn_out = BertSelfAttention(cfg, self.attention_fn, name="attention")(hidden, mask)
        hidden = ln("attention_ln")(hidden + attn_out)
        mlp = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype, param_dtype=jnp.float32,
                       name="mlp_wi")(hidden)
        mlp = nn.gelu(mlp, approximate=True)
        mlp = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, param_dtype=jnp.float32,
                       name="mlp_wo")(mlp)
        return ln("output_ln")(hidden + mlp)


class BertEncoder(nn.Module):
    """Token ids -> contextual embeddings [b, L, hidden]."""

    config: BertConfig
    attention_fn: Callable = full_attention

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, position_ids=None):
        cfg = self.config
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if position_ids is None:
            position_ids = jnp.arange(input_ids.shape[-1])[None, :]
        embed = lambda num, name: nn.Embed(
            num, cfg.hidden_size, dtype=cfg.dtype, param_dtype=jnp.float32, name=name
        )
        hidden = (
            embed(cfg.vocab_size, "word_embedding")(input_ids)
            + embed(cfg.max_position_embeddings, "position_embedding")(position_ids)
            + embed(cfg.type_vocab_size, "type_embedding")(token_type_ids)
        )
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                              param_dtype=jnp.float32, name="embedding_ln")(hidden)
        for i in range(cfg.num_layers):
            hidden = BertLayer(cfg, self.attention_fn, name=f"layer_{i}")(hidden)
        return hidden


class BertForMaskedLM(nn.Module):
    """MLM head for pretraining-style benchmarks + serving logits."""

    config: BertConfig
    attention_fn: Callable = full_attention

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None):
        cfg = self.config
        hidden = BertEncoder(cfg, self.attention_fn, name="encoder")(input_ids, token_type_ids)
        hidden = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, param_dtype=jnp.float32,
                          name="mlm_transform")(hidden)
        hidden = nn.gelu(hidden, approximate=True)
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                              param_dtype=jnp.float32, name="mlm_ln")(hidden)
        # Logits in f32 for a stable softmax-xent.
        logits = nn.Dense(cfg.vocab_size, dtype=jnp.float32, param_dtype=jnp.float32,
                          name="mlm_head")(hidden.astype(jnp.float32))
        return logits
