"""Control-plane scale observatory: synthetic topologies + seeded load.

``topology`` builds deterministic thousand-node clusters (pools, selectors,
gang shapes) from a seed; ``loadgen`` drives gang-arrival waves, pod churn,
node kills, and watch storms against the real apiserver+scheduler stack
over HTTP. Together they are the harness behind ``tools/bench_controlplane``
and ``e2e/controlplane_scale_driver.py`` (ROADMAP item 5).
"""

from .topology import POOL_LABEL, GangShape, PoolSpec, SyntheticTopology, synth_gangs, synthesize
from .loadgen import LoadGenerator

__all__ = [
    "POOL_LABEL",
    "GangShape",
    "PoolSpec",
    "SyntheticTopology",
    "synth_gangs",
    "synthesize",
    "LoadGenerator",
]
