"""Seeded load generator: drives the real apiserver+scheduler over HTTP.

Every scenario step (gang arrivals, pod churn, node kills, watch storms)
draws from one ``random.Random(seed)`` stream, so a run is replayable from
``(topology, seed)`` alone. All traffic goes through the apiserver's real
HTTP listener — the point is to load the full stack (routing, auth hooks,
JSON codec, watch fanout), not the Store in isolation.

The generator never writes ``spec.nodeName`` — binding is the scheduler's
job; the loadgen only observes bindings via reads.
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Iterable, List, Optional

from ..runtime.tracing import TRACER, format_traceparent
from ..scheduler.gang import POD_GROUP_LABEL, POD_GROUP_SIZE_ANNOTATION
from ..tpu.topology import RESOURCE_TPU
from .topology import GangShape, SyntheticTopology


def _percentile(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    ordered = sorted(samples)
    idx = min(len(ordered) - 1, int(round(q * (len(ordered) - 1))))
    return ordered[idx]


class LoadGenerator:
    def __init__(self, base_url: str, topology: SyntheticTopology,
                 seed: int = 0, namespace: str = "default",
                 timeout_s: float = 30.0, flow: Optional[str] = None,
                 traceparent: Optional[str] = None) -> None:
        self.base = base_url.rstrip("/")
        self.topology = topology
        self.namespace = namespace
        self.timeout_s = timeout_s
        self.rng = random.Random(f"loadgen:{seed}")
        self.submitted_gangs: Dict[str, GangShape] = {}
        #: flow identity stamped on every request (X-Flow-Client) so the
        #: apiserver's fairness gate can classify this generator's traffic —
        #: the abuse harness runs one loadgen per tenant persona
        self.flow = flow
        #: W3C trace context for this generator's traffic: gang submits open
        #: a client-side ``gang.submit`` span continuing it, so the trace
        #: federation e2e can inject a known trace id at the user edge and
        #: find it again in the bound pod's annotation
        self.traceparent = traceparent

    # -- raw HTTP -------------------------------------------------------------

    def _request(self, method: str, path: str, body: Optional[dict] = None) -> Any:
        data = json.dumps(body).encode() if body is not None else None
        headers = {"content-type": "application/json"} if data else {}
        if self.flow:
            headers["x-flow-client"] = self.flow
        cur = TRACER.current_span()
        if cur is not None:
            headers["traceparent"] = format_traceparent(cur)
        elif self.traceparent:
            headers["traceparent"] = self.traceparent
        req = urllib.request.Request(
            self.base + path, data=data, headers=headers, method=method)
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            payload = resp.read()
        return json.loads(payload) if payload else None

    def _get(self, path: str) -> Any:
        return self._request("GET", path)

    def _post(self, path: str, body: dict) -> Any:
        return self._request("POST", path, body)

    def _delete(self, path: str) -> None:
        try:
            self._request("DELETE", path)
        except urllib.error.HTTPError as err:
            if err.code != 404:  # racing a GC is fine, anything else is not
                raise

    # -- topology -------------------------------------------------------------

    def register_nodes(self, limit: Optional[int] = None) -> int:
        """POST every synthetic node; returns how many were created."""
        count = 0
        for node in self.topology.nodes():
            if limit is not None and count >= limit:
                break
            self._post("/api/v1/nodes", node)
            count += 1
        return count

    def kill_nodes(self, count: int) -> List[str]:
        """Seeded node kills — the churn a preemptible fleet sees."""
        names = self.topology.node_names()
        doomed = self.rng.sample(names, min(count, len(names)))
        for name in doomed:
            self._delete(f"/api/v1/nodes/{name}")
        return doomed

    # -- gangs ----------------------------------------------------------------

    def pod_name(self, gang: str, i: int) -> str:
        return f"{gang}-{i}"

    def submit_gang(self, shape: GangShape) -> List[str]:
        # The user edge of the gang journey: every member POST runs under
        # one client-side span (continuing self.traceparent when set), so
        # the federated trace starts in THIS process, not at the apiserver.
        with TRACER.span("gang.submit", traceparent=self.traceparent,
                         gang=shape.name, size=shape.size):
            return self._submit_gang(shape)

    def _submit_gang(self, shape: GangShape) -> List[str]:
        names = []
        for i in range(shape.size):
            name = self.pod_name(shape.name, i)
            pod = {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": name,
                    "namespace": self.namespace,
                    "labels": {POD_GROUP_LABEL: shape.name},
                    "annotations": {POD_GROUP_SIZE_ANNOTATION: str(shape.size)},
                },
                "spec": {
                    "nodeSelector": dict(shape.selector),
                    "containers": [{
                        "name": "trainer",
                        "resources": {
                            "limits": {RESOURCE_TPU: str(shape.chips_per_pod)}},
                    }],
                },
            }
            self._post(f"/api/v1/namespaces/{self.namespace}/pods", pod)
            names.append(name)
        self.submitted_gangs[shape.name] = shape
        return names

    def gang_wave(self, shapes: Iterable[GangShape]) -> List[str]:
        pods: List[str] = []
        for shape in shapes:
            pods.extend(self.submit_gang(shape))
        return pods

    def _list_pods(self) -> List[Dict[str, Any]]:
        return self._get(f"/api/v1/namespaces/{self.namespace}/pods")["items"]

    def bound_gangs(self) -> Dict[str, int]:
        """gang name -> members bound so far (observed via reads)."""
        bound: Dict[str, int] = {}
        for pod in self._list_pods():
            gang = (pod["metadata"].get("labels") or {}).get(POD_GROUP_LABEL)
            if gang and (pod.get("spec") or {}).get("nodeName"):
                bound[gang] = bound.get(gang, 0) + 1
        return bound

    def wait_gangs_bound(self, gangs: Iterable[str], timeout_s: float = 60.0,
                         interval_s: float = 0.1) -> None:
        want = {g: self.submitted_gangs[g].size for g in gangs}
        missing = dict(want)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            bound = self.bound_gangs()
            missing = {g: n for g, n in want.items() if bound.get(g, 0) < n}
            if not missing:
                return
            time.sleep(interval_s)
        raise AssertionError(f"gangs not fully bound after {timeout_s}s: {missing}")

    def churn_pods(self, fraction: float) -> int:
        """Delete a seeded fraction of bound pods (notebook-style churn)."""
        bound = [p["metadata"]["name"] for p in self._list_pods()
                 if (p.get("spec") or {}).get("nodeName")]
        doomed = self.rng.sample(bound, int(len(bound) * fraction))
        for name in doomed:
            self._delete(f"/api/v1/namespaces/{self.namespace}/pods/{name}")
        return len(doomed)

    # -- watch storm ----------------------------------------------------------

    def watch_storm(self, streams: int = 8, relists: int = 32,
                    duration_s: float = 2.0) -> Dict[str, Any]:
        """Mass relist: ``streams`` concurrent watch streams draining events
        while ``relists`` full LISTs fire back-to-back — the NotebookOS-style
        fanout burst. Returns client-side latency stats; the server-side view
        is ``apiserver_request_seconds{verb="list"}``."""
        stop = threading.Event()
        events_seen = [0] * streams

        def drain(idx: int) -> None:
            url = (f"{self.base}/api/v1/namespaces/{self.namespace}/pods"
                   "?watch=true&sendInitial=true")
            req = urllib.request.Request(
                url, headers={"x-flow-client": self.flow} if self.flow else {})
            try:
                with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                    while not stop.is_set():
                        line = resp.readline()
                        if not line:
                            break
                        events_seen[idx] += 1
            except (OSError, urllib.error.URLError):
                pass  # the server tearing down mid-storm is part of the storm

        threads = [threading.Thread(target=drain, args=(i,), daemon=True)
                   for i in range(streams)]
        for t in threads:
            t.start()
        latencies_ms: List[float] = []
        deadline = time.monotonic() + duration_s
        fired = 0
        while fired < relists or time.monotonic() < deadline:
            t0 = time.perf_counter()
            self._list_pods()
            latencies_ms.append((time.perf_counter() - t0) * 1000.0)
            fired += 1
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        return {
            "streams": streams,
            "lists": fired,
            "watch_events": sum(events_seen),
            "list_p50_ms": _percentile(latencies_ms, 0.50),
            "list_p99_ms": _percentile(latencies_ms, 0.99),
        }
