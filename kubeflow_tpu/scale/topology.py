"""Deterministic synthetic cluster topologies for control-plane scale runs.

A :class:`SyntheticTopology` is fully determined by ``(num_nodes, seed)``:
the same inputs produce byte-identical node objects, pool splits, and gang
shapes on every run, so bench rows and parity tests are reproducible. Nodes
are shaped exactly like the GKE-style fixtures the controllers already
understand (``make_tpu_node``), with one extra pool label the scheduler's
indexed ledger can group on.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

from ..controllers.builtin import make_tpu_node

POOL_LABEL = "scale.kubeflow.org/pool"

# (generation, topology label, chips per node) — the slice shapes real
# GKE TPU node pools come in; chips/node stays small so gangs span nodes.
_POOL_KINDS = (
    ("v4", "2x2x1", 4),
    ("v5e", "2x4", 8),
    ("v5e", "2x2", 4),
    ("v5p", "2x2x4", 16),
)


@dataclass(frozen=True)
class PoolSpec:
    name: str
    generation: str
    topology: str
    chips_per_node: int
    nodes: int

    def selector(self) -> Dict[str, str]:
        return {POOL_LABEL: self.name}


@dataclass(frozen=True)
class GangShape:
    name: str
    size: int
    chips_per_pod: int
    selector: Dict[str, str] = field(default_factory=dict)


@dataclass
class SyntheticTopology:
    seed: int
    pools: List[PoolSpec]

    @property
    def total_nodes(self) -> int:
        return sum(p.nodes for p in self.pools)

    @property
    def total_chips(self) -> int:
        return sum(p.nodes * p.chips_per_node for p in self.pools)

    def pool(self, name: str) -> PoolSpec:
        for p in self.pools:
            if p.name == name:
                return p
        raise KeyError(name)

    def node_name(self, pool: PoolSpec, i: int) -> str:
        return f"{pool.name}-node-{i:05d}"

    def nodes(self) -> Iterator[Dict[str, Any]]:
        """Node objects in deterministic order (pool by pool)."""
        for pool in self.pools:
            for i in range(pool.nodes):
                node = make_tpu_node(
                    self.node_name(pool, i), pool.generation, pool.topology,
                    pool.chips_per_node)
                node["metadata"]["labels"][POOL_LABEL] = pool.name
                yield node

    def node_names(self) -> List[str]:
        return [self.node_name(p, i) for p in self.pools for i in range(p.nodes)]


def synthesize(num_nodes: int, seed: int = 0,
               num_pools: Optional[int] = None) -> SyntheticTopology:
    """Split ``num_nodes`` across a few heterogeneous pools, seeded."""
    # string seeds stay deterministic across processes (tuple seeds hash)
    rng = random.Random(f"topology:{seed}:{num_nodes}")
    if num_pools is None:
        num_pools = max(1, min(len(_POOL_KINDS), num_nodes // 50 or 1))
    # seeded weights decide the split; every pool gets at least one node
    weights = [rng.uniform(0.5, 1.5) for _ in range(num_pools)]
    total_w = sum(weights)
    counts = [max(1, int(num_nodes * w / total_w)) for w in weights]
    counts[0] += num_nodes - sum(counts)  # absorb rounding in the first pool
    pools = []
    for i, count in enumerate(counts):
        generation, topo, chips = _POOL_KINDS[i % len(_POOL_KINDS)]
        pools.append(PoolSpec(
            name=f"pool-{i}-{generation}", generation=generation,
            topology=topo, chips_per_node=chips, nodes=count))
    return SyntheticTopology(seed=seed, pools=pools)


def synth_gangs(topology: SyntheticTopology, count: int, seed: int = 0,
                prefix: str = "gang", max_size: int = 8) -> List[GangShape]:
    """Seeded gang shapes sized to fit somewhere in ``topology``: each gang
    targets one pool via selector and asks for at most a node's worth of
    chips per pod, so a quiet cluster can always bind it."""
    rng = random.Random(f"gangs:{seed}:{count}")
    shapes = []
    for i in range(count):
        pool = rng.choice(topology.pools)
        size = rng.randint(2, min(max_size, max(2, pool.nodes)))
        chips = rng.choice([c for c in (1, 2, 4, pool.chips_per_node)
                            if c <= pool.chips_per_node])
        shapes.append(GangShape(
            name=f"{prefix}-{i:04d}", size=size, chips_per_pod=chips,
            selector=pool.selector()))
    return shapes
