"""Platform assembly: wire the full control plane over one store.

The in-process analog of deploying all reference components into a cluster
(manifests L9): builtin substrate controllers, the PodDefault webhook, and
the platform controllers, all sharing one Store. Tests and the e2e harness
build a platform and drive it exactly the way a user drives a cluster.
"""

from __future__ import annotations

from typing import Optional

from .apiserver.store import Store
from .controllers.builtin import DeploymentReconciler, PodletReconciler, StatefulSetReconciler
from .controllers.notebook import NotebookConfig, NotebookReconciler
from .controllers.profile import ProfileConfig, ProfileReconciler
from .controllers.studyjob import StudyJobReconciler, TrialPodRunner
from .controllers.tensorboard import TensorboardConfig, TensorboardReconciler
from .runtime.manager import Manager, Reconciler
from .scheduler.core import SchedulerReconciler
from .serving.controller import InferenceServiceReconciler, ServingConfig
from .webhook.poddefault import admission_hook


def build_platform(
    store: Optional[Store] = None,
    notebook_config: Optional[NotebookConfig] = None,
    profile_config: Optional[ProfileConfig] = None,
    tensorboard_config: Optional[TensorboardConfig] = None,
    serving_config: Optional[ServingConfig] = None,
    trial_runner: Optional[Reconciler] = None,
    with_substrate: bool = True,
    scheduler: Optional[Reconciler] = None,
    extra_reconcilers=(),
) -> Manager:
    mgr = Manager(store)
    domain = (notebook_config or NotebookConfig()).cluster_domain
    mgr.store.register_admission(admission_hook(mgr.client, cluster_domain=domain))
    if with_substrate:
        mgr.add(StatefulSetReconciler())
        mgr.add(DeploymentReconciler())
        mgr.add(scheduler if scheduler is not None else SchedulerReconciler())
        mgr.add(PodletReconciler())
    mgr.add(NotebookReconciler(notebook_config))
    mgr.add(ProfileReconciler(profile_config))
    mgr.add(TensorboardReconciler(tensorboard_config))
    mgr.add(StudyJobReconciler())
    mgr.add(trial_runner if trial_runner is not None else TrialPodRunner())
    mgr.add(InferenceServiceReconciler(serving_config))
    for rec in extra_reconcilers:
        mgr.add(rec)
    return mgr
