"""TPU-slice gang scheduler: the one component allowed to bind pods.

See docs/SCHEDULER.md for the gang/priority/quota model and the split
from PodletReconciler (now a pure kubelet).
"""

from .core import SCHED, BackoffQueue, SchedulerReconciler
from .flight import Decision, FlightRecorder
from .gang import (
    DEFAULT_PRIORITY,
    POD_GROUP_LABEL,
    POD_GROUP_SIZE_ANNOTATION,
    PRIORITY_CLASSES,
    Gang,
    gang_of,
    priority_of,
    requires_scheduling,
)
from .ledger import ChipLedger

__all__ = [
    "SCHED",
    "BackoffQueue",
    "SchedulerReconciler",
    "ChipLedger",
    "Decision",
    "FlightRecorder",
    "Gang",
    "gang_of",
    "priority_of",
    "requires_scheduling",
    "POD_GROUP_LABEL",
    "POD_GROUP_SIZE_ANNOTATION",
    "PRIORITY_CLASSES",
    "DEFAULT_PRIORITY",
]
