"""Scheduler flight recorder: structured decision traces for "why is my
gang Pending?".

kube-scheduler answers that question with per-plugin filter messages
flattened into one FailedScheduling Event string. That string is the
summary; the *evidence* — which nodes were considered, why each was
rejected, what quota said, whether preemption was attempted and who the
victim was — is normally gone the moment the cycle ends. The flight
recorder keeps it: every scheduling cycle appends one :class:`Decision`
to a bounded ring, served as JSON at ``GET /debug/scheduler``
(``?gang=ns/name`` filter, ``?limit=``) on every app that mounts
``runtime/obs.py``, and mirrored into
``scheduler_decision_total{outcome,reason}`` so dashboards see the same
taxonomy the debug surface explains.

Node verdict reasons come from :meth:`ChipLedger.explain` and are
machine-readable: ``feasible``, ``selector_mismatch``,
``insufficient_chips``, ``reserved_by_other_gang``.
"""

from __future__ import annotations

import threading
import time
from collections import Counter, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..runtime.metrics import METRICS
from ..runtime.obs import register_debug_source
from ..web.http import HttpError, Request

SCHED = METRICS.namespace("scheduler")

#: default ring size — at the scheduler's backoff cap (5 s) this covers
#: tens of minutes of a stuck gang's attempts, plus surrounding traffic
DEFAULT_CAPACITY = 512


@dataclass
class Decision:
    """One scheduling cycle's verdict, fully self-describing."""

    gang: str  # "ns/name"
    outcome: str  # SchedulerReconciler outcome: bound/unschedulable/...
    reason: str  # dominant machine-readable cause within the outcome
    message: str  # the human summary (what the Event says)
    attempt: int  # consecutive failures per the backoff queue
    backoff_seconds: float  # requeue delay chosen for this cycle
    wall_time: float  # unix seconds of the decision
    nodes: List[Dict[str, Any]] = field(default_factory=list)  # ledger.explain()
    quota: Optional[Dict[str, Any]] = None  # admission arithmetic when checked
    preemption: Optional[Dict[str, Any]] = None  # candidates considered, victim
    placement: Optional[List[str]] = None  # node per member when bound

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "gang": self.gang,
            "outcome": self.outcome,
            "reason": self.reason,
            "message": self.message,
            "attempt": self.attempt,
            "backoffSeconds": round(self.backoff_seconds, 4),
            "wallTime": self.wall_time,
            "nodes": self.nodes,
        }
        if self.quota is not None:
            out["quota"] = self.quota
        if self.preemption is not None:
            out["preemption"] = self.preemption
        if self.placement is not None:
            out["placement"] = self.placement
        return out


#: node verdicts kept verbatim per decision before aggregation kicks in
DEFAULT_VERDICT_TOP_K = 8


def truncate_node_verdicts(
    nodes: List[Dict[str, Any]], top_k: int = DEFAULT_VERDICT_TOP_K
) -> List[Dict[str, Any]]:
    """Cap a decision's per-node verdict list for storage.

    At thousands of nodes one unschedulable cycle would otherwise pin one
    dict per rejected node in the recorder ring (512 decisions × 10k nodes).
    The first ``top_k`` verdicts survive verbatim; the tail collapses into
    one summary row per reason — ``...and N more nodes: insufficient_chips``
    — so the debug surface still shows the full shape of the rejection.
    Callers must derive ``dominant_node_reason`` / the Event message from
    the full list *before* truncating; those stay exact.
    """
    if top_k < 0 or len(nodes) <= top_k:
        return list(nodes)
    kept = list(nodes[:top_k])
    tail = Counter(v.get("reason", "unknown") for v in nodes[top_k:])
    for reason, count in sorted(tail.items(), key=lambda kv: (-kv[1], kv[0])):
        kept.append({
            "node": f"...and {count} more nodes",
            "reason": reason,
            "truncated": count,
            "summary": f"...and {count} more nodes: {reason}",
        })
    return kept


def dominant_node_reason(nodes: List[Dict[str, Any]]) -> str:
    """The single most common rejection among non-feasible verdicts — what
    the ``reason`` label carries for an unschedulable decision."""
    tally = Counter(v["reason"] for v in nodes if v.get("reason") != "feasible")
    if not tally:
        return "no_nodes"
    return tally.most_common(1)[0][0]


def failed_scheduling_message(gang_size: int, nodes: List[Dict[str, Any]]) -> str:
    """kube-scheduler's classic summary line: ``0/N nodes are available:
    X insufficient chips, ...`` — built from the same verdicts the debug
    surface serves, so the Event and the trace can never disagree."""
    tally = Counter(v["reason"] for v in nodes if v.get("reason") != "feasible")
    if not nodes:
        return f"0/{gang_size} hosts bindable: no TPU nodes registered"
    parts = [
        f"{count} {reason.replace('_', ' ')}"
        for reason, count in sorted(tally.items(), key=lambda kv: (-kv[1], kv[0]))
    ]
    feasible = sum(1 for v in nodes if v.get("reason") == "feasible")
    if feasible:
        # individually feasible nodes exist, but not enough of them for
        # the whole gang at once — name that explicitly
        parts.append(f"{feasible} feasible but gang needs all-or-nothing placement")
    return f"0/{len(nodes)} nodes can host the gang: " + ", ".join(parts)


class FlightRecorder:
    """Bounded ring of scheduling decisions + the /debug/scheduler handler."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        register_debug_source("scheduler", self.debug_handler)

    def record(self, decision: Decision) -> None:
        if not decision.wall_time:
            decision.wall_time = time.time()
        with self._lock:
            self._ring.append(decision)
        SCHED.counter(
            "decision_total", outcome=decision.outcome, reason=decision.reason
        ).inc()

    def decisions(
        self, gang: Optional[str] = None, limit: int = 128
    ) -> List[Decision]:
        """Most recent last; ``gang`` filters on the "ns/name" string."""
        with self._lock:
            items = list(self._ring)
        if gang is not None:
            items = [d for d in items if d.gang == gang]
        return items[-max(0, limit):]

    def last_for(self, gang: str) -> Optional[Decision]:
        with self._lock:
            for d in reversed(self._ring):
                if d.gang == gang:
                    return d
        return None

    def debug_handler(self, req: Request) -> Dict[str, Any]:
        try:
            limit = int(req.query1("limit", "128"))
        except ValueError:
            raise HttpError(400, "limit must be an integer") from None
        gang = req.query1("gang") or None
        decisions = self.decisions(gang=gang, limit=limit)
        return {
            "scheduler": "kubeflow-tpu",
            "gang": gang,
            "count": len(decisions),
            "decisions": [d.to_dict() for d in decisions],
        }
