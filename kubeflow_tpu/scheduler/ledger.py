"""Incremental chips-in-use ledger + gang reservations.

The pre-split podlet recomputed node occupancy with an O(all-pods) scan
per scheduling attempt (builtin.py round-5 profile: a top cost under
churn). The ledger replaces that with event-driven accounting: informer
pod/node events adjust per-node usage in O(1), and every placement query
reads the cached totals under one lock.

Two kinds of state:

- **records** — one per bound, non-terminal pod: which node, how many
  chips, which gang, what priority. Fed by informer events and by the
  scheduler's own binds (the "assume" step — the informer echo of our
  write arrives later and lands on the identical record, so replays are
  harmless). Terminal phases and deletions free the chips; a MODIFIED
  without a nodeName never erases a record, because binds are never
  undone in this system and a stale pre-bind replay must not undercount.

- **reservations** — per-gang, TTL-bounded holds on capacity that is not
  yet (fully) bound: while a gang assembles, while its binds are written
  one by one, and across a preemption (victim evicted, preemptor not yet
  bound). Reservations are what make all-or-nothing placement composable
  with first-come-first-served arrivals: without them, two 2-host gangs
  interleave to one host each and deadlock.

Placement itself (``place_and_reserve``) runs under the same lock so the
feasibility check and the hold are atomic with respect to concurrent
informer updates.
"""

from __future__ import annotations

import heapq
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from ..api import meta as apimeta
from ..monitoring.goodput import TENANT_METER
from ..tpu.topology import RESOURCE_TPU, pod_tpu_chips
from .gang import TERMINAL_PHASES, gang_of, is_quarantined

PodKey = Tuple[Optional[str], str]
GangKey = Tuple[Optional[str], str]

# Stamped per node (see on_node_event); excluded from pool fingerprints
# because it is unique per node and would degenerate every pool to size 1.
HOSTNAME_LABEL = "kubernetes.io/hostname"


@dataclass
class _PodRecord:
    node: str
    chips: int
    namespace: Optional[str]
    gang: GangKey
    priority: int


def node_tpu_capacity(node: Dict[str, Any]) -> int:
    raw = ((node.get("status") or {}).get("capacity") or {}).get(RESOURCE_TPU, 0)
    try:
        return int(raw or 0)
    except (TypeError, ValueError):
        return 0


class ChipLedger:
    def __init__(self, indexed: bool = True) -> None:
        self._lock = threading.Lock()
        self._capacity: Dict[str, int] = {}
        self._labels: Dict[str, Dict[str, str]] = {}
        self._records: Dict[PodKey, _PodRecord] = {}
        self._used: Dict[str, int] = {}
        self._reserved: Dict[GangKey, Tuple[float, Dict[str, int]]] = {}
        # Free-chip index: nodes grouped into pools by label fingerprint
        # (all labels except the per-node hostname), with per-pool heaps of
        # node ranks bucketed by base free chips (capacity - used). Queries
        # touch pools + buckets instead of every node; reservation and
        # assume_freed adjustments are overlaid per affected node. The index
        # is maintained unconditionally (O(1) amortized per event) —
        # ``indexed`` only selects the placement query path.
        self.indexed = indexed
        self._rank: Dict[str, int] = {}  # node -> position in _capacity order
        self._rank_node: Dict[int, str] = {}
        self._next_rank = 0
        self._fp: Dict[str, frozenset] = {}  # node -> pool fingerprint
        self._pools: Dict[frozenset, Dict[str, Any]] = {}
        self._base_free: Dict[str, int] = {}  # node -> capacity - used
        self._hn: Dict[str, Optional[str]] = {}  # node -> hostname label value
        self._by_hostname: Dict[str, Set[str]] = {}
        # Nodes cordoned by the straggler detector's quarantine annotation
        # (scheduler/gang.py QUARANTINE_ANNOTATION): excluded from placement
        # in BOTH the scan and indexed paths (decision parity holds), still
        # tracked for capacity/used so explain() can say why. Maintained
        # from node events; an annotation clear un-cordons on the next event.
        self._cordoned: Set[str] = set()

    # -- event feeds ---------------------------------------------------------

    def on_node_event(self, event_type: str, node: Dict[str, Any]) -> None:
        name = apimeta.name_of(node)
        with self._lock:
            if event_type == "DELETED":
                self._capacity.pop(name, None)
                self._labels.pop(name, None)
                self._cordoned.discard(name)
                self._index_drop(name)
            else:
                # cordon state first: _index_touch consults it to keep the
                # pool index free of quarantined nodes
                if is_quarantined(node):
                    self._cordoned.add(name)
                else:
                    self._cordoned.discard(name)
                if name not in self._capacity:
                    # mirrors dict insertion order: re-adding a deleted node
                    # appends it, re-setting an existing key keeps its slot
                    self._rank[name] = self._next_rank
                    self._rank_node[self._next_rank] = name
                    self._next_rank += 1
                self._capacity[name] = node_tpu_capacity(node)
                labels = dict(apimeta.labels_of(node))
                # kubelets stamp every node with its hostname; synthesize it so
                # by-name nodeSelector pinning works against fixture nodes too
                labels.setdefault(HOSTNAME_LABEL, name)
                self._labels[name] = labels
                self._index_touch(name)

    def on_pod_event(self, event_type: str, pod: Dict[str, Any]) -> None:
        key = (apimeta.namespace_of(pod), apimeta.name_of(pod))
        phase = (pod.get("status") or {}).get("phase")
        node = (pod.get("spec") or {}).get("nodeName")
        with self._lock:
            if event_type == "DELETED" or phase in TERMINAL_PHASES:
                self._drop(key)
            elif node:
                g = gang_of(pod)
                self._put(key, _PodRecord(node, pod_tpu_chips(pod), key[0], g.key, g.priority))
            # else: live unbound pod — keep any existing record (see module doc)

    def record_bind(self, pod: Dict[str, Any]) -> None:
        """Assume a bind this scheduler just wrote, ahead of the informer echo."""
        self.on_pod_event("MODIFIED", pod)

    def sync_from(self, nodes: Iterable[Dict[str, Any]], pods: Iterable[Dict[str, Any]]) -> None:
        """Cacheless fallback (no Manager/informer): rebuild from a fresh list.
        Reservations are kept — they are scheduler state, not cluster state."""
        with self._lock:
            stale = list(self._records)
            self._capacity.clear()
            self._labels.clear()
            self._records.clear()
            self._used.clear()
            self._rank.clear()
            self._rank_node.clear()
            self._next_rank = 0
            self._fp.clear()
            self._pools.clear()
            self._base_free.clear()
            self._hn.clear()
            self._by_hostname.clear()
            self._cordoned.clear()
        # settle tenant meter intervals for everything we just forgot; pods
        # still bound re-open their interval when re-listed below
        for key in stale:
            TENANT_METER.on_unbind(key)
        for n in nodes:
            self.on_node_event("ADDED", n)
        for p in pods:
            self.on_pod_event("ADDED", p)

    # -- reads ---------------------------------------------------------------

    def has_nodes(self) -> bool:
        with self._lock:
            return bool(self._capacity)

    def used_on(self, node: str) -> int:
        with self._lock:
            return self._used.get(node, 0)

    def used_in_namespace(self, namespace: Optional[str]) -> int:
        with self._lock:
            return sum(r.chips for r in self._records.values() if r.namespace == namespace)

    def running_gangs(self) -> Dict[GangKey, Dict[str, Any]]:
        """Bound, non-terminal pods grouped by gang — the preemption candidates."""
        with self._lock:
            out: Dict[GangKey, Dict[str, Any]] = {}
            for key, r in self._records.items():
                g = out.setdefault(r.gang, {"priority": r.priority, "pods": [], "by_node": {}})
                g["priority"] = max(g["priority"], r.priority)
                g["pods"].append(key)
                g["by_node"][r.node] = g["by_node"].get(r.node, 0) + r.chips
            return out

    def free_chips(self, exclude_gang: Optional[GangKey] = None,
                   now: Optional[float] = None) -> Dict[str, int]:
        now = time.monotonic() if now is None else now
        with self._lock:
            return self._free_locked(exclude_gang, now)

    def reservations(self) -> Dict[GangKey, Dict[str, int]]:
        now = time.monotonic()
        with self._lock:
            self._purge_expired(now)
            return {k: dict(v[1]) for k, v in self._reserved.items()}

    # -- placement + reservations -------------------------------------------

    def place_and_reserve(
        self,
        gang_key: GangKey,
        requirements: List[Tuple[int, Dict[str, str]]],
        ttl: Optional[float] = None,
        assume_freed: Optional[Dict[str, int]] = None,
        now: Optional[float] = None,
        use_index: Optional[bool] = None,
    ) -> Optional[List[str]]:
        """All-or-nothing placement for ``requirements`` = [(chips, nodeSelector)].

        Returns one node name per requirement, or None if the whole set
        cannot fit. Free capacity excludes every other gang's reservation
        but includes this gang's own (it is re-planning its own hold).
        With ``ttl`` set, a feasible plan atomically replaces the gang's
        reservation; ``ttl=None`` is a pure feasibility query.
        ``assume_freed`` adds hypothetical capacity (a preemption victim's
        chips) before planning. ``use_index`` overrides the constructor's
        ``indexed`` choice for this one query (both paths return identical
        placements — see tests/test_scale.py parity suite).
        """
        now = time.monotonic() if now is None else now
        use = self.indexed if use_index is None else use_index
        with self._lock:
            if use:
                delta = self._delta_locked(gang_key, assume_freed, now)
                placement = self._select_indexed(requirements, delta)
            else:
                free = self._free_locked(gang_key, now)
                for node, chips in (assume_freed or {}).items():
                    free[node] = free.get(node, 0) + chips
                placement = self._select_scan(requirements, free)
            if placement is None:
                return None
            if ttl is not None:
                hold: Dict[str, int] = {}
                for node, (chips, _sel) in zip(placement, requirements):
                    if chips:
                        hold[node] = hold.get(node, 0) + chips
                if hold:
                    self._reserved[gang_key] = (now + ttl, hold)
                else:
                    self._reserved.pop(gang_key, None)
            return placement

    def explain(
        self,
        gang_key: GangKey,
        requirements: List[Tuple[int, Dict[str, str]]],
        now: Optional[float] = None,
    ) -> List[Dict[str, Any]]:
        """Per-node feasibility verdict for the flight recorder: why each
        candidate node can or cannot host (part of) the gang. Reasons are
        machine-readable — ``feasible``, ``selector_mismatch``,
        ``insufficient_chips``, ``reserved_by_other_gang``, ``quarantined``
        — the scheduler analog of kube-scheduler's per-plugin filter
        failure messages.

        A node is judged against the *smallest* matching requirement: "can
        this node host ANY member" — per-member assignment is the placer's
        job, the verdict only explains infeasibility.
        """
        now = time.monotonic() if now is None else now
        with self._lock:
            free = self._free_locked(gang_key, now)
            raw_free = {n: cap - self._used.get(n, 0) for n, cap in self._capacity.items()}
            verdicts: List[Dict[str, Any]] = []
            for node in sorted(self._capacity):
                labels = self._labels.get(node, {})
                matching = [
                    chips
                    for chips, selector in requirements
                    if not any(labels.get(k) != v for k, v in (selector or {}).items())
                ]
                if node in self._cordoned:
                    # quarantine outranks every other verdict: the node may
                    # have free matching chips, the detector said never mind
                    reason = "quarantined"
                    need = min(matching or [c for c, _s in requirements] or [0])
                elif not matching:
                    reason = "selector_mismatch"
                    need = min((c for c, _s in requirements), default=0)
                else:
                    need = min(matching)
                    if free.get(node, 0) >= need:
                        reason = "feasible"
                    elif raw_free.get(node, 0) >= need:
                        # only reservations held by OTHER gangs separate
                        # raw free capacity from schedulable free capacity
                        reason = "reserved_by_other_gang"
                    else:
                        reason = "insufficient_chips"
                verdicts.append(
                    {
                        "node": node,
                        "reason": reason,
                        "free_chips": free.get(node, 0),
                        "capacity": self._capacity[node],
                        "needed": need,
                    }
                )
            return verdicts

    def reserve(self, gang_key: GangKey, by_node: Dict[str, int], ttl: float,
                now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._reserved[gang_key] = (now + ttl, dict(by_node))

    def release(self, gang_key: GangKey) -> None:
        with self._lock:
            self._reserved.pop(gang_key, None)

    # -- internals (lock held) -----------------------------------------------

    def _select_scan(
        self, requirements: List[Tuple[int, Dict[str, str]]], free: Dict[str, int]
    ) -> Optional[List[str]]:
        """Reference placement: full scan over every node per requirement.
        Kept as the ground truth the index is proven against, and as the
        full-scan arm of the CONTROLPLANE bench."""
        placement: List[str] = []
        for chips, selector in requirements:
            best: Optional[str] = None
            for node in self._capacity:
                if node in self._cordoned:
                    continue
                labels = self._labels.get(node, {})
                if any(labels.get(k) != v for k, v in (selector or {}).items()):
                    continue
                if chips:
                    if free.get(node, 0) < chips:
                        continue
                    # best-fit: pack slices tightly so whole nodes stay
                    # free for the next multi-chip gang
                    if best is None or free[node] < free[best]:
                        best = node
                elif best is None:
                    best = node
            if best is None:
                return None
            placement.append(best)
            if chips:
                free[best] -= chips
        return placement

    def _delta_locked(
        self,
        exclude_gang: Optional[GangKey],
        assume_freed: Optional[Dict[str, int]],
        now: float,
    ) -> Dict[str, int]:
        """Sparse free-chip adjustments vs the indexed base (capacity - used):
        other gangs' reservations subtract, assume_freed adds. Only the few
        nodes touched by holds appear here — the index covers the rest."""
        self._purge_expired(now)
        delta: Dict[str, int] = {}
        for gkey, (_deadline, by_node) in self._reserved.items():
            if gkey == exclude_gang:
                continue
            for node, chips in by_node.items():
                delta[node] = delta.get(node, 0) - chips
        for node, chips in (assume_freed or {}).items():
            delta[node] = delta.get(node, 0) + chips
        return delta

    def _select_indexed(
        self, requirements: List[Tuple[int, Dict[str, str]]], delta: Dict[str, int]
    ) -> Optional[List[str]]:
        """Index-backed placement, decision-identical to ``_select_scan``.

        The scan's best-fit comparison (strict ``<`` over ``_capacity``
        iteration order) picks the node minimizing (free, insertion rank);
        a zero-chip requirement picks the minimum rank outright. Both are
        answered from per-pool free-buckets, with delta-overlaid nodes
        (reservations / assume_freed / chips consumed by earlier
        requirements in this same query) rescored individually.
        """
        placement: List[str] = []
        for chips, selector in requirements:
            sel = selector or {}
            # (free-or-0, rank, node); free participates only when chips > 0
            best: Optional[Tuple[int, int, str]] = None
            hostname = sel.get(HOSTNAME_LABEL)
            if hostname is not None:
                # hostname is excluded from pool fingerprints (unique per
                # node) — answer from the reverse map instead of the pools
                for node in self._by_hostname.get(hostname, ()):
                    cand = self._node_candidate(node, chips, sel, delta)
                    if cand is not None and (best is None or cand[:2] < best[:2]):
                        best = cand
            else:
                for pool in self._pools.values():
                    plabels = pool["labels"]
                    if any(plabels.get(k) != v for k, v in sel.items()):
                        continue
                    cand = self._pool_best(pool, chips, delta)
                    if cand is not None and (best is None or cand[:2] < best[:2]):
                        best = cand
                # pool buckets answer from base free; delta-affected nodes
                # were skipped there and are rescored with adjusted free
                for node in delta:
                    cand = self._node_candidate(node, chips, sel, delta)
                    if cand is not None and (best is None or cand[:2] < best[:2]):
                        best = cand
            if best is None:
                return None
            node = best[2]
            placement.append(node)
            if chips:
                delta[node] = delta.get(node, 0) - chips
        return placement

    def _node_candidate(
        self, node: str, chips: int, sel: Dict[str, str], delta: Dict[str, int]
    ) -> Optional[Tuple[int, int, str]]:
        if node not in self._capacity:
            return None  # assume_freed may name nodes the ledger never saw
        if node in self._cordoned:
            return None
        labels = self._labels.get(node, {})
        if any(labels.get(k) != v for k, v in sel.items()):
            return None
        if chips:
            free = self._base_free[node] + delta.get(node, 0)
            if free < chips:
                return None
            return (free, self._rank[node], node)
        return (0, self._rank[node], node)

    def _pool_best(
        self, pool: Dict[str, Any], chips: int, delta: Dict[str, int]
    ) -> Optional[Tuple[int, int, str]]:
        best: Optional[Tuple[int, int, str]] = None
        for f in sorted(pool["buckets"]):
            if chips and f < chips:
                continue
            top = self._peek_bucket(pool, f, delta if chips else None)
            if top is None:
                continue
            rank, node = top
            if chips:
                # buckets ascend by free: the first feasible one IS the
                # best-fit minimum, and its heap top the tie-break winner
                return (f, rank, node)
            cand = (0, rank, node)
            if best is None or cand < best:
                best = cand
        return best

    def _peek_bucket(
        self, pool: Dict[str, Any], f: int, exclude: Optional[Dict[str, int]]
    ) -> Optional[Tuple[int, str]]:
        """Min valid rank in a (pool, free) bucket without consuming it.
        Stale entries (node moved pool / changed free / deleted) are popped
        for good — lazy deletion; excluded (delta-overlaid) nodes are popped
        and pushed back after the peek."""
        heap = pool["buckets"].get(f)
        if not heap:
            pool["buckets"].pop(f, None)
            return None
        fp = pool["fp"]
        stash: List[int] = []
        found: Optional[Tuple[int, str]] = None
        while heap:
            rank = heap[0]
            node = self._rank_node.get(rank)
            if node is None or self._fp.get(node) != fp or self._base_free.get(node) != f:
                heapq.heappop(heap)
                continue
            if exclude is not None and node in exclude:
                stash.append(heapq.heappop(heap))
                continue
            found = (rank, node)
            break
        for rank in stash:
            heapq.heappush(heap, rank)
        if not heap:
            pool["buckets"].pop(f, None)
        return found

    def _index_touch(self, name: str) -> None:
        cap = self._capacity.get(name)
        if cap is None:
            self._index_drop(name)
            return
        if name in self._cordoned:
            # keep the node out of the pool index entirely; _peek_bucket's
            # lazy deletion (fp mismatch) purges any stale heap entries. The
            # hostname map stays — _node_candidate rejects cordoned nodes.
            fp = self._fp.pop(name, None)
            if fp is not None:
                self._pool_remove(name, fp)
            self._base_free.pop(name, None)
            return
        labels = self._labels.get(name, {})
        hostname = labels.get(HOSTNAME_LABEL)
        old_hn = self._hn.get(name)
        if old_hn != hostname:
            if old_hn is not None:
                peers = self._by_hostname.get(old_hn)
                if peers is not None:
                    peers.discard(name)
                    if not peers:
                        del self._by_hostname[old_hn]
            if hostname is not None:
                self._by_hostname.setdefault(hostname, set()).add(name)
            self._hn[name] = hostname
        fp = frozenset(kv for kv in labels.items() if kv[0] != HOSTNAME_LABEL)
        old_fp = self._fp.get(name)
        if old_fp is not None and old_fp != fp:
            self._pool_remove(name, old_fp)
        self._fp[name] = fp
        pool = self._pools.get(fp)
        if pool is None:
            pool = {
                "fp": fp,
                "labels": dict(fp),
                "nodes": set(),
                "buckets": {},
            }
            self._pools[fp] = pool
        pool["nodes"].add(name)
        base_free = cap - self._used.get(name, 0)
        if self._base_free.get(name) != base_free or old_fp != fp:
            self._base_free[name] = base_free
            heapq.heappush(pool["buckets"].setdefault(base_free, []), self._rank[name])

    def _index_drop(self, name: str) -> None:
        fp = self._fp.pop(name, None)
        if fp is not None:
            self._pool_remove(name, fp)
        self._base_free.pop(name, None)
        hostname = self._hn.pop(name, None)
        if hostname is not None:
            peers = self._by_hostname.get(hostname)
            if peers is not None:
                peers.discard(name)
                if not peers:
                    del self._by_hostname[hostname]
        rank = self._rank.pop(name, None)
        if rank is not None:
            self._rank_node.pop(rank, None)

    def _pool_remove(self, name: str, fp: frozenset) -> None:
        pool = self._pools.get(fp)
        if pool is None:
            return
        pool["nodes"].discard(name)
        if not pool["nodes"]:
            del self._pools[fp]

    def _free_locked(self, exclude_gang: Optional[GangKey], now: float) -> Dict[str, int]:
        self._purge_expired(now)
        free = {n: cap - self._used.get(n, 0) for n, cap in self._capacity.items()}
        for gkey, (_deadline, by_node) in self._reserved.items():
            if gkey == exclude_gang:
                continue
            for node, chips in by_node.items():
                free[node] = free.get(node, 0) - chips
        return free

    def _purge_expired(self, now: float) -> None:
        expired = [k for k, (deadline, _hold) in self._reserved.items() if deadline <= now]
        for k in expired:
            del self._reserved[k]

    def _put(self, key: PodKey, rec: _PodRecord) -> None:
        old = self._records.get(key)
        if old is not None:
            self._adjust(old.node, -old.chips)
        self._records[key] = rec
        self._adjust(rec.node, rec.chips)
        # tenant chip-second accrual opens at bind; the meter is idempotent
        # for the informer echo of a bind this scheduler already assumed
        TENANT_METER.on_bind(key, rec.namespace, rec.chips)

    def _drop(self, key: PodKey) -> None:
        old = self._records.pop(key, None)
        if old is not None:
            self._adjust(old.node, -old.chips)
            TENANT_METER.on_unbind(key)

    def _adjust(self, node: str, delta: int) -> None:
        n = self._used.get(node, 0) + delta
        if n:
            self._used[node] = n
        else:
            self._used.pop(node, None)
        if node in self._capacity:
            self._index_touch(node)

    # -- test/debug ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "capacity": dict(self._capacity),
                "used": dict(self._used),
                "records": {k: vars(v).copy() for k, v in self._records.items()},
                "reserved": {k: dict(v[1]) for k, v in self._reserved.items()},
                "cordoned": sorted(self._cordoned),
            }
