"""Incremental chips-in-use ledger + gang reservations.

The pre-split podlet recomputed node occupancy with an O(all-pods) scan
per scheduling attempt (builtin.py round-5 profile: a top cost under
churn). The ledger replaces that with event-driven accounting: informer
pod/node events adjust per-node usage in O(1), and every placement query
reads the cached totals under one lock.

Two kinds of state:

- **records** — one per bound, non-terminal pod: which node, how many
  chips, which gang, what priority. Fed by informer events and by the
  scheduler's own binds (the "assume" step — the informer echo of our
  write arrives later and lands on the identical record, so replays are
  harmless). Terminal phases and deletions free the chips; a MODIFIED
  without a nodeName never erases a record, because binds are never
  undone in this system and a stale pre-bind replay must not undercount.

- **reservations** — per-gang, TTL-bounded holds on capacity that is not
  yet (fully) bound: while a gang assembles, while its binds are written
  one by one, and across a preemption (victim evicted, preemptor not yet
  bound). Reservations are what make all-or-nothing placement composable
  with first-come-first-served arrivals: without them, two 2-host gangs
  interleave to one host each and deadlock.

Placement itself (``place_and_reserve``) runs under the same lock so the
feasibility check and the hold are atomic with respect to concurrent
informer updates.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..api import meta as apimeta
from ..tpu.topology import RESOURCE_TPU, pod_tpu_chips
from .gang import TERMINAL_PHASES, gang_of

PodKey = Tuple[Optional[str], str]
GangKey = Tuple[Optional[str], str]


@dataclass
class _PodRecord:
    node: str
    chips: int
    namespace: Optional[str]
    gang: GangKey
    priority: int


def node_tpu_capacity(node: Dict[str, Any]) -> int:
    raw = ((node.get("status") or {}).get("capacity") or {}).get(RESOURCE_TPU, 0)
    try:
        return int(raw or 0)
    except (TypeError, ValueError):
        return 0


class ChipLedger:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._capacity: Dict[str, int] = {}
        self._labels: Dict[str, Dict[str, str]] = {}
        self._records: Dict[PodKey, _PodRecord] = {}
        self._used: Dict[str, int] = {}
        self._reserved: Dict[GangKey, Tuple[float, Dict[str, int]]] = {}

    # -- event feeds ---------------------------------------------------------

    def on_node_event(self, event_type: str, node: Dict[str, Any]) -> None:
        name = apimeta.name_of(node)
        with self._lock:
            if event_type == "DELETED":
                self._capacity.pop(name, None)
                self._labels.pop(name, None)
            else:
                self._capacity[name] = node_tpu_capacity(node)
                labels = dict(apimeta.labels_of(node))
                # kubelets stamp every node with its hostname; synthesize it so
                # by-name nodeSelector pinning works against fixture nodes too
                labels.setdefault("kubernetes.io/hostname", name)
                self._labels[name] = labels

    def on_pod_event(self, event_type: str, pod: Dict[str, Any]) -> None:
        key = (apimeta.namespace_of(pod), apimeta.name_of(pod))
        phase = (pod.get("status") or {}).get("phase")
        node = (pod.get("spec") or {}).get("nodeName")
        with self._lock:
            if event_type == "DELETED" or phase in TERMINAL_PHASES:
                self._drop(key)
            elif node:
                g = gang_of(pod)
                self._put(key, _PodRecord(node, pod_tpu_chips(pod), key[0], g.key, g.priority))
            # else: live unbound pod — keep any existing record (see module doc)

    def record_bind(self, pod: Dict[str, Any]) -> None:
        """Assume a bind this scheduler just wrote, ahead of the informer echo."""
        self.on_pod_event("MODIFIED", pod)

    def sync_from(self, nodes: Iterable[Dict[str, Any]], pods: Iterable[Dict[str, Any]]) -> None:
        """Cacheless fallback (no Manager/informer): rebuild from a fresh list.
        Reservations are kept — they are scheduler state, not cluster state."""
        with self._lock:
            self._capacity.clear()
            self._labels.clear()
            self._records.clear()
            self._used.clear()
        for n in nodes:
            self.on_node_event("ADDED", n)
        for p in pods:
            self.on_pod_event("ADDED", p)

    # -- reads ---------------------------------------------------------------

    def has_nodes(self) -> bool:
        with self._lock:
            return bool(self._capacity)

    def used_on(self, node: str) -> int:
        with self._lock:
            return self._used.get(node, 0)

    def used_in_namespace(self, namespace: Optional[str]) -> int:
        with self._lock:
            return sum(r.chips for r in self._records.values() if r.namespace == namespace)

    def running_gangs(self) -> Dict[GangKey, Dict[str, Any]]:
        """Bound, non-terminal pods grouped by gang — the preemption candidates."""
        with self._lock:
            out: Dict[GangKey, Dict[str, Any]] = {}
            for key, r in self._records.items():
                g = out.setdefault(r.gang, {"priority": r.priority, "pods": [], "by_node": {}})
                g["priority"] = max(g["priority"], r.priority)
                g["pods"].append(key)
                g["by_node"][r.node] = g["by_node"].get(r.node, 0) + r.chips
            return out

    def free_chips(self, exclude_gang: Optional[GangKey] = None,
                   now: Optional[float] = None) -> Dict[str, int]:
        now = time.monotonic() if now is None else now
        with self._lock:
            return self._free_locked(exclude_gang, now)

    def reservations(self) -> Dict[GangKey, Dict[str, int]]:
        now = time.monotonic()
        with self._lock:
            self._purge_expired(now)
            return {k: dict(v[1]) for k, v in self._reserved.items()}

    # -- placement + reservations -------------------------------------------

    def place_and_reserve(
        self,
        gang_key: GangKey,
        requirements: List[Tuple[int, Dict[str, str]]],
        ttl: Optional[float] = None,
        assume_freed: Optional[Dict[str, int]] = None,
        now: Optional[float] = None,
    ) -> Optional[List[str]]:
        """All-or-nothing placement for ``requirements`` = [(chips, nodeSelector)].

        Returns one node name per requirement, or None if the whole set
        cannot fit. Free capacity excludes every other gang's reservation
        but includes this gang's own (it is re-planning its own hold).
        With ``ttl`` set, a feasible plan atomically replaces the gang's
        reservation; ``ttl=None`` is a pure feasibility query.
        ``assume_freed`` adds hypothetical capacity (a preemption victim's
        chips) before planning.
        """
        now = time.monotonic() if now is None else now
        with self._lock:
            free = self._free_locked(gang_key, now)
            for node, chips in (assume_freed or {}).items():
                free[node] = free.get(node, 0) + chips
            placement: List[str] = []
            for chips, selector in requirements:
                best: Optional[str] = None
                for node in self._capacity:
                    labels = self._labels.get(node, {})
                    if any(labels.get(k) != v for k, v in (selector or {}).items()):
                        continue
                    if chips:
                        if free.get(node, 0) < chips:
                            continue
                        # best-fit: pack slices tightly so whole nodes stay
                        # free for the next multi-chip gang
                        if best is None or free[node] < free[best]:
                            best = node
                    elif best is None:
                        best = node
                if best is None:
                    return None
                placement.append(best)
                if chips:
                    free[best] -= chips
            if ttl is not None:
                hold: Dict[str, int] = {}
                for node, (chips, _sel) in zip(placement, requirements):
                    if chips:
                        hold[node] = hold.get(node, 0) + chips
                if hold:
                    self._reserved[gang_key] = (now + ttl, hold)
                else:
                    self._reserved.pop(gang_key, None)
            return placement

    def explain(
        self,
        gang_key: GangKey,
        requirements: List[Tuple[int, Dict[str, str]]],
        now: Optional[float] = None,
    ) -> List[Dict[str, Any]]:
        """Per-node feasibility verdict for the flight recorder: why each
        candidate node can or cannot host (part of) the gang. Reasons are
        machine-readable — ``feasible``, ``selector_mismatch``,
        ``insufficient_chips``, ``reserved_by_other_gang`` — the scheduler
        analog of kube-scheduler's per-plugin filter failure messages.

        A node is judged against the *smallest* matching requirement: "can
        this node host ANY member" — per-member assignment is the placer's
        job, the verdict only explains infeasibility.
        """
        now = time.monotonic() if now is None else now
        with self._lock:
            free = self._free_locked(gang_key, now)
            raw_free = {n: cap - self._used.get(n, 0) for n, cap in self._capacity.items()}
            verdicts: List[Dict[str, Any]] = []
            for node in sorted(self._capacity):
                labels = self._labels.get(node, {})
                matching = [
                    chips
                    for chips, selector in requirements
                    if not any(labels.get(k) != v for k, v in (selector or {}).items())
                ]
                if not matching:
                    reason = "selector_mismatch"
                    need = min((c for c, _s in requirements), default=0)
                else:
                    need = min(matching)
                    if free.get(node, 0) >= need:
                        reason = "feasible"
                    elif raw_free.get(node, 0) >= need:
                        # only reservations held by OTHER gangs separate
                        # raw free capacity from schedulable free capacity
                        reason = "reserved_by_other_gang"
                    else:
                        reason = "insufficient_chips"
                verdicts.append(
                    {
                        "node": node,
                        "reason": reason,
                        "free_chips": free.get(node, 0),
                        "capacity": self._capacity[node],
                        "needed": need,
                    }
                )
            return verdicts

    def reserve(self, gang_key: GangKey, by_node: Dict[str, int], ttl: float,
                now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._reserved[gang_key] = (now + ttl, dict(by_node))

    def release(self, gang_key: GangKey) -> None:
        with self._lock:
            self._reserved.pop(gang_key, None)

    # -- internals (lock held) -----------------------------------------------

    def _free_locked(self, exclude_gang: Optional[GangKey], now: float) -> Dict[str, int]:
        self._purge_expired(now)
        free = {n: cap - self._used.get(n, 0) for n, cap in self._capacity.items()}
        for gkey, (_deadline, by_node) in self._reserved.items():
            if gkey == exclude_gang:
                continue
            for node, chips in by_node.items():
                free[node] = free.get(node, 0) - chips
        return free

    def _purge_expired(self, now: float) -> None:
        expired = [k for k, (deadline, _hold) in self._reserved.items() if deadline <= now]
        for k in expired:
            del self._reserved[k]

    def _put(self, key: PodKey, rec: _PodRecord) -> None:
        old = self._records.get(key)
        if old is not None:
            self._adjust(old.node, -old.chips)
        self._records[key] = rec
        self._adjust(rec.node, rec.chips)

    def _drop(self, key: PodKey) -> None:
        old = self._records.pop(key, None)
        if old is not None:
            self._adjust(old.node, -old.chips)

    def _adjust(self, node: str, delta: int) -> None:
        n = self._used.get(node, 0) + delta
        if n:
            self._used[node] = n
        else:
            self._used.pop(node, None)

    # -- test/debug ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "capacity": dict(self._capacity),
                "used": dict(self._used),
                "records": {k: vars(v).copy() for k, v in self._records.items()},
                "reserved": {k: dict(v[1]) for k, v in self._reserved.items()},
            }
