"""Gang (pod-group) model: labels, sizes, priorities, and the predicate
deciding which pods the scheduler owns.

A TPU slice is useless until EVERY host of the slice is bound — a 2x4
v5e notebook is two pods that must land together or not at all. Slice
owners (the notebook StatefulSet, the StudyJob trial runner) stamp their
pods with a pod-group label and an expected-size annotation; the
scheduler places members all-or-nothing (kube-scheduler's coscheduling /
Volcano gang semantics). Pods without the label form an implicit gang of
one, so plain pods flow through the same path.

Quota constants live here (not in controllers/profile.py) because the
scheduler is the enforcement point: ProfileReconciler *writes* the
ResourceQuota, the scheduler *admits against it* at bind time.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from ..api import meta as apimeta
from ..tpu.topology import RESOURCE_TPU, pod_tpu_chips

#: Label naming the gang a pod belongs to (value: gang name, unique per ns).
POD_GROUP_LABEL = "scheduling.kubeflow.org/pod-group"
#: Annotation carrying the expected member count of the gang.
POD_GROUP_SIZE_ANNOTATION = "scheduling.kubeflow.org/pod-group-size"

#: Drain protocol annotations (docs/ELASTICITY.md). A workload opts into
#: graceful preemption by stamping DRAIN_GRACE on its pods; the scheduler
#: then signals eviction by writing DRAIN_DEADLINE (unix seconds) instead
#: of deleting immediately, and the workload acks with DRAIN_ACK (the step
#: it checkpointed) once its state is safe. Pods without DRAIN_GRACE keep
#: the original immediate-evict behavior.
DRAIN_GRACE_ANNOTATION = "scheduling.kubeflow.org/drain-grace-seconds"
DRAIN_DEADLINE_ANNOTATION = "scheduling.kubeflow.org/drain-deadline"
DRAIN_ACK_ANNOTATION = "scheduling.kubeflow.org/drain-acked"

#: Node quarantine annotation (docs/SCHEDULER.md). The straggler detector
#: stamps it on a node hosting a hung worker; the ChipLedger then excludes
#: the node from placement (flight-recorder verdict: ``quarantined``) until
#: an operator clears the annotation. Cordon, not drain: pods already bound
#: there are evicted through the normal drain protocol, new work never lands.
QUARANTINE_ANNOTATION = "scheduling.kubeflow.org/quarantined"

#: Name of the per-namespace ResourceQuota ProfileReconciler materializes.
QUOTA_NAME = "kf-resource-quota"
#: The hard-limit key for TPU chips inside that quota.
TPU_QUOTA_KEY = f"requests.{RESOURCE_TPU}"

#: priorityClassName → numeric priority. Notebooks outrank trials by
#: default: an interactive user waiting on a slice preempts batch HPO.
PRIORITY_CLASSES: Dict[str, int] = {
    "system": 1000,
    "notebook": 100,
    "default": 50,
    "trial": 10,
    "batch": 0,
}
DEFAULT_PRIORITY = PRIORITY_CLASSES["default"]

TERMINAL_PHASES = ("Succeeded", "Failed")


@dataclass(frozen=True)
class Gang:
    """One co-scheduling unit: which pods, how many expected, what rank."""

    namespace: Optional[str]
    name: str
    size: int
    priority: int
    labeled: bool  # explicit pod-group label vs implicit gang-of-one

    @property
    def key(self) -> Tuple[Optional[str], str]:
        return (self.namespace, self.name)


def priority_of(pod: Dict[str, Any]) -> int:
    spec = pod.get("spec") or {}
    explicit = spec.get("priority")
    if isinstance(explicit, int):
        return explicit
    return PRIORITY_CLASSES.get(spec.get("priorityClassName", ""), DEFAULT_PRIORITY)


def gang_of(pod: Dict[str, Any]) -> Gang:
    ns = apimeta.namespace_of(pod)
    group = apimeta.labels_of(pod).get(POD_GROUP_LABEL)
    if not group:
        # Implicit gang of one; "pod:" prefix keeps the key space disjoint
        # from label values (which cannot contain ":").
        return Gang(ns, f"pod:{apimeta.name_of(pod)}", 1, priority_of(pod), False)
    try:
        size = int(apimeta.annotations_of(pod).get(POD_GROUP_SIZE_ANNOTATION, "1"))
    except ValueError:
        size = 1
    return Gang(ns, group, max(size, 1), priority_of(pod), True)


def drain_grace_of(pod: Dict[str, Any]) -> float:
    """Seconds of drain grace this pod opted into (0 = evict immediately)."""
    raw = apimeta.annotations_of(pod).get(DRAIN_GRACE_ANNOTATION)
    if raw is None:
        return 0.0
    try:
        return max(0.0, float(raw))
    except (TypeError, ValueError):
        return 0.0


def is_quarantined(node: Dict[str, Any]) -> bool:
    """Is this node cordoned by the straggler detector? Any value other
    than empty/"false" counts — the annotation carries a JSON verdict."""
    raw = apimeta.annotations_of(node).get(QUARANTINE_ANNOTATION)
    return raw is not None and raw not in ("", "false")


def is_terminal(pod: Dict[str, Any]) -> bool:
    return (pod.get("status") or {}).get("phase") in TERMINAL_PHASES


def requires_scheduling(pod: Dict[str, Any], have_nodes: bool) -> bool:
    """Does this pod need a node before the kubelet may run it?

    Mirrors the capacity model the podlet enforced pre-split: with zero
    nodes in the store, podless test pods just run — but a pod requesting
    ``google.com/tpu`` chips must wait for a node with capacity, exactly
    like a GKE cluster with no TPU node pools.
    """
    if is_terminal(pod):
        return False
    if (pod.get("spec") or {}).get("nodeName"):
        return False
    return have_nodes or pod_tpu_chips(pod) > 0
