"""SchedulerReconciler: gang-aware pod→node binding.

This subsystem owns the single write of ``spec.nodeName`` in the whole
control plane (enforced by tests/test_lint.py); PodletReconciler is a
pure kubelet that runs whatever is bound. One scheduling cycle:

1. derive the pod's gang (slice-owning StatefulSet / trial / implicit
   gang-of-one) and list its members;
2. if the gang is still assembling, hold a capacity reservation for the
   FULL expected size so interleaved arrivals cannot strand it at a
   partial slice, and wait (reservation released on assembly timeout);
3. admit against the namespace ResourceQuota (chips already bound in the
   namespace + the gang's ask vs the Profile's hard TPU limit);
4. place all members all-or-nothing against the ledger's cached free
   capacity (selector match + best-fit chips), bind each with an
   optimistic-concurrency retry, and release the reservation;
5. infeasible → try preempting the lowest-priority running gang whose
   chips make the placement feasible (reserve first, THEN evict, so the
   victim's replacement pods cannot steal the freed chips back);
6. still stuck → mark Unschedulable and requeue with per-gang
   exponential backoff (replacing the old flat 0.25 s poll).

Metrics live under the ``scheduler_`` namespace; every cycle runs in a
``runtime.tracing`` span.
"""

from __future__ import annotations

import calendar
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..api import meta as apimeta
from ..apiserver.client import Client
from ..apiserver.store import Conflict, NotFound
from ..runtime.manager import Reconciler, Request, Result
from ..runtime.metrics import METRICS
from ..runtime.tracing import (
    BIND_TRACEPARENT_ANNOTATION,
    TRACEPARENT_ANNOTATION,
    TRACER,
    Span,
    format_traceparent,
)
from ..tpu.topology import chips_in_quota, pod_tpu_chips
from .flight import (
    Decision,
    FlightRecorder,
    dominant_node_reason,
    failed_scheduling_message,
    truncate_node_verdicts,
)
from .gang import (
    DRAIN_ACK_ANNOTATION,
    DRAIN_DEADLINE_ANNOTATION,
    POD_GROUP_LABEL,
    QUOTA_NAME,
    TPU_QUOTA_KEY,
    Gang,
    drain_grace_of,
    gang_of,
    is_terminal,
    requires_scheduling,
)
from .ledger import ChipLedger, GangKey

SCHED = METRICS.namespace("scheduler")

#: Event source.component for everything this scheduler writes
COMPONENT = "tpu-scheduler"


class BackoffQueue:
    """Per-gang exponential scheduling backoff, capped.

    The pre-split podlet requeued unschedulable pods at a flat 0.25 s —
    a 4 Hz poll per stuck pod forever. Here each consecutive failure
    doubles the delay up to ``cap``; any success (or the gang vanishing)
    forgets the entry so the next contention starts fast again.
    """

    def __init__(self, base: float = 0.05, cap: float = 5.0) -> None:
        self.base = base
        self.cap = cap
        self._fails: Dict[Any, int] = {}
        self._lock = threading.Lock()

    def next_delay(self, key: Any) -> float:
        with self._lock:
            n = self._fails.get(key, 0)
            self._fails[key] = n + 1
        return min(self.base * (2 ** n), self.cap)

    def forget(self, key: Any) -> None:
        with self._lock:
            self._fails.pop(key, None)

    def failures(self, key: Any) -> int:
        with self._lock:
            return self._fails.get(key, 0)

    def __len__(self) -> int:
        with self._lock:
            return len(self._fails)


class SchedulerReconciler(Reconciler):
    FOR = ("v1", "Pod")

    def __init__(
        self,
        assembly_timeout: float = 30.0,
        reservation_ttl: float = 10.0,
        backoff_base: float = 0.05,
        backoff_cap: float = 5.0,
        indexed_ledger: bool = True,
        verdict_top_k: int = 8,
        cycles_window_s: float = 30.0,
    ) -> None:
        self.ledger = ChipLedger(indexed=indexed_ledger)
        self.backoff = BackoffQueue(backoff_base, backoff_cap)
        # every cycle's verdict, served at GET /debug/scheduler (flight.py)
        self.flight = FlightRecorder()
        self.assembly_timeout = assembly_timeout
        self.reservation_ttl = reservation_ttl
        #: per-decision node-verdict cap; beyond it, verdicts aggregate into
        #: one summary row per reason (flight.truncate_node_verdicts)
        self.verdict_top_k = verdict_top_k
        self.cycles_window_s = cycles_window_s
        self._wired = False
        self._lock = threading.Lock()
        #: monotonic completion times of recent scheduling cycles, feeding
        #: the scheduler_cycles_per_sec gauge at scrape time
        self._cycle_times: "deque[float]" = deque(maxlen=65536)
        METRICS.register_collector("scheduler_cycle_rate", self._collect_cycle_rate)
        #: gang → a member pod to requeue when a node appears
        self._pending: Dict[GangKey, Tuple[Optional[str], str]] = {}
        #: gang → monotonic time of its first scheduling attempt
        self._first_attempt: Dict[GangKey, float] = {}
        #: pod key → gang key, for cleanup when a pod vanishes
        self._gang_of_pod: Dict[Tuple[Optional[str], str], GangKey] = {}
        #: victim gang → in-flight drain (docs/ELASTICITY.md): who asked,
        #: the grace deadline, and the pods/chips the eviction will free
        self._draining: Dict[GangKey, Dict[str, Any]] = {}
        #: gang → its lifecycle root span: opened at gang submit (first
        #: scheduling attempt), parented to the submitting client's trace
        #: via the creation-traceparent annotation, closed by _gang_done /
        #: _pod_gone; every cycle/quota/preempt/bind span hangs under it
        self._gang_spans: Dict[GangKey, Span] = {}

    def watches(self):
        def wake_pending(_node: Dict[str, Any]) -> List[Request]:
            # New/changed capacity: re-kick one representative per pending
            # gang instead of waiting out its backoff.
            with self._lock:
                return [Request(ns, name) for (ns, name) in self._pending.values()]

        return [(("v1", "Node"), wake_pending)]

    # -- ledger wiring -------------------------------------------------------

    def _ensure_wired(self, client: Client) -> None:
        if self._wired:
            return
        if self.cache is None:
            # Unit-test mode: no informers; sync_from runs per cycle instead.
            self._wired = True
            return
        node_inf = self.cache.informer_for("v1", "Node")
        pod_inf = self.cache.informer_for("v1", "Pod")
        node_inf.add_event_handler(self.ledger.on_node_event)
        pod_inf.add_event_handler(self.ledger.on_pod_event)
        node_inf.wait_synced()
        pod_inf.wait_synced()
        # Handlers only see future events; backfill the synced mirror. A
        # double-apply from the overlap window is harmless — records are
        # keyed by pod identity and writes are idempotent.
        for node in node_inf.list():
            self.ledger.on_node_event("ADDED", node)
        for pod in pod_inf.list():
            self.ledger.on_pod_event("ADDED", pod)
        self._wired = True

    # -- reconcile -----------------------------------------------------------

    def reconcile(self, client: Client, req: Request) -> Result:
        self._ensure_wired(client)
        if self.cache is None:
            self.ledger.sync_from(client.list("v1", "Node"), client.list("v1", "Pod"))
        pod = client.get_opt("v1", "Pod", req.name, req.namespace)
        if pod is None or is_terminal(pod):
            self._pod_gone((req.namespace, req.name))
            return Result()
        if (pod.get("spec") or {}).get("nodeName"):
            self._gang_done(gang_of(pod).key, bound=False)
            return Result()
        if not requires_scheduling(pod, self.ledger.has_nodes()):
            return Result()

        gang = gang_of(pod)
        key = gang.key
        with self._lock:
            self._gang_of_pod[(req.namespace, req.name)] = key
            self._first_attempt.setdefault(key, time.monotonic())
            root = self._gang_spans.get(key)
        if root is None:
            # Gang submit: open the lifecycle root. Parent preference is the
            # pod's creation traceparent (the client call that submitted the
            # gang), falling back to the current reconcile span — either way
            # the whole gang journey shares one trace id.
            root = TRACER.start_span(
                "gang.lifecycle",
                traceparent=apimeta.annotations_of(pod).get(
                    TRACEPARENT_ANNOTATION),
                gang=f"{key[0]}/{key[1]}", size=gang.size)
            with self._lock:
                root = self._gang_spans.setdefault(key, root)
        with TRACER.span(
            "schedule", parent=root, controller=type(self).__name__,
            gang=f"{key[0]}/{key[1]}"
        ) as span:
            outcome, delay = self._schedule_gang(client, gang, pod, span)
            span.set("outcome", outcome)
        with self._lock:
            self._cycle_times.append(time.monotonic())
        SCHED.counter("attempts_total", result=outcome).inc()
        with self._lock:
            SCHED.gauge("pending_gangs").set(len(self._pending))
        return Result(requeue_after=delay) if delay else Result()

    def _schedule_gang(
        self, client: Client, gang: Gang, pod: Dict[str, Any], span
    ) -> Tuple[str, float]:
        key = gang.key
        members = self._members(client, gang, pod)
        unbound = [
            p for p in members
            if not (p.get("spec") or {}).get("nodeName") and not is_terminal(p)
        ]
        span.set("members", len(members))
        span.set("unbound", len(unbound))
        if not unbound:
            self._gang_done(key, bound=False)
            return "noop", 0.0

        if len(members) < gang.size:
            return self._await_assembly(client, gang, pod, span)

        # Quota admission: chips already bound in the namespace plus this
        # gang's ask must fit the Profile's hard TPU limit.
        needed = sum(pod_tpu_chips(p) for p in unbound)
        if needed:
            with TRACER.span("schedule.quota", namespace=str(gang.namespace),
                             chips=needed) as qspan:
                hard = self._quota_hard(client, gang.namespace)
                denied = False
                if hard is not None:
                    bound_ns = self.ledger.used_in_namespace(gang.namespace)
                    denied = bound_ns + needed > hard
                qspan.set("admitted", not denied)
            if denied:
                msg = (
                    f"namespace TPU quota exceeded: {bound_ns} chips bound + "
                    f"{needed} requested > {hard} allowed"
                )
                self._mark_unschedulable(client, unbound, msg)
                self._note_pending(key, unbound[0])
                delay = self.backoff.next_delay(key)
                self._record(
                    client, gang, unbound, "quota_denied", "quota", msg, delay,
                    quota={"boundChips": bound_ns, "requestedChips": needed,
                           "hardLimit": hard, "admitted": False},
                    failed_event=True,
                )
                return "quota_denied", delay

        requirements = [
            (pod_tpu_chips(p), (p.get("spec") or {}).get("nodeSelector") or {})
            for p in unbound
        ]
        placement = self.ledger.place_and_reserve(key, requirements, self.reservation_ttl)
        if placement is None:
            with TRACER.span("schedule.preempt", gang=f"{key[0]}/{key[1]}"):
                preemption = self._try_preempt(client, gang, requirements, span)
            if preemption.get("victim"):
                # Victim evicted; its chips free asynchronously while our
                # reservation (taken before the eviction) holds the claim.
                self._note_pending(key, unbound[0])
                self._record(
                    client, gang, unbound, "preempted", "preemption",
                    f"preempting lower-priority gang {preemption['victim']}",
                    self.backoff.base, preemption=preemption,
                )
                return "preempted", self.backoff.base
            if preemption.get("draining"):
                # Victim is checkpointing under its drain grace; our
                # reservation (refreshed each cycle) holds the claim until
                # it acks or the deadline passes, then we evict and bind.
                d = preemption["draining"]
                self._note_pending(key, unbound[0])
                delay = max(0.05, min(d["graceDeadline"] - time.time(), 1.0))
                self._record(
                    client, gang, unbound, "awaiting_drain", "draining",
                    f"victim gang {d['gang']} draining "
                    f"(grace deadline {d['graceDeadline']:.3f})",
                    delay, preemption=preemption,
                )
                return "awaiting_drain", delay
            self.ledger.release(key)
            # Re-judge each node AFTER releasing our own hold so the
            # verdicts describe the world the next attempt will see.
            nodes = self.ledger.explain(key, requirements)
            msg = failed_scheduling_message(gang.size, nodes)
            self._mark_unschedulable(client, unbound, msg)
            self._note_pending(key, unbound[0])
            delay = self.backoff.next_delay(key)
            self._record(
                client, gang, unbound, "unschedulable",
                dominant_node_reason(nodes), msg, delay,
                nodes=nodes,
                preemption=preemption if preemption["considered"] else None,
                failed_event=True,
            )
            return "unschedulable", delay

        return self._bind(client, key, unbound, placement, span, members)

    def _await_assembly(
        self, client: Client, gang: Gang, pod: Dict[str, Any], span
    ) -> Tuple[str, float]:
        """Gang not fully created yet: hold capacity for the FULL slice."""
        key = gang.key
        with self._lock:
            waited = time.monotonic() - self._first_attempt.get(key, time.monotonic())
        if waited > self.assembly_timeout:
            # Slice owner never produced the rest (stuck controller, scaled
            # down mid-flight): stop hoarding chips, keep retrying slowly.
            self.ledger.release(key)
            span.set("assembly_timeout", True)
            self._note_pending(key, pod)
            delay = self.backoff.next_delay(key)
            self._record(
                client, gang, [pod], "assembly_timeout", "assembly_timeout",
                f"gang incomplete after {waited:.1f}s (size {gang.size}); "
                "capacity reservation released", delay, failed_event=True,
            )
            return "assembly_timeout", delay
        template = (
            pod_tpu_chips(pod),
            (pod.get("spec") or {}).get("nodeSelector") or {},
        )
        self.ledger.place_and_reserve(key, [template] * gang.size, self.reservation_ttl)
        self._note_pending(key, pod)
        delay = min(self.reservation_ttl / 2, 1.0)
        self._record(
            client, gang, [pod], "waiting_gang", "assembling",
            f"waiting for gang members (size {gang.size}); chips reserved", delay,
        )
        # The missing members' ADDED events re-trigger scheduling; this
        # requeue only refreshes the reservation TTL / catches timeouts.
        return "waiting_gang", delay

    def _bind(
        self,
        client: Client,
        key: GangKey,
        unbound: List[Dict[str, Any]],
        placement: List[str],
        span,
        members: Optional[List[Dict[str, Any]]] = None,
    ) -> Tuple[str, float]:
        gang = gang_of(unbound[0])
        with TRACER.span("schedule.bind", gang=f"{key[0]}/{key[1]}",
                         pods=len(unbound)) as bspan:
            for target, node in zip(unbound, placement):
                ns, name = apimeta.namespace_of(target), apimeta.name_of(target)
                fresh = client.get_opt("v1", "Pod", name, ns)
                if fresh is None or (fresh.get("spec") or {}).get("nodeName"):
                    continue
                fresh["spec"]["nodeName"] = node
                # The bind traceparent rides the same write that sets
                # nodeName: podlet/engine/training spans started off the
                # bound pod join the gang's trace through this annotation.
                md = fresh.setdefault("metadata", {})
                ann = dict(md.get("annotations") or {})
                ann[BIND_TRACEPARENT_ANNOTATION] = format_traceparent(bspan)
                md["annotations"] = ann
                try:
                    bound = client.update(fresh)
                except Conflict:
                    # Raced a concurrent write; the reservation keeps the
                    # gang's chips held while we retry the remainder next
                    # cycle.
                    self._record(
                        client, gang, [], "bind_conflict", "conflict",
                        f"optimistic-concurrency conflict binding {ns}/{name}; retrying",
                        self.backoff.base,
                    )
                    return "bind_conflict", self.backoff.base
                self.ledger.record_bind(bound)
                client.emit_event(
                    bound, "Scheduled",
                    f"Successfully assigned {ns}/{name} to {node}",
                    component=COMPONENT,
                )
        self.ledger.release(key)
        with self._lock:
            root = self._gang_spans.get(key)
        self._observe_bind_latency(members or unbound, root)
        self._gang_done(key, bound=True)
        span.set("nodes", ",".join(sorted(set(placement))))
        self._record(
            client, gang, [], "bound", "scheduled",
            f"all {len(placement)} members bound", 0.0,
            placement=list(placement),
        )
        return "bound", 0.0

    def _try_preempt(
        self, client: Client, gang: Gang, requirements, span
    ) -> Dict[str, Any]:
        """Evict the lowest-priority running gang whose chips make this
        gang's placement feasible. Reserve first, then evict — and when the
        victim opted into drain grace (gang.DRAIN_GRACE_ANNOTATION), evict
        in two phases: signal a drain deadline, give the workload until ack
        or deadline to checkpoint, THEN delete (docs/ELASTICITY.md).

        Returns the flight-recorder preemption record: every candidate
        considered, the victim chosen with its identity + grace deadline
        (``victim`` is None when nothing helps), or ``draining`` while a
        victim's grace window is still open."""
        in_flight = self._check_draining(client, gang, requirements, span)
        if in_flight is not None:
            return in_flight
        candidates = sorted(
            (
                (info["priority"], sum(info["by_node"].values()), vkey, info)
                for vkey, info in self.ledger.running_gangs().items()
                if info["priority"] < gang.priority and vkey != gang.key
                and sum(info["by_node"].values()) > 0
            ),
        )
        considered: List[Dict[str, Any]] = []
        for prio, chips, vkey, info in candidates:
            considered.append(
                {"gang": f"{vkey[0]}/{vkey[1]}", "priority": prio, "chips": chips}
            )
            with self._lock:
                claimed = vkey in self._draining
            if claimed:
                # Already draining for some other preemptor; its chips are
                # spoken for, so evicting it twice would double-count them.
                considered[-1]["verdict"] = "already_draining"
                continue
            placement = self.ledger.place_and_reserve(
                gang.key, requirements, self.reservation_ttl, assume_freed=info["by_node"]
            )
            if placement is None:
                considered[-1]["verdict"] = "would_not_help"
                continue
            considered[-1]["verdict"] = "chosen"
            victim_id = f"{vkey[0]}/{vkey[1]}"
            grace = self._victim_grace(client, info["pods"])
            if grace <= 0:
                self._evict_pods(client, gang, info["pods"])
                SCHED.counter("preemptions_total").inc()
                span.set("preempted", victim_id)
                return {
                    "considered": considered,
                    "victim": victim_id,
                    "graceDeadline": None,
                }
            deadline = time.time() + grace
            self._request_drain(client, gang, victim_id, info["pods"], grace, deadline)
            with self._lock:
                self._draining[vkey] = {
                    "for": gang.key,
                    "victim": victim_id,
                    "deadline": deadline,
                    "pods": list(info["pods"]),
                    "by_node": dict(info["by_node"]),
                }
            span.set("draining", victim_id)
            return {
                "considered": considered,
                "victim": None,
                "draining": {
                    "gang": victim_id,
                    "preemptor": f"{gang.namespace}/{gang.name}",
                    "graceDeadline": deadline,
                },
            }
        return {"considered": considered, "victim": None}

    def _check_draining(
        self, client: Client, gang: Gang, requirements, span
    ) -> Optional[Dict[str, Any]]:
        """Phase 2 of the drain protocol: if this gang already signalled a
        victim, either finish the eviction (all live pods acked, pods gone,
        or deadline passed) or keep waiting with the reservation alive."""
        with self._lock:
            item = next(
                ((vk, d) for vk, d in self._draining.items() if d["for"] == gang.key),
                None,
            )
        if item is None:
            return None
        vkey, drain = item
        if drain.get("evicted"):
            # Eviction already issued, but the informer-fed ledger may not
            # have echoed the deletes yet — the victim's chips still look
            # used. Hold the claim (refreshing the reservation) until they
            # actually free, so this gang neither re-preempts the ghost nor
            # loses the capacity to a third gang in the lag window.
            info = self.ledger.running_gangs().get(vkey)
            if info is None or sum(info["by_node"].values()) == 0:
                with self._lock:
                    self._draining.pop(vkey, None)
                return None
            self.ledger.place_and_reserve(
                gang.key, requirements, self.reservation_ttl,
                assume_freed=drain["by_node"],
            )
            return {
                "considered": [],
                "victim": None,
                "draining": {
                    "gang": drain["victim"],
                    "preemptor": f"{gang.namespace}/{gang.name}",
                    "graceDeadline": drain["deadline"],
                    "freeing": True,
                },
            }
        # Refresh our claim on the victim's chips each cycle so the TTL
        # cannot lapse while the victim checkpoints.
        self.ledger.place_and_reserve(
            gang.key, requirements, self.reservation_ttl, assume_freed=drain["by_node"]
        )
        acked, live = self._drain_progress(client, drain["pods"])
        if live == 0 or acked == live or time.time() >= drain["deadline"]:
            self._evict_pods(client, gang, drain["pods"])
            with self._lock:
                # Keep the entry in an "evicted" state (see above) until the
                # ledger stops counting the victim's chips.
                drain["evicted"] = True
            SCHED.counter("preemptions_total").inc()
            SCHED.counter(
                "drains_completed_total",
                outcome="acked" if live and acked == live else
                ("gone" if live == 0 else "deadline"),
            ).inc()
            span.set("preempted", drain["victim"])
            return {
                "considered": [],
                "victim": drain["victim"],
                "graceDeadline": drain["deadline"],
                "drainAckedPods": acked,
            }
        return {
            "considered": [],
            "victim": None,
            "draining": {
                "gang": drain["victim"],
                "preemptor": f"{gang.namespace}/{gang.name}",
                "graceDeadline": drain["deadline"],
                "ackedPods": acked,
                "livePods": live,
            },
        }

    def _victim_grace(self, client: Client, pods) -> float:
        grace = 0.0
        for vns, vname in pods:
            victim = client.get_opt("v1", "Pod", vname, vns)
            if victim is not None:
                grace = max(grace, drain_grace_of(victim))
        return grace

    def _request_drain(
        self, client: Client, gang: Gang, victim_id: str, pods, grace: float,
        deadline: float,
    ) -> None:
        """Phase 1: stamp the deadline on every live victim pod, tell the
        workload (TrainingPreempted Event), and flight-record the drain
        under the VICTIM's gang so its operator sees who preempted it."""
        for vns, vname in pods:
            victim = client.get_opt("v1", "Pod", vname, vns)
            if victim is None:
                continue
            try:
                client.patch(
                    "v1", "Pod", vname,
                    {"metadata": {"annotations": {
                        DRAIN_DEADLINE_ANNOTATION: f"{deadline:.3f}"}}},
                    vns,
                )
            except (Conflict, NotFound):
                continue
            client.emit_event(
                victim,
                "TrainingPreempted",
                f"drain requested by higher-priority gang "
                f"{gang.namespace}/{gang.name}: checkpoint within {grace:.1f}s "
                f"(deadline {deadline:.3f}) or be evicted",
                type_="Warning",
                component=COMPONENT,
            )
        SCHED.counter("drains_requested_total").inc()
        self.flight.record(
            Decision(
                gang=victim_id,
                outcome="drain_requested",
                reason="preemption",
                message=(
                    f"draining for higher-priority gang "
                    f"{gang.namespace}/{gang.name}; grace {grace:.1f}s"
                ),
                attempt=0,
                backoff_seconds=0.0,
                wall_time=time.time(),
                nodes=[],
                quota=None,
                preemption={
                    "victim": victim_id,
                    "preemptor": f"{gang.namespace}/{gang.name}",
                    "graceDeadline": deadline,
                },
                placement=None,
            )
        )

    def _drain_progress(self, client: Client, pods) -> Tuple[int, int]:
        """(acked, live) across the victim's pods; terminal/vanished pods
        count as neither (their chips free on their own)."""
        acked = live = 0
        for vns, vname in pods:
            victim = client.get_opt("v1", "Pod", vname, vns)
            if victim is None or is_terminal(victim):
                continue
            live += 1
            if apimeta.annotations_of(victim).get(DRAIN_ACK_ANNOTATION):
                acked += 1
        return acked, live

    def _evict_pods(self, client: Client, gang: Gang, pods) -> None:
        for vns, vname in pods:
            victim = client.get_opt("v1", "Pod", vname, vns)
            if victim is not None:
                client.emit_event(
                    victim,
                    "Preempted",
                    f"evicted by higher-priority gang {gang.namespace}/{gang.name}",
                    type_="Warning",
                    component=COMPONENT,
                )
            client.delete_opt("v1", "Pod", vname, vns)

    # -- helpers -------------------------------------------------------------

    def _record(
        self,
        client: Client,
        gang: Gang,
        unbound: List[Dict[str, Any]],
        outcome: str,
        reason: str,
        message: str,
        delay: float,
        nodes: Optional[List[Dict[str, Any]]] = None,
        quota: Optional[Dict[str, Any]] = None,
        preemption: Optional[Dict[str, Any]] = None,
        placement: Optional[List[str]] = None,
        failed_event: bool = False,
    ) -> None:
        """Flight-record this cycle's verdict; with ``failed_event``, also
        summarize it as ONE aggregated FailedScheduling Warning per unbound
        pod (the recorder bumps ``count`` on repeats, so a gang stuck for
        an hour carries one Event whose count is the attempt tally)."""
        key = gang.key
        self.flight.record(
            Decision(
                gang=f"{key[0]}/{key[1]}",
                outcome=outcome,
                reason=reason,
                message=message,
                attempt=self.backoff.failures(key),
                backoff_seconds=delay,
                wall_time=time.time(),
                # dominant_node_reason/failed_scheduling_message were computed
                # from the FULL verdict list by the caller; the stored copy is
                # capped so one unschedulable cycle on a 10k-node cluster
                # doesn't pin thousands of dicts in the recorder ring
                nodes=truncate_node_verdicts(nodes or [], self.verdict_top_k),
                quota=quota,
                preemption=preemption,
                placement=placement,
            )
        )
        if failed_event:
            for p in unbound:
                client.emit_event(
                    p, "FailedScheduling", message, type_="Warning",
                    component=COMPONENT,
                )

    def _members(self, client: Client, gang: Gang, pod: Dict[str, Any]) -> List[Dict[str, Any]]:
        if not gang.labeled:
            return [pod]
        selector = {POD_GROUP_LABEL: gang.name}
        if self.cache is not None:
            members = self.cache.list("v1", "Pod", gang.namespace, label_selector=selector)
        else:
            members = client.list("v1", "Pod", gang.namespace, label_selector=selector)
        # The informer mirror can lag the triggering pod's own creation.
        if not any(apimeta.name_of(m) == apimeta.name_of(pod) for m in members):
            members = list(members) + [pod]
        return sorted(members, key=apimeta.name_of)

    def _quota_hard(self, client: Client, namespace: Optional[str]) -> Optional[int]:
        quota = client.get_opt("v1", "ResourceQuota", QUOTA_NAME, namespace)
        if quota is None:
            return None
        hard = ((quota.get("spec") or {}).get("hard") or {}).get(TPU_QUOTA_KEY)
        if hard is None:
            return None
        return chips_in_quota(hard)

    def _mark_unschedulable(self, client: Client, pods: List[Dict[str, Any]], message: str) -> None:
        status = {
            "phase": "Pending",
            "conditions": [
                {
                    "type": "PodScheduled",
                    "status": "False",
                    "reason": "Unschedulable",
                    "message": message,
                }
            ],
        }
        for p in pods:
            fresh = client.get_opt("v1", "Pod", apimeta.name_of(p), apimeta.namespace_of(p))
            if fresh is None:
                continue
            fresh["status"] = apimeta.deepcopy(status)
            try:
                # Identical writes are no-ops in the store (no watch event),
                # so re-marking per backoff attempt causes no event storms.
                client.update_status(fresh)
            except (Conflict, NotFound):
                pass

    def _note_pending(self, key: GangKey, pod: Dict[str, Any]) -> None:
        with self._lock:
            self._pending[key] = (apimeta.namespace_of(pod), apimeta.name_of(pod))

    def _gang_done(self, key: GangKey, bound: bool) -> None:
        self.backoff.forget(key)
        self.ledger.release(key)
        self._cancel_drains_for(key)
        with self._lock:
            self._pending.pop(key, None)
            first = self._first_attempt.pop(key, None)
            root = self._gang_spans.pop(key, None)
        if root is not None and not root.end_ns:
            # end_ns set means the abandoned-span sweep beat us to it (an
            # hour-pending gang) — don't record the root twice
            root.set("gang.bound", bound)
            TRACER.end_span(root)
        if bound and first is not None:
            SCHED.histogram("time_to_bind_seconds").observe(time.monotonic() - first)

    #: bucket ladder for the end-to-end bind SLI; creationTimestamps have
    #: 1 s resolution, so the sub-second buckets catch same-second binds
    BIND_LATENCY_BUCKETS = (0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)

    def _observe_bind_latency(
        self, members: List[Dict[str, Any]], root: Optional[Span] = None
    ) -> None:
        """End-to-end bind SLI: earliest gang member creationTimestamp (the
        submit, stamped by the apiserver in wall time) → last pod bound
        (now). Unlike time_to_bind_seconds — first *attempt* to bind — this
        includes apiserver/informer/workqueue time before the scheduler ever
        saw the gang, which is exactly the control-plane latency the scale
        harness loads. The gang root span gets the same anchors as
        attributes (and the histogram an exemplar with its trace id) so the
        critical-path analyzer can reconstruct this exact observation from
        the assembled trace."""
        submitted: Optional[float] = None
        for p in members:
            stamp = (p.get("metadata") or {}).get("creationTimestamp")
            if not stamp:
                continue
            try:
                ts = calendar.timegm(time.strptime(stamp, "%Y-%m-%dT%H:%M:%SZ"))
            except ValueError:
                continue
            submitted = ts if submitted is None else min(submitted, ts)
        if submitted is None:
            return
        latency = max(0.0, time.time() - submitted)
        if root is not None:
            root.set("gang.submitted_unix", submitted)
            root.set("gang.bind_latency_s", round(latency, 6))
        SCHED.histogram(
            "bind_latency_seconds", buckets=self.BIND_LATENCY_BUCKETS
        ).observe(latency, trace_id=root.trace_id if root else None)

    def _collect_cycle_rate(self) -> None:
        """Scrape-time collector: scheduling cycles completed per second
        over the trailing window."""
        now = time.monotonic()
        cutoff = now - self.cycles_window_s
        with self._lock:
            while self._cycle_times and self._cycle_times[0] < cutoff:
                self._cycle_times.popleft()
            n = len(self._cycle_times)
        SCHED.gauge("cycles_per_sec").set(round(n / self.cycles_window_s, 6))

    def _pod_gone(self, pod_key: Tuple[Optional[str], str]) -> None:
        with self._lock:
            gkey = self._gang_of_pod.pop(pod_key, None)
            orphaned = gkey is not None and gkey not in self._gang_of_pod.values()
        if orphaned:
            self.backoff.forget(gkey)
            self.ledger.release(gkey)
            self._cancel_drains_for(gkey)
            with self._lock:
                self._pending.pop(gkey, None)
                self._first_attempt.pop(gkey, None)
                root = self._gang_spans.pop(gkey, None)
                SCHED.gauge("pending_gangs").set(len(self._pending))
            if root is not None and not root.end_ns:
                root.set("gang.bound", False)
                TRACER.end_span(root)

    def _cancel_drains_for(self, key: GangKey) -> None:
        """Preemptor bound or vanished: forget drains it requested so the
        victim is no longer claimed (the stale deadline annotation is
        harmless — without a deletion the workload just keeps training)."""
        with self._lock:
            stale = [vk for vk, d in self._draining.items() if d["for"] == key]
            for vk in stale:
                self._draining.pop(vk, None)


def main() -> None:  # python -m kubeflow_tpu.scheduler.core
    from ..runtime.bootstrap import run_role

    run_role("scheduler", SchedulerReconciler())


if __name__ == "__main__":
    main()
