"""Tensorboards web app backend: CRUD over Tensorboard CRs.

Re-implements the reference TWA backend (crud-web-apps/tensorboards/backend/
app/routes/: post.py:14-38 creates the CR from {name, logspath}; get/delete
are generic CR CRUD via the shared crud_backend).
"""

from __future__ import annotations

from typing import Optional

from ..api import meta as apimeta
from ..apiserver.client import Client
from ..apiserver.store import Conflict
from ..controllers.tensorboard import TB_API, parse_logspath
from ..web.openapi import annotate, install_apidocs
from ..web.resources import install_cluster_api
from ..web.static import install_spa, load_ui
from ..web.auth import AuthConfig, Authorizer, install_auth, issue_csrf_cookie
from ..web.http import App, HttpError, JsonResponse, Request


def make_tensorboards_app(client: Client, auth: Optional[AuthConfig] = None) -> App:
    cfg = auth or AuthConfig()
    authorizer = Authorizer(client, cfg)
    app = App("tensorboards-web-app")
    install_auth(app, authorizer)

    @app.route("/api/config")
    def config(req: Request):
        resp = JsonResponse({"config": {}})
        issue_csrf_cookie(resp, cfg)
        return resp

    @app.route("/api/namespaces/<ns>/tensorboards")
    @annotate(response="TensorboardList")
    def list_tbs(req: Request):
        authorizer.ensure(req.context["user"], "list", req.params["ns"])
        out = []
        for tb in client.list(TB_API, "Tensorboard", req.params["ns"]):
            status = tb.get("status") or {}
            out.append(
                {
                    "name": apimeta.name_of(tb),
                    "namespace": req.params["ns"],
                    "logspath": tb.get("spec", {}).get("logspath", ""),
                    "ready": status.get("readyReplicas", 0) > 0,
                    "conditions": status.get("conditions", []),
                }
            )
        return {"tensorboards": out}

    @app.route("/api/namespaces/<ns>/tensorboards", methods=("POST",))
    @annotate(response="Status")
    def create_tb(req: Request):
        ns = req.params["ns"]
        authorizer.ensure(req.context["user"], "create", ns)
        body = req.json or {}
        name, logspath = body.get("name"), body.get("logspath", "")
        if not name:
            raise HttpError(400, "name required")
        try:
            parse_logspath(logspath)
        except ValueError as e:
            raise HttpError(400, str(e)) from None
        try:
            client.create(apimeta.new_object(TB_API, "Tensorboard", name, ns, spec={"logspath": logspath}))
        except Conflict:
            raise HttpError(409, f"tensorboard {name!r} exists") from None
        return {"status": "created"}

    @app.route("/api/namespaces/<ns>/tensorboards/<name>", methods=("DELETE",))
    @annotate(response="Status")
    def delete_tb(req: Request):
        authorizer.ensure(req.context["user"], "delete", req.params["ns"])
        client.delete(TB_API, "Tensorboard", req.params["name"], req.params["ns"])
        return {"status": "deleted"}

    install_cluster_api(app, client, authorizer)
    install_apidocs(app)
    install_spa(app, load_ui("tensorboards.html"), cfg)
    return app

def main() -> None:  # python -m kubeflow_tpu.services.tensorboards
    from ..runtime.bootstrap import run_webapp

    run_webapp("tensorboards-web-app", lambda client, auth: make_tensorboards_app(client, auth))


if __name__ == "__main__":
    main()
