"""Jupyter web app backend: the notebook spawner REST API.

Re-implements the reference JWA backend (crud-web-apps/jupyter/backend/):

- the spawn path (apps/default/routes/post.py:11-74): form → workspace/data
  PVCs → Notebook CR, honoring admin readOnly config,
- GET routes (apps/common/routes/get.py): /api/config, per-namespace
  notebooks/pvcs/poddefaults, and accelerator discovery — the reference's
  ``/api/gpus`` intersects config vendor limit-keys with node capacity
  (get.py:50-71); here ``/api/tpus`` reports TPU generations/topologies
  actually present on nodes by the GKE labels,
- start/stop (apps/common/routes/patch.py): toggle the
  ``kubeflow-resource-stopped`` annotation,
- status derivation from CR status/events (apps/common/status.py),
- per-call authorization + CSRF (crud_backend semantics).

TPU specifics: the form's ``tpus`` selection lands in ``spec.tpu`` of the
Notebook CR — sizing the StatefulSet to the slice's host count — and a
``configurations`` label selects TPU PodDefaults for env/limits injection.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..api import meta as apimeta
from ..apiserver.client import Client
from ..apiserver.store import Conflict
from ..controllers.notebook import STOP_ANNOTATION
from ..tpu.topology import (
    ACCELERATORS,
    NODE_LABEL_ACCELERATOR,
    NODE_LABEL_TOPOLOGY,
    RESOURCE_TPU,
)
from ..web.openapi import annotate, install_apidocs
from ..web.resources import install_cluster_api
from ..web.static import install_spa, load_ui
from ..web.auth import AuthConfig, Authorizer, install_auth, issue_csrf_cookie
from ..web.http import App, HttpError, JsonResponse, Request
from .spawner_config import SpawnerConfig

NOTEBOOK_API = "kubeflow.org/v1beta1"


def notebook_status(nb: Dict[str, Any], events: List[Dict[str, Any]]) -> Dict[str, str]:
    """UI status from CR state (apps/common/status.py:1-99)."""
    if STOP_ANNOTATION in apimeta.annotations_of(nb):
        return {"phase": "stopped", "message": "Notebook is stopped"}
    status = nb.get("status") or {}
    conditions = status.get("conditions") or []
    for c in conditions:
        if c.get("type") == "Failed" and c.get("status") == "True":
            return {"phase": "error", "message": c.get("message", "failed")}
    tpu = status.get("tpu")
    want = tpu["numHosts"] if tpu else 1
    ready = status.get("readyReplicas", 0)
    if ready >= want:
        return {"phase": "ready", "message": "Running"}
    warnings = [e for e in events if e.get("type") == "Warning"]
    if warnings:
        return {"phase": "warning", "message": warnings[-1].get("message", "")}
    return {"phase": "waiting", "message": f"{ready}/{want} hosts ready"}


def make_jupyter_app(
    client: Client,
    auth: Optional[AuthConfig] = None,
    spawner: Optional[SpawnerConfig] = None,
    cache: Optional["InformerCache"] = None,
) -> App:
    from ..runtime.informer import InformerCache

    cfg = auth or AuthConfig()
    spawner = spawner or SpawnerConfig()
    authorizer = Authorizer(client, cfg)
    app = App("jupyter-web-app")
    install_auth(app, authorizer)
    # List endpoints read through shared informers (KFAM informer-lister
    # pattern, api_default.go:71-75) — a populated namespace must not cost
    # an apiserver table scan per UI poll.
    cache = cache or InformerCache(client)

    def user(req: Request) -> str:
        return req.context["user"]

    # -- config + discovery --------------------------------------------------
    @app.route("/api/config")
    def get_config(req: Request):
        resp = JsonResponse({"config": spawner.config})
        issue_csrf_cookie(resp, cfg)
        return resp

    @app.route("/api/tpus")
    @annotate(response="TpuList")
    def get_tpus(req: Request):
        """TPU discovery: generations/topologies present in node capacity
        (the reference's vendor discovery reshaped for slices)."""
        found: Dict[str, Dict[str, Any]] = {}
        for node in cache.list("v1", "Node"):
            labels = apimeta.labels_of(node)
            gke_name = labels.get(NODE_LABEL_ACCELERATOR)
            capacity = int((node.get("status", {}).get("capacity") or {}).get(RESOURCE_TPU, 0))
            if not gke_name or capacity <= 0:
                continue
            gen = next((g for g, a in ACCELERATORS.items() if a.gke_name == gke_name), None)
            if gen is None:
                continue
            entry = found.setdefault(gen, {"generation": gen, "topologies": set(), "chipsPerNode": capacity})
            topo = labels.get(NODE_LABEL_TOPOLOGY)
            if topo:
                entry["topologies"].add(topo)
        return {
            "tpus": [
                {**e, "topologies": sorted(e["topologies"])} for e in found.values()
            ]
        }

    # -- listings ------------------------------------------------------------
    @app.route("/api/namespaces/<ns>/notebooks")
    @annotate(response="NotebookList")
    def list_notebooks(req: Request):
        authorizer.ensure(user(req), "list", req.params["ns"])
        ns = req.params["ns"]
        out = []
        all_events = cache.list("v1", "Event", ns)
        for nb in cache.list(NOTEBOOK_API, "Notebook", ns):
            name = apimeta.name_of(nb)
            events = [
                e for e in all_events
                if e.get("involvedObject", {}).get("name") == name
            ]
            tpu = nb.get("spec", {}).get("tpu")
            out.append(
                {
                    "name": name,
                    "namespace": ns,
                    "image": _first_container(nb).get("image", ""),
                    "tpu": tpu,
                    "status": notebook_status(nb, events),
                    "serverType": "jupyter",
                }
            )
        return {"notebooks": out}

    @app.route("/api/namespaces/<ns>/notebooks/<name>")
    def get_notebook(req: Request):
        authorizer.ensure(user(req), "get", req.params["ns"])
        nb = client.get_opt(NOTEBOOK_API, "Notebook", req.params["name"], req.params["ns"])
        if nb is None:
            raise HttpError(404, "notebook not found")
        return {"notebook": nb}

    @app.route("/api/namespaces/<ns>/pvcs")
    @annotate(response="PvcList")
    def list_pvcs(req: Request):
        authorizer.ensure(user(req), "list", req.params["ns"])
        return {"pvcs": cache.list("v1", "PersistentVolumeClaim", req.params["ns"])}

    @app.route("/api/namespaces/<ns>/poddefaults")
    @annotate(response="PodDefaultList")
    def list_poddefaults(req: Request):
        authorizer.ensure(user(req), "list", req.params["ns"])
        pds = cache.list("kubeflow.org/v1alpha1", "PodDefault", req.params["ns"])
        return {
            "poddefaults": [
                {
                    "label": next(iter((pd["spec"].get("selector") or {}).get("matchLabels") or {}), ""),
                    "desc": pd["spec"].get("desc", apimeta.name_of(pd)),
                    "name": apimeta.name_of(pd),
                }
                for pd in pds
            ]
        }

    # -- spawn ---------------------------------------------------------------
    @app.route("/api/namespaces/<ns>/notebooks", methods=("POST",))
    @annotate(response="Status", request="SpawnForm")
    def create_notebook(req: Request):
        ns = req.params["ns"]
        authorizer.ensure(user(req), "create", ns)
        form = req.json or {}
        name = form.get("name")
        if not name:
            raise HttpError(400, "notebook name required")
        image = spawner.form_value(form, "image")
        if isinstance(image, dict):
            image = image.get("value", "")
        cpu = str(spawner.form_value(form, "cpu"))
        memory = str(spawner.form_value(form, "memory"))
        tpu = spawner.tpu_of_form(form)

        # Resolve scheduling groups BEFORE any PVC creation: a bad key must
        # 400 without leaving orphaned volumes behind.
        affinity = tolerations = None
        affinity_key = spawner.form_value(form, "affinityConfig")
        if affinity_key:
            opt = next((o for o in (spawner.defaults.get("affinityConfig", {}).get("options") or [])
                        if o.get("configKey") == affinity_key), None)
            if opt is None:
                raise HttpError(400, f"unknown affinityConfig {affinity_key!r}")
            affinity = opt.get("affinity", {})
        tol_key = spawner.form_value(form, "tolerationGroup")
        if tol_key:
            opt = next((o for o in (spawner.defaults.get("tolerationGroup", {}).get("options") or [])
                        if o.get("groupKey") == tol_key), None)
            if opt is None:
                raise HttpError(400, f"unknown tolerationGroup {tol_key!r}")
            tolerations = opt.get("tolerations", [])

        volumes, mounts = [], []
        workspace = spawner.form_value(form, "workspaceVolume")
        for vol in ([workspace] if workspace else []) + list(spawner.form_value(form, "dataVolumes") or []):
            pvc_info = _ensure_pvc(client, ns, name, vol)
            if pvc_info:
                volumes.append({"name": pvc_info["name"], "persistentVolumeClaim": {"claimName": pvc_info["name"]}})
                mounts.append({"name": pvc_info["name"], "mountPath": vol.get("mount", "/data")})

        labels = {}
        for conf in spawner.form_value(form, "configurations") or []:
            labels[conf] = "true"

        container: Dict[str, Any] = {
            "name": name,
            "image": image,
            "resources": {"requests": {"cpu": cpu, "memory": memory}},
            "volumeMounts": mounts,
        }
        if spawner.form_value(form, "shm"):
            volumes.append({"name": "dshm", "emptyDir": {"medium": "Memory"}})
            container["volumeMounts"] = mounts + [{"name": "dshm", "mountPath": "/dev/shm"}]

        pod_spec: Dict[str, Any] = {"containers": [container], "volumes": volumes}

        # Affinity/toleration groups (reference spawner_ui_config.yaml:155-200,
        # form.py set_notebook_affinity/tolerations), resolved above. TPU
        # topology selectors are injected by the PodDefault webhook and
        # merge with these by key.
        if affinity is not None:
            pod_spec["affinity"] = affinity
        if tolerations is not None:
            pod_spec["tolerations"] = tolerations

        spec: Dict[str, Any] = {"template": {"spec": pod_spec}}
        if tpu:
            spec["tpu"] = tpu

        nb = apimeta.new_object(NOTEBOOK_API, "Notebook", name, ns, labels=labels, spec=spec)
        try:
            client.create(nb)
        except Conflict:
            raise HttpError(409, f"notebook {name!r} exists") from None
        return {"status": "created", "notebook": name}

    @app.route("/api/namespaces/<ns>/notebooks/<name>", methods=("PATCH",))
    @annotate(response="Status")
    def patch_notebook(req: Request):
        ns, name = req.params["ns"], req.params["name"]
        authorizer.ensure(user(req), "update", ns)
        body = req.json or {}
        stopped = body.get("stopped")
        if client.get_opt(NOTEBOOK_API, "Notebook", name, ns) is None:
            raise HttpError(404, "notebook not found")
        # Atomic merge-patch (reference patch.py PATCHes the annotation the
        # same way): a get→update here would race the controller's status
        # writes and surface spurious 409s to the UI.
        value = client.store.now() if stopped else None
        client.patch(
            NOTEBOOK_API,
            "Notebook",
            name,
            {"metadata": {"annotations": {STOP_ANNOTATION: value}}},
            ns,
        )
        return {"status": "stopped" if stopped else "started"}

    @app.route("/api/namespaces/<ns>/notebooks/<name>", methods=("DELETE",))
    @annotate(response="Status")
    def delete_notebook(req: Request):
        ns, name = req.params["ns"], req.params["name"]
        authorizer.ensure(user(req), "delete", ns)
        client.delete(NOTEBOOK_API, "Notebook", name, ns)
        return {"status": "deleted"}

    install_cluster_api(app, client, authorizer, cache=cache)
    install_apidocs(app)
    install_spa(app, load_ui("jupyter.html"), cfg)
    return app


def _first_container(nb: Dict[str, Any]) -> Dict[str, Any]:
    containers = nb.get("spec", {}).get("template", {}).get("spec", {}).get("containers") or [{}]
    return containers[0]


def _ensure_pvc(client: Client, ns: str, nb_name: str, vol: Dict[str, Any]) -> Optional[Dict[str, str]]:
    """Create the PVC for a 'new' volume; reference existing ones as-is."""
    if not isinstance(vol, dict):
        return None
    # Simplified UI shape ({type: new|existing, name, size, mount}) — the
    # declarative spawner form submits this; the Angular reference builds
    # the full newPvc object client-side instead.
    if "name" in vol and "newPvc" not in vol and "existingSource" not in vol and "existing" not in vol:
        if not vol["name"]:
            return None
        if vol.get("type") == "existing":
            vol = {"existing": vol["name"], "mount": vol.get("mount", "/data")}
        else:
            vol = {
                "newPvc": {
                    "metadata": {"name": vol["name"]},
                    "spec": {
                        "resources": {"requests": {"storage": vol.get("size") or "10Gi"}},
                        "accessModes": ["ReadWriteOnce"],
                    },
                },
                "mount": vol.get("mount", "/data"),
            }
    if "existingSource" in vol or "existing" in vol:
        name = vol.get("existing") or (vol.get("existingSource") or {}).get(
            "persistentVolumeClaim", {}
        ).get("claimName")
        return {"name": name} if name else None
    new = vol.get("newPvc")
    if not new:
        return None
    pvc_name = (new.get("metadata") or {}).get("name", f"{nb_name}-vol")
    pvc_name = pvc_name.replace("{notebook-name}", nb_name)
    pvc_spec = apimeta.deepcopy(new.get("spec") or {})
    storage_class = pvc_spec.get("storageClassName")
    # Storage-class sentinels (volumes webapp form.py:4-19).
    if storage_class == "{none}":
        pvc_spec["storageClassName"] = None
    elif storage_class == "{empty}":
        pvc_spec.pop("storageClassName", None)
    pvc = apimeta.new_object("v1", "PersistentVolumeClaim", pvc_name, ns, spec=pvc_spec)
    try:
        client.create(pvc)
    except Conflict:
        pass  # already exists (concurrent spawn or reused workspace) — mount it
    return {"name": pvc_name}

def main() -> None:  # python -m kubeflow_tpu.services.jupyter
    import os

    from ..runtime.bootstrap import run_webapp

    def factory(client, auth):
        spawner = None
        path = os.environ.get("SPAWNER_CONFIG")
        if path and os.path.exists(path):
            with open(path) as f:
                spawner = SpawnerConfig.from_yaml(f.read())
        return make_jupyter_app(client, auth=auth, spawner=spawner)

    run_webapp("jupyter-web-app", factory)


if __name__ == "__main__":
    main()
