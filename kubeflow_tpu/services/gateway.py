"""Authenticating front gateway — the platform's Dex/oauth2-proxy/Istio
analog (VERDICT r4 missing #2 / next #4).

In the reference, end users never reach a web backend directly: they log in
through Dex or IAP (testing/auth.py drives the Dex form; test_jwa.py:7-9
logs in before touching JWA) and the Istio ingressgateway is the only thing
that sets the trusted identity header on upstream requests
(profile_controller.go:340-438 builds the AuthorizationPolicies that match
it). The web backends therefore TRUST ``kubeflow-userid`` blindly — the
trust root is the gateway, not the backend.

This module is that trust root for the TPU platform:

- **Session login**: ``GET /login`` serves a form; ``POST /login`` checks
  the credential table (``GATEWAY_USERS`` env / Secret: PBKDF2-hashed
  passwords, :func:`hash_password`) and sets a signed, HttpOnly session
  cookie (HMAC-SHA256 over ``email|expiry`` with ``GATEWAY_SESSION_KEY``).
- **Reverse proxy**: every other path is forwarded to the routed upstream
  (``GATEWAY_ROUTES`` env: ``/jupyter=http://...;/=http://dashboard...``),
  with the incoming ``kubeflow-userid`` header STRIPPED (spoof attempt →
  the session's identity wins), the session's identity injected, and the
  gateway's shared secret attached (``x-gateway-token``).
- **Backend rejection of spoofed direct requests**: backends configured
  with ``GATEWAY_SHARED_SECRET`` (web/auth.py) 401 any request whose
  ``x-gateway-token`` doesn't match — a client that bypasses the gateway
  and hand-writes ``kubeflow-userid`` gets nothing, the Istio
  per-request-enforcement analog.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import secrets
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

from ..web.auth import GATEWAY_TOKEN_HEADER, USERID_HEADER
from ..web.http import App, JsonResponse, Request

SESSION_COOKIE = "kubeflow-session"

#: request headers never forwarded upstream: identity is gateway-asserted,
#: hop-by-hop headers are per-connection. The cookie header is re-written
#: separately (the session cookie must never reach backends).
_STRIP = {USERID_HEADER, GATEWAY_TOKEN_HEADER, "host", "connection", "keep-alive",
          "transfer-encoding", "content-length", "upgrade", "proxy-authorization",
          "cookie"}
#: response headers not passed back (the gateway's server sets its own).
_STRIP_RESP = {"connection", "keep-alive", "transfer-encoding", "content-length",
               "set-cookie"}  # multi-valued: carried via get_all, not the dict


class _NoRedirectHandler(urllib.request.HTTPRedirectHandler):
    def redirect_request(self, *args, **kwargs):
        return None


_no_redirect_opener = urllib.request.build_opener(_NoRedirectHandler)


def hash_password(password: str, salt: Optional[bytes] = None, rounds: int = 100_000) -> str:
    """``pbkdf2$<rounds>$<salt-b64>$<hash-b64>`` — the credential-table entry
    format (print one with ``python -m kubeflow_tpu.services.gateway --hash``)."""
    salt = salt if salt is not None else secrets.token_bytes(16)
    digest = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, rounds)
    return "pbkdf2$%d$%s$%s" % (
        rounds, base64.b64encode(salt).decode(), base64.b64encode(digest).decode())


def check_password(password: str, entry: str) -> bool:
    try:
        scheme, rounds, salt_b64, hash_b64 = entry.split("$")
        if scheme != "pbkdf2":
            return False
        digest = hashlib.pbkdf2_hmac(
            "sha256", password.encode(), base64.b64decode(salt_b64), int(rounds))
        return hmac.compare_digest(digest, base64.b64decode(hash_b64))
    except (ValueError, TypeError):
        return False


def users_from_env() -> Dict[str, str]:
    """``GATEWAY_USERS`` = ``email=pbkdf2$...;email2=...`` (the Secret-mounted
    credential table — the platform's Dex staticPasswords analog)."""
    table: Dict[str, str] = {}
    for entry in filter(None, os.environ.get("GATEWAY_USERS", "").split(";")):
        email, _, entry_hash = entry.partition("=")
        if email and entry_hash:
            table[email.strip()] = entry_hash.strip()
    return table


def routes_from_env() -> List[Tuple[str, str]]:
    """``GATEWAY_ROUTES`` = ``/jupyter=http://...;/=http://dashboard...``;
    longest prefix wins (so ``/`` can be the dashboard fallback)."""
    routes: List[Tuple[str, str]] = []
    for entry in filter(None, os.environ.get("GATEWAY_ROUTES", "").split(";")):
        prefix, _, url = entry.partition("=")
        if prefix and url:
            routes.append((prefix.strip(), url.strip().rstrip("/")))
    return sorted(routes, key=lambda r: len(r[0]), reverse=True)


class SessionSigner:
    """Signed session tokens: ``email|expiry|hmac(email|expiry)``."""

    def __init__(self, key: Optional[bytes] = None, ttl: float = 12 * 3600):
        self.key = key or os.environ.get("GATEWAY_SESSION_KEY", "").encode() \
            or secrets.token_bytes(32)
        self.ttl = ttl

    def issue(self, email: str) -> str:
        expiry = str(int(time.time() + self.ttl))
        payload = f"{email}|{expiry}"
        sig = hmac.new(self.key, payload.encode(), hashlib.sha256).hexdigest()
        return base64.urlsafe_b64encode(f"{payload}|{sig}".encode()).decode()

    def verify(self, token: Optional[str]) -> Optional[str]:
        """Token → email, or None (absent/forged/expired)."""
        if not token:
            return None
        try:
            email, expiry, sig = base64.urlsafe_b64decode(token.encode()).decode().rsplit("|", 2)
        except (ValueError, UnicodeDecodeError):
            return None
        want = hmac.new(self.key, f"{email}|{expiry}".encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(sig, want):
            return None
        if time.time() >= float(expiry):
            return None
        return email


def _login_page() -> str:
    from ..web.static import load_ui

    return load_ui("login.html")


def make_gateway_app(
    users: Optional[Dict[str, str]] = None,
    routes: Optional[List[Tuple[str, str]]] = None,
    signer: Optional[SessionSigner] = None,
    shared_secret: Optional[str] = None,
    secure_cookies: bool = False,
    timeout: float = 30.0,
) -> App:
    users = users if users is not None else users_from_env()
    routes = routes if routes is not None else routes_from_env()
    signer = signer or SessionSigner()
    shared_secret = shared_secret if shared_secret is not None \
        else os.environ.get("GATEWAY_SHARED_SECRET", "")
    app = App("gateway")
    login_html = _login_page()  # render-once, like install_spa pages
    # one PBKDF2 evaluation regardless of user existence (no enumeration
    # timing oracle): unknown emails verify against this throwaway entry
    dummy_entry = hash_password(secrets.token_urlsafe(8))

    def session_cookie(token: str, max_age: Optional[int] = None) -> str:
        attrs = f"{SESSION_COOKIE}={token}; Path=/; HttpOnly; SameSite=Lax"
        if max_age is not None:
            attrs += f"; Max-Age={max_age}"
        if secure_cookies:
            attrs += "; Secure"
        return attrs

    @app.route("/login")
    def login_form(req: Request):
        return JsonResponse(login_html,
                            headers={"Content-Type": "text/html; charset=utf-8"})

    @app.route("/login", methods=("POST",))
    def login_submit(req: Request):
        # accept JSON (kfui form serializer / API clients) and classic form
        # posts; sniff the body since in-process calls carry no content-type
        raw = req.body.decode(errors="replace")
        if req.header("content-type").startswith("application/json") or \
                raw.lstrip().startswith("{"):
            body = req.json or {}
            email = body.get("email", "")
            password = body.get("password", "")
        else:
            from urllib.parse import parse_qs

            form = parse_qs(raw)
            email = (form.get("email") or [""])[0]
            password = (form.get("password") or [""])[0]
        entry = users.get(email)
        ok = check_password(password, entry) if entry else (
            check_password(password, dummy_entry) and False)
        if not ok:
            return JsonResponse({"error": "invalid credentials", "status": 401}, status=401)
        resp = JsonResponse({"status": "ok", "user": email})
        resp.cookies.append(session_cookie(signer.issue(email)))
        return resp

    @app.route("/logout", methods=("GET", "POST"))
    def logout(req: Request):
        resp = JsonResponse({"status": "logged out"})
        resp.cookies.append(session_cookie("", max_age=0))
        return resp

    @app.route("/healthz")
    def healthz(req: Request):
        return {"status": "ok", "role": "gateway"}

    @app.middleware
    def proxy(req: Request) -> Optional[JsonResponse]:
        if req.path in ("/login", "/logout", "/healthz"):
            return None  # the gateway's own routes
        email = signer.verify(req.cookie(SESSION_COOKIE))
        if email is None:
            accepts = req.header("accept", "")
            if req.method == "GET" and "text/html" in accepts:
                return JsonResponse(
                    "", status=302,
                    headers={"Location": "/login",
                             "Content-Type": "text/html; charset=utf-8"})
            return JsonResponse(
                {"error": "not logged in", "status": 401}, status=401)
        def prefix_matches(p: str) -> bool:
            # segment-boundary prefix: /volumes must not capture
            # /volumesnapshots (that belongs to the "/" fallback route)
            if p == "/":
                return True
            return req.path == p or req.path.startswith(p + "/")

        match = next(((p, u) for p, u in routes if prefix_matches(p)), None)
        if match is None:
            return JsonResponse({"error": f"no route for {req.path}", "status": 404},
                                status=404)
        prefix, upstream = match
        # prefix rewrite, the VirtualService http-rewrite-uri analog
        # (notebook_controller.go:414-417): /jupyter/api/x -> /api/x upstream
        path = req.path if prefix == "/" else "/" + req.path[len(prefix):].lstrip("/")
        # identity is gateway-asserted: any client-supplied value dies here
        headers = {k: v for k, v in req.headers.items() if k.lower() not in _STRIP}
        headers[USERID_HEADER] = email
        if shared_secret:
            headers[GATEWAY_TOKEN_HEADER] = shared_secret
        # forward cookies MINUS the gateway session: a backend must never
        # hold a replayable all-routes credential (oauth2-proxy behavior)
        fwd_cookies = [p.strip() for p in (req.header("cookie") or "").split(";")
                       if p.strip() and not p.strip().startswith(SESSION_COOKIE + "=")]
        if fwd_cookies:
            headers["cookie"] = "; ".join(fwd_cookies)
        from urllib.parse import urlencode

        qs = urlencode(req.query, doseq=True)
        url = upstream + path + (f"?{qs}" if qs else "")
        up_req = urllib.request.Request(
            url, data=req.body or None, method=req.method, headers=headers)
        try:
            # no server-side redirect following: a 3xx is RELAYED to the
            # browser (the HTTPError path below), never fetched by the
            # gateway itself (SSRF surface + wrong-method replays)
            with _no_redirect_opener.open(up_req, timeout=timeout) as up:
                body = up.read()
                resp_headers = {k: v for k, v in up.headers.items()
                                if k.lower() not in _STRIP_RESP}
                resp = JsonResponse(body, status=up.status, headers=resp_headers)
                resp.cookies.extend(up.headers.get_all("set-cookie") or [])
                return resp
        except urllib.error.HTTPError as e:
            body = e.read()
            resp_headers = {k: v for k, v in e.headers.items()
                            if k.lower() not in _STRIP_RESP}
            resp = JsonResponse(body, status=e.code, headers=resp_headers)
            resp.cookies.extend(e.headers.get_all("set-cookie") or [])
            return resp
        except (urllib.error.URLError, OSError) as e:
            return JsonResponse({"error": f"upstream unreachable: {e}", "status": 502},
                                status=502)

    return app


def main(argv=None) -> None:
    import argparse
    import logging

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--hash", metavar="PASSWORD",
                        help="print a GATEWAY_USERS credential hash and exit")
    parser.add_argument("--port", type=int, default=int(os.environ.get("PORT", "8083")))
    args = parser.parse_args(argv)
    if args.hash:
        print(hash_password(args.hash))
        return
    logging.basicConfig(level=os.environ.get("LOG_LEVEL", "INFO"))
    from ..runtime.bootstrap import block_forever
    from ..utils import env_flag

    app = make_gateway_app(secure_cookies=env_flag("APP_SECURE_COOKIES"))
    server = app.serve(args.port, host="0.0.0.0")
    logging.getLogger("kubeflow_tpu.gateway").info(
        "gateway on :%d (%d users, %d routes)", server.port,
        len(users_from_env()), len(routes_from_env()))
    try:
        block_forever()
    finally:
        server.close()


if __name__ == "__main__":
    main()
