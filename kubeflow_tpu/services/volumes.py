"""Volumes web app backend: PVC CRUD.

Re-implements the reference VWA backend (crud-web-apps/volumes/backend/apps/
common/form.py:22-38 pvc_from_dict; storage-class sentinels {none}/{empty}
form.py:4-19). Deletion is refused while a pod mounts the PVC — the UI-level
guard the reference implements client-side.
"""

from __future__ import annotations

from typing import Optional

from ..api import meta as apimeta
from ..apiserver.client import Client
from ..apiserver.store import Conflict
from ..utils.quantity import parse_quantity
from ..web.openapi import annotate, install_apidocs
from ..web.resources import install_cluster_api
from ..web.static import install_spa, load_ui
from ..web.auth import AuthConfig, Authorizer, install_auth, issue_csrf_cookie
from ..web.http import App, HttpError, JsonResponse, Request


def make_volumes_app(client: Client, auth: Optional[AuthConfig] = None) -> App:
    cfg = auth or AuthConfig()
    authorizer = Authorizer(client, cfg)
    app = App("volumes-web-app")
    install_auth(app, authorizer)

    @app.route("/api/config")
    def config(req: Request):
        resp = JsonResponse({"config": {}})
        issue_csrf_cookie(resp, cfg)
        return resp

    @app.route("/api/namespaces/<ns>/pvcs")
    @annotate(response="PvcList")
    def list_pvcs(req: Request):
        ns = req.params["ns"]
        authorizer.ensure(req.context["user"], "list", ns)
        mounted = _mounted_pvcs(client, ns)
        return {
            "pvcs": [
                {
                    "name": apimeta.name_of(p),
                    "namespace": ns,
                    "capacity": (p.get("spec", {}).get("resources", {}).get("requests") or {}).get("storage", ""),
                    # numeric for column sorting: '20Gi' < '100Gi' must not
                    # compare lexicographically (utils/quantity.py)
                    "capacityBytes": parse_quantity(
                        (p.get("spec", {}).get("resources", {}).get("requests") or {}).get("storage")),
                    "modes": p.get("spec", {}).get("accessModes", []),
                    "class": p.get("spec", {}).get("storageClassName"),
                    "inUse": apimeta.name_of(p) in mounted,
                }
                for p in client.list("v1", "PersistentVolumeClaim", ns)
            ]
        }

    @app.route("/api/namespaces/<ns>/pvcs", methods=("POST",))
    @annotate(response="Status")
    def create_pvc(req: Request):
        ns = req.params["ns"]
        authorizer.ensure(req.context["user"], "create", ns)
        body = req.json or {}
        name = body.get("name")
        if not name:
            raise HttpError(400, "name required")
        size = body.get("size", "10Gi")
        mode = body.get("mode", "ReadWriteOnce")
        storage_class = body.get("class", "{empty}")
        spec = {
            "accessModes": [mode],
            "resources": {"requests": {"storage": size}},
        }
        if storage_class == "{none}":
            spec["storageClassName"] = None
        elif storage_class != "{empty}":
            spec["storageClassName"] = storage_class
        try:
            client.create(apimeta.new_object("v1", "PersistentVolumeClaim", name, ns, spec=spec))
        except Conflict:
            raise HttpError(409, f"pvc {name!r} exists") from None
        return {"status": "created"}

    @app.route("/api/namespaces/<ns>/pvcs/<name>", methods=("DELETE",))
    @annotate(response="Status")
    def delete_pvc(req: Request):
        ns, name = req.params["ns"], req.params["name"]
        authorizer.ensure(req.context["user"], "delete", ns)
        if name in _mounted_pvcs(client, ns):
            raise HttpError(409, f"pvc {name!r} is mounted by a pod")
        client.delete("v1", "PersistentVolumeClaim", name, ns)
        return {"status": "deleted"}

    install_cluster_api(app, client, authorizer)
    install_apidocs(app)
    install_spa(app, load_ui("volumes.html"), cfg)
    return app


def _mounted_pvcs(client: Client, ns: str) -> set:
    used = set()
    for pod in client.list("v1", "Pod", ns):
        for vol in pod.get("spec", {}).get("volumes", []) or []:
            claim = (vol.get("persistentVolumeClaim") or {}).get("claimName")
            if claim:
                used.add(claim)
    return used

def main() -> None:  # python -m kubeflow_tpu.services.volumes
    from ..runtime.bootstrap import run_webapp

    run_webapp("volumes-web-app", lambda client, auth: make_volumes_app(client, auth))


if __name__ == "__main__":
    main()
