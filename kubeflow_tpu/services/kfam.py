"""KFAM: profile + contributor access management REST API.

Re-implements the reference access-management service
(components/access-management/kfam/):

- routes (routers.go:32-99): POST/DELETE ``/kfam/v1/profiles[/<name>]``,
  GET/POST/DELETE ``/kfam/v1/bindings``, GET ``/kfam/v1/role/clusteradmin``,
- permission gate: only the profile owner or a cluster admin may manage a
  profile's bindings (api_default.go:303-310),
- a contributor = RoleBinding (annotations ``user``/``role``,
  bindings.go:103-106) + per-user Istio AuthorizationPolicy (:120-138),
- binding name mangling (getBindingName :61-78): ``user-<user>-clusterrole-
  <role>`` with non-alphanumerics dashed,
- role map admin/edit/view ↔ kubeflow-admin/edit/view (:39-46).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from ..api import meta as apimeta
from ..apiserver.client import Client
from ..apiserver.store import Conflict, NotFound
from ..controllers.profile import PROFILE_API, ROLE_MAP
from ..runtime.metrics import METRICS
from ..web.auth import AuthConfig, Authorizer, install_auth
from ..web.openapi import annotate, install_apidocs
from ..web.http import App, HttpError, Request

BINDING_ANNOTATION_USER = "user"
BINDING_ANNOTATION_ROLE = "role"


def binding_name(user: str, role: str) -> str:
    mangled = re.sub(r"[^a-z0-9]", "-", user.lower())
    return f"user-{mangled}-clusterrole-kubeflow-{role}"


def make_kfam_app(
    client: Client,
    auth: Optional[AuthConfig] = None,
    userid_header: str = "kubeflow-userid",
    cache: Optional["InformerCache"] = None,
) -> App:
    from ..runtime.informer import InformerCache

    cfg = auth or AuthConfig(userid_header=userid_header)
    authorizer = Authorizer(client, cfg)
    app = App("kfam")
    install_auth(app, authorizer, enable_csrf=False)
    # List hot paths read through shared informers, not per-request API
    # scans — the reference reads RoleBindings via a 60-min shared informer
    # lister (access-management/kfam/api_default.go:71-75).
    cache = cache or InformerCache(client)

    def profile_of(name: str) -> Dict[str, Any]:
        profile = client.get_opt(PROFILE_API, "Profile", name)
        if profile is None:
            raise HttpError(404, f"profile {name!r} not found")
        return profile

    def ensure_owner_or_admin(user: str, profile_name: str) -> None:
        profile = profile_of(profile_name)
        owner = profile.get("spec", {}).get("owner", {}).get("name", "")
        if user != owner and not authorizer.is_cluster_admin(user):
            raise HttpError(403, f"user {user!r} is neither owner of {profile_name!r} nor cluster admin")

    # -- profiles ------------------------------------------------------------
    @app.route("/kfam/v1/profiles", methods=("POST",))
    @annotate(response="Profile", request="Profile")
    def create_profile(req: Request):
        body = req.json or {}
        name = (body.get("metadata") or {}).get("name") or body.get("name")
        if not name:
            raise HttpError(400, "profile name required")
        owner = (body.get("spec") or {}).get("owner") or {
            "kind": "User",
            "name": req.context["user"],
        }
        profile = apimeta.new_object(
            PROFILE_API,
            "Profile",
            name,
            spec={"owner": owner, **{k: v for k, v in (body.get("spec") or {}).items() if k != "owner"}},
        )
        METRICS.counter("kfam_request_total", route="create_profile").inc()
        try:
            return client.create(profile)
        except Conflict:
            raise HttpError(409, f"profile {name!r} already exists") from None

    @app.route("/kfam/v1/profiles/<name>", methods=("DELETE",))
    @annotate(response="Status")
    def delete_profile(req: Request):
        ensure_owner_or_admin(req.context["user"], req.params["name"])
        client.delete(PROFILE_API, "Profile", req.params["name"])
        return {"status": "deleted"}

    @app.route("/kfam/v1/profiles/<name>", methods=("GET",))
    @annotate(response="Profile")
    def get_profile(req: Request):
        return profile_of(req.params["name"])

    # -- bindings ------------------------------------------------------------
    @app.route("/kfam/v1/bindings", methods=("POST",))
    @annotate(response="BindingCreated", request="Binding")
    def create_binding(req: Request):
        body = req.json or {}
        ns = body.get("referredNamespace")
        subject = body.get("user") or {}
        role = ((body.get("roleRef") or {}).get("name") or "edit").lower()
        if role not in ROLE_MAP:
            raise HttpError(400, f"unknown role {role!r}; want one of {sorted(ROLE_MAP)}")
        if not ns or not subject.get("name"):
            raise HttpError(400, "referredNamespace and user.name required")
        ensure_owner_or_admin(req.context["user"], ns)

        name = binding_name(subject["name"], role)
        rb = apimeta.new_object(
            "rbac.authorization.k8s.io/v1",
            "RoleBinding",
            name,
            ns,
            annotations={
                BINDING_ANNOTATION_USER: subject["name"],
                BINDING_ANNOTATION_ROLE: role,
            },
            roleRef={
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": ROLE_MAP[role],
            },
            subjects=[{"kind": "User", "name": subject["name"]}],
        )
        try:
            rb = client.create(rb)  # re-bind: the response carries the write RV
        except Conflict:
            raise HttpError(409, "binding already exists") from None
        policy = apimeta.new_object(
            "security.istio.io/v1beta1",
            "AuthorizationPolicy",
            name,
            ns,
            spec={
                "rules": [
                    {
                        "when": [
                            {
                                "key": f"request.headers[{cfg.userid_header}]",
                                "values": [f"{cfg.userid_prefix}{subject['name']}"],
                            }
                        ]
                    }
                ]
            },
        )
        try:
            client.create(policy)
        except Conflict:
            pass  # leftover from a half-completed earlier create; rb is the gate
        METRICS.counter("kfam_request_total", route="create_binding").inc()
        return {"status": "created", "binding": rb}

    @app.route("/kfam/v1/bindings", methods=("DELETE",))
    @annotate(response="Status", request="Binding")
    def delete_binding(req: Request):
        body = req.json or {}
        ns = body.get("referredNamespace")
        subject = (body.get("user") or {}).get("name")
        role = ((body.get("roleRef") or {}).get("name") or "edit").lower()
        if not ns or not subject:
            raise HttpError(400, "referredNamespace and user.name required")
        ensure_owner_or_admin(req.context["user"], ns)
        name = binding_name(subject, role)
        rv = None
        try:
            gone = client.delete("rbac.authorization.k8s.io/v1", "RoleBinding", name, ns)
            rv = (gone.get("metadata") or {}).get("resourceVersion")
        except NotFound:
            pass
        client.delete_opt("security.istio.io/v1beta1", "AuthorizationPolicy", name, ns)
        # The tombstone RV lets the caller issue a list with
        # minResourceVersion= and be guaranteed not to see this binding.
        return {"status": "deleted", "resourceVersion": rv}

    @app.route("/kfam/v1/bindings", methods=("GET",))
    @annotate(
        response="BindingList",
        query=[
            {"name": "namespace"},
            {"name": "user"},
            {"name": "role"},
            {"name": "minResourceVersion",
             "description": "read-your-writes barrier: do not serve a view older than this RV"},
        ],
    )
    def list_bindings(req: Request):
        want_ns = req.query1("namespace")
        want_user = req.query1("user")
        want_role = req.query1("role")
        # Read-your-writes: a client that just mutated a binding passes the
        # write's RV; the informer blocks until its mirror reflects it
        # (K8s resourceVersionMatch=NotOlderThan semantics).
        min_rv: Optional[int] = None
        raw_rv = req.query1("minResourceVersion")
        if raw_rv:
            try:
                min_rv = int(raw_rv)
            except ValueError:
                raise HttpError(400, f"invalid minResourceVersion {raw_rv!r}") from None
        # Resolve the barrier ONCE, with a short bound: the RV is untrusted
        # client input, so a bogus future RV must not hold a worker thread —
        # and certainly not once per namespace. If the mirror can't reach
        # the RV in time, degrade to direct lists (a live read trivially
        # satisfies any genuine barrier).
        barrier_ok = True
        if min_rv is not None:
            inf = cache.informer_for("rbac.authorization.k8s.io/v1", "RoleBinding")
            barrier_ok = inf.wait_synced(5.0) and inf.wait_rv(min_rv, timeout=2.0)

        def role_bindings(ns: str) -> List[Dict[str, Any]]:
            if barrier_ok:
                return cache.list("rbac.authorization.k8s.io/v1", "RoleBinding", ns)
            return client.list("rbac.authorization.k8s.io/v1", "RoleBinding", ns)

        bindings: List[Dict[str, Any]] = []
        namespaces = [want_ns] if want_ns else [
            apimeta.name_of(n) for n in cache.list("v1", "Namespace")
        ]
        for ns in namespaces:
            for rb in role_bindings(ns):
                anns = apimeta.annotations_of(rb)
                if BINDING_ANNOTATION_USER not in anns or BINDING_ANNOTATION_ROLE not in anns:
                    continue  # not a kfam contributor binding
                if want_user and anns[BINDING_ANNOTATION_USER] != want_user:
                    continue
                if want_role and anns[BINDING_ANNOTATION_ROLE] != want_role:
                    continue
                bindings.append(
                    {
                        "user": {"kind": "User", "name": anns[BINDING_ANNOTATION_USER]},
                        "referredNamespace": ns,
                        "roleRef": {
                            "apiGroup": "rbac.authorization.k8s.io",
                            "kind": "ClusterRole",
                            "name": anns[BINDING_ANNOTATION_ROLE],
                        },
                    }
                )
        return {"bindings": bindings}

    # -- cluster admin check -------------------------------------------------
    @app.route("/kfam/v1/role/clusteradmin", methods=("GET",))
    def cluster_admin(req: Request):
        user = req.query1("user") or req.context["user"]
        return authorizer.is_cluster_admin(user)

    # API contract (reference ships access-management/api/swagger.yaml by
    # hand; ours is generated from the route table so it cannot drift).
    install_apidocs(app, base_path="/kfam")
    return app

def main() -> None:  # python -m kubeflow_tpu.services.kfam
    import os

    from ..runtime.bootstrap import run_webapp

    os.environ.setdefault("PORT", "8081")
    run_webapp("kfam", lambda client, auth: make_kfam_app(client, auth))


if __name__ == "__main__":
    main()
