"""REST services: KFAM, spawner/CRUD backends, dashboard BFF, serving.

Each service is an ``App`` (kubeflow_tpu.web) over the shared store client —
the in-process analog of the reference's separately-deployed pods behind
Istio. All are servable over real HTTP (``app.serve()``) and callable
in-process for tests.
"""
