"""Central dashboard BFF: namespaces, activities, metrics, workgroup flow.

Re-implements the reference centraldashboard server (components/
centraldashboard/app/): the Express BFF's API surface (api.ts:29-102),
the registration/workgroup flow (api_workgroup.ts), settings/links from a
ConfigMap (k8s_service.ts:81-89), platform inference from node providerID
(:138-150), and the pluggable MetricsService interface
(metrics_service.ts:20-41) — implemented here by a TPU metrics provider
(chips allocated vs capacity per node/namespace) instead of Stackdriver.

Workgroup routes proxy to KFAM exactly as the reference's DefaultApi client
does (app/clients/profile_controller.ts), here via in-process dispatch.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..api import meta as apimeta
from ..apiserver.client import Client
from ..controllers.profile import PROFILE_API
from ..tpu.topology import RESOURCE_TPU, pod_tpu_chips
from ..web.openapi import annotate, install_apidocs
from ..web.static import install_spa, load_ui
from ..web.auth import AuthConfig, Authorizer, install_auth
from ..web.http import App, HttpError, JsonResponse, Request

SETTINGS_CONFIGMAP = "centraldashboard-config"
KUBEFLOW_VERSION = "tpu-native-dev"
DEFAULT_LINKS = {
    "menuLinks": [
        {"type": "item", "link": "/jupyter/", "text": "Notebooks", "icon": "book"},
        {"type": "item", "link": "/tensorboards/", "text": "Tensorboards", "icon": "assessment"},
        {"type": "item", "link": "/volumes/", "text": "Volumes", "icon": "device:storage"},
        {"type": "item", "link": "/katib/", "text": "Experiments (HPO)", "icon": "kubeflow:katib"},
        {"type": "item", "link": "/serving/", "text": "Model Serving", "icon": "kubeflow:models"},
    ],
    "externalLinks": [],
    "quickLinks": [
        {"text": "Create a new Notebook server", "desc": "Jupyter on TPU slices", "link": "/jupyter/new"},
    ],
}


class TpuMetricsService:
    """MetricsService impl (interface: metrics_service.ts:20-41) reporting
    TPU chip allocation. With a monitoring plane wired, node utilization is
    read from federated ``node_tpu_*_chips`` gauges (published by whichever
    process runs ``monitoring.install_cluster_collector``) and the raw pod
    math becomes the fallback for clusters without a monitor running."""

    def __init__(self, client: Client, cache: Optional["InformerCache"] = None,
                 monitoring=None):
        from ..runtime.informer import InformerCache

        self.client = client
        # Watch-backed reads: a dashboard poll must not list every pod in
        # the cluster per request (the reference reads through a shared
        # informer — kfam/api_default.go:71-75).
        self.cache = cache or InformerCache(client)
        #: MonitoringPlane or bare TSDB (both expose the read surface)
        self.monitoring = monitoring
        self.tsdb = getattr(monitoring, "tsdb", monitoring)

    def _list(self, api_version: str, kind: str, namespace: Optional[str] = None):
        return self.cache.list(api_version, kind, namespace)

    def node_tpu_utilization(self) -> List[Dict[str, Any]]:
        federated = self._federated_node_utilization()
        if federated is not None:
            return federated
        out = []
        pods = self._list("v1", "Pod")
        for node in self._list("v1", "Node"):
            name = apimeta.name_of(node)
            capacity = int((node.get("status", {}).get("capacity") or {}).get(RESOURCE_TPU, 0))
            if capacity <= 0:
                continue
            used = sum(
                pod_tpu_chips(p) for p in pods if p.get("spec", {}).get("nodeName") == name
            )
            out.append({"node": name, "capacityChips": capacity, "allocatedChips": used,
                        "utilization": used / capacity})
        return out

    def _federated_node_utilization(self) -> Optional[List[Dict[str, Any]]]:
        """Node rows from the TSDB's fresh ``node_tpu_*_chips`` series, or
        None when nothing federated is available (fall back to pod math).
        Stale series are excluded by the TSDB read path, so a dead monitor
        degrades to the fallback instead of pinning old numbers."""
        if self.tsdb is None:
            return None
        caps = self.tsdb.latest("node_tpu_capacity_chips")
        if not caps:
            return None
        alloc = {
            labels.get("node"): value
            for labels, _ts, value in self.tsdb.latest("node_tpu_allocated_chips")
        }
        out = []
        for labels, _ts, capacity in caps:
            node = labels.get("node")
            if node is None or capacity <= 0:
                continue
            used = alloc.get(node, 0.0)
            out.append({"node": node, "capacityChips": int(capacity),
                        "allocatedChips": int(used),
                        "utilization": used / capacity, "source": "federated"})
        return sorted(out, key=lambda r: r["node"]) or None

    def namespace_tpu_usage(self, namespace: str) -> Dict[str, Any]:
        used = sum(pod_tpu_chips(p) for p in self._list("v1", "Pod", namespace))
        return {"namespace": namespace, "allocatedChips": used}

    def platform_overview(self, window_s: float = 300.0) -> Dict[str, Any]:
        """The monitoring plane's aggregate view: per-target health, the
        fleet-wide serving tail over ``window_s``, and the live alert table
        — 503 without a plane (there is nothing honest to show)."""
        if self.tsdb is None:
            raise HttpError(503, "monitoring plane not wired")
        import time as _time

        now = _time.time()
        targets = []
        durations = {
            tuple(sorted(labels.items())): value
            for labels, _ts, value in self.tsdb.latest("scrape_duration_seconds",
                                                       include_stale=True)
        }
        for labels, ts, value in self.tsdb.latest("up", include_stale=True):
            targets.append({
                "instance": labels.get("instance", ""),
                "job": labels.get("job", ""),
                "up": value,
                "lastScrapeAgoSeconds": round(max(0.0, now - ts), 3),
                "scrapeDurationSeconds": durations.get(tuple(sorted(labels.items()))),
            })
        serving = {
            "ttftP99": self.tsdb.histogram_quantile(
                "serving_ttft_seconds", 0.99, window_s, now),
            "queueWaitP99": self.tsdb.histogram_quantile(
                "serving_queue_wait_seconds", 0.99, window_s, now),
            "windowSeconds": window_s,
        }
        # control-plane SLIs (ISSUE 11): the scheduler's scraped rate/bind
        # gauges plus per-queue backlog pressure, same federated source
        cycles = [v for _l, _ts, v in self.tsdb.latest("scheduler_cycles_per_sec")]
        saturation = {
            labels.get("queue", ""): value
            for labels, _ts, value in self.tsdb.latest("workqueue_saturation")
        }
        scheduler = {
            "cyclesPerSec": round(sum(cycles), 6) if cycles else None,
            "bindLatencyP99": self.tsdb.histogram_quantile(
                "scheduler_bind_latency_seconds", 0.99, window_s, now),
            "workqueueSaturation": saturation,
            "windowSeconds": window_s,
        }
        rules = getattr(self.monitoring, "rules", None)
        alerts = rules.snapshot()["alerts"] if rules is not None else []
        return {
            "targets": sorted(targets, key=lambda t: t["instance"]),
            "serving": serving,
            "scheduler": scheduler,
            "goodput": self._goodput_overview(),
            "tenants": self._tenant_overview(),
            "tracing": self._tracing_overview(),
            "stragglers": self._straggler_overview(),
            "alerts": alerts,
            "series": self.tsdb.stats(),
        }

    def _goodput_overview(self) -> Dict[str, Any]:
        """The federated goodput story (ISSUE 19): the workloads' live
        goodput fraction, the badput decomposition by bucket summed across
        instances, and serving token goodput from the waste counters."""
        fractions = {
            labels.get("workload", ""): value
            for labels, _ts, value in self.tsdb.latest(
                "training_goodput_fraction")
        }
        badput: Dict[str, float] = {}
        for labels, _ts, value in self.tsdb.latest(
                "training_badput_seconds_total"):
            bucket = labels.get("bucket", "other")
            badput[bucket] = badput.get(bucket, 0.0) + value
        goodput_s = sum(v for _l, _ts, v in self.tsdb.latest(
            "training_goodput_seconds_total"))
        delivered = sum(v for _l, _ts, v in self.tsdb.latest(
            "serving_tokens_out_total"))
        discarded = sum(v for _l, _ts, v in self.tsdb.latest(
            "serving_discarded_tail_tokens_total"))
        return {
            "trainingGoodputFraction": fractions or None,
            "trainingGoodputSeconds": round(goodput_s, 6),
            "trainingBadputSeconds": {k: round(v, 6)
                                      for k, v in sorted(badput.items())},
            "servingTokenGoodputFraction": (
                delivered / (delivered + discarded)
                if delivered + discarded > 0 else None),
        }

    def _tenant_overview(self) -> List[Dict[str, Any]]:
        """Per-namespace resource accounting: chip-seconds accrued by the
        scheduler's bind/unbind lifecycle, tokens in/out from serving."""
        chip_seconds: Dict[str, float] = {}
        for labels, _ts, value in self.tsdb.latest("tenant_chip_seconds_total"):
            ns = labels.get("namespace", "default")
            chip_seconds[ns] = chip_seconds.get(ns, 0.0) + value
        tokens: Dict[str, Dict[str, float]] = {}
        for labels, _ts, value in self.tsdb.latest("tenant_tokens_total"):
            ns = labels.get("namespace", "default")
            direction = labels.get("direction", "out")
            per = tokens.setdefault(ns, {})
            per[direction] = per.get(direction, 0.0) + value
        return [
            {"namespace": ns,
             "chipSeconds": round(chip_seconds.get(ns, 0.0), 6),
             "tokensIn": tokens.get(ns, {}).get("in", 0.0),
             "tokensOut": tokens.get(ns, {}).get("out", 0.0)}
            for ns in sorted(set(chip_seconds) | set(tokens))
        ]

    def _straggler_overview(self) -> Optional[Dict[str, Any]]:
        """The straggler plane's fleet view (ISSUE 20): per-worker skew
        scores and hang counts from the federated TSDB, plus — when the
        plane runs a StragglerDetector — its active quarantines and the
        last hang verdict. None when no straggler series have federated
        and no detector is wired."""
        scores = {
            labels.get("worker", ""): value
            for labels, _ts, value in self.tsdb.latest(
                "training_straggler_score")
        }
        hangs: Dict[str, float] = {}
        for labels, _ts, value in self.tsdb.latest(
                "training_hangs_detected_total"):
            worker = labels.get("worker", "")
            hangs[worker] = hangs.get(worker, 0.0) + value
        detector = getattr(self.monitoring, "stragglers", None)
        snap = detector.snapshot() if detector is not None else None
        if not scores and not hangs and snap is None:
            return None
        return {
            "workerScores": scores or None,
            "hangsDetected": hangs or None,
            "activeQuarantines": snap["quarantined"] if snap else [],
            "lastHangVerdict": snap["lastHangVerdict"] if snap else None,
        }

    def _tracing_overview(self) -> Optional[Dict[str, Any]]:
        """Slowest gang binds from the plane's TraceCollector, each carrying
        its critical-path decomposition — the answer to 'WHERE did that p99
        bind latency go' next to the histogram that says it exists.  None
        when the plane federates metrics but not traces."""
        collector = getattr(self.monitoring, "traces", None)
        if collector is None:
            return None
        from ..monitoring.traces import critical_path

        slowest = []
        for row in collector.slowest_binds(5):
            assembled = collector.trace(row["traceId"])
            if assembled is not None:
                path = critical_path(assembled)
                if path is not None:
                    row = dict(row, criticalPath=path)
            slowest.append(row)
        return {"slowestBinds": slowest,
                "tracesFederated": len(collector.trace_ids())}


def make_dashboard_app(
    client: Client,
    kfam_app: Optional[App] = None,
    auth: Optional[AuthConfig] = None,
    cache: Optional["InformerCache"] = None,
    monitoring=None,
) -> App:
    cfg = auth or AuthConfig()
    authorizer = Authorizer(client, cfg)
    metrics = TpuMetricsService(client, cache=cache, monitoring=monitoring)
    app = App("centraldashboard")
    install_auth(app, authorizer, enable_csrf=False)

    def user(req: Request) -> str:
        return req.context["user"]

    def kfam(req: Request, method: str, path: str, body: Any = None) -> JsonResponse:
        if kfam_app is None:
            raise HttpError(503, "KFAM not wired")
        resp = kfam_app.call(method, path, body, {cfg.userid_header: user(req)})
        if resp.status >= 400:
            raise HttpError(resp.status, (resp.body or {}).get("error", "kfam error"))
        return resp

    # -- cluster views -------------------------------------------------------
    @app.route("/api/namespaces")
    def namespaces(req: Request):
        return [apimeta.name_of(n) for n in metrics.cache.list("v1", "Namespace")]

    @app.route("/api/activities/<ns>")
    def activities(req: Request):
        authorizer.ensure(user(req), "list", req.params["ns"])
        events = metrics.cache.list("v1", "Event", req.params["ns"])
        return sorted(events, key=lambda e: e.get("lastTimestamp", ""), reverse=True)[:50]

    @app.route("/api/metrics/<kind>")
    def metric(req: Request):
        kind = req.params["kind"]
        if kind == "node":
            return metrics.node_tpu_utilization()
        if kind == "namespace":
            ns = req.query1("namespace")
            if not ns:
                raise HttpError(400, "namespace query param required")
            authorizer.ensure(user(req), "list", ns)
            return metrics.namespace_tpu_usage(ns)
        if kind == "platform":
            try:
                window = float(req.query1("window", "300"))
            except ValueError:
                raise HttpError(400, "window must be a number") from None
            return metrics.platform_overview(window_s=window)
        raise HttpError(400, f"unknown metric {kind!r} (node|namespace|platform)")

    @app.route("/api/dashboard-links")
    def links(req: Request):
        cm = client.get_opt("v1", "ConfigMap", SETTINGS_CONFIGMAP, "kubeflow")
        if cm and "links" in (cm.get("data") or {}):
            import json

            return json.loads(cm["data"]["links"])
        return DEFAULT_LINKS

    @app.route("/api/dashboard-settings")
    def settings(req: Request):
        cm = client.get_opt("v1", "ConfigMap", SETTINGS_CONFIGMAP, "kubeflow")
        if cm and "settings" in (cm.get("data") or {}):
            import json

            return json.loads(cm["data"]["settings"])
        return {"DASHBOARD_FORCE_IFRAME": True}

    @app.route("/debug")
    def debug(req: Request):
        """Build/runtime info (reference server.ts /debug route)."""
        import platform as _platform
        import sys as _sys

        return {
            "app": "centraldashboard",
            "kubeflowVersion": KUBEFLOW_VERSION,
            "python": _sys.version.split()[0],
            "platform": _platform.platform(),
            "user": user(req),
        }

    @app.route("/api/platform-info")
    def platform_info(req: Request):
        provider = "other"
        for node in metrics.cache.list("v1", "Node"):
            pid = node.get("spec", {}).get("providerID", "")
            if pid.startswith("gce://"):
                provider = "gce"
                break
            if pid.startswith("aws://"):
                provider = "aws"
                break
        return {"provider": provider, "kubeflowVersion": KUBEFLOW_VERSION}

    # -- workgroup / registration flow --------------------------------------
    @app.route("/api/workgroup/exists")
    @annotate(response="WorkgroupExists")
    def exists(req: Request):
        u = user(req)
        # Live list, not the informer: registration immediately re-queries
        # this route after POST /api/workgroup/create (page reload), and a
        # stale mirror would bounce the new user back to the signup card.
        # Profiles are small and this route is not a hot poll path.
        owned = [
            apimeta.name_of(p)
            for p in client.list(PROFILE_API, "Profile")
            if p.get("spec", {}).get("owner", {}).get("name") == u
        ]
        return {"hasWorkgroup": bool(owned), "user": u, "namespaces": owned,
                "hasAuth": not cfg.disable_auth, "registrationFlowAllowed": True}

    @app.route("/api/workgroup/create", methods=("POST",))
    def create(req: Request):
        body = req.json or {}
        name = body.get("namespace") or user(req).split("@")[0].replace(".", "-")
        kfam(req, "POST", "/kfam/v1/profiles", {"name": name})
        return {"message": f"profile {name} created"}

    @app.route("/api/workgroup/env-info")
    @annotate(response="EnvInfo")
    def env_info(req: Request):
        u = user(req)
        profiles = client.list(PROFILE_API, "Profile")  # live: follows registration immediately
        namespaces = []
        for p in profiles:
            ns = apimeta.name_of(p)
            owner = p.get("spec", {}).get("owner", {}).get("name")
            role = "owner" if owner == u else None
            if role is None:
                resp = kfam(req, "GET", f"/kfam/v1/bindings?namespace={ns}&user={u}")
                if (resp.body or {}).get("bindings"):
                    role = "contributor"
            if role:
                namespaces.append({"namespace": ns, "role": role})
        return {
            "user": u,
            "platform": app.call("GET", "/api/platform-info", None, {cfg.userid_header: u}).body,
            "namespaces": namespaces,
            "isClusterAdmin": authorizer.is_cluster_admin(u),
        }

    @app.route("/api/workgroup/nuke-self", methods=("DELETE",))
    def nuke_self(req: Request):
        u = user(req)
        nuked = []
        # Deliberately a live list, not the informer: a destructive flow must
        # not act on a stale mirror (miss = orphaned profile).
        for p in client.list(PROFILE_API, "Profile"):
            if p.get("spec", {}).get("owner", {}).get("name") == u:
                kfam(req, "DELETE", f"/kfam/v1/profiles/{apimeta.name_of(p)}")
                nuked.append(apimeta.name_of(p))
        return {"message": f"removed profiles {nuked}"}

    @app.route("/api/workgroup/get-all-namespaces")
    def all_namespaces(req: Request):
        if not authorizer.is_cluster_admin(user(req)):
            raise HttpError(403, "cluster admin only")
        out = []
        for p in client.list(PROFILE_API, "Profile"):
            ns = apimeta.name_of(p)
            resp = kfam(req, "GET", f"/kfam/v1/bindings?namespace={ns}")
            contributors = [b["user"]["name"] for b in (resp.body or {}).get("bindings", [])]
            out.append([ns, contributors])
        return out

    def _contributors(req: Request, min_rv=None) -> List[str]:
        # contributor ↔ edit role (api_workgroup.ts:40-48); the owner's admin
        # binding is not a contributor. min_rv = read-your-writes barrier
        # after a mutation (KFAM's informer waits for the write's RV).
        url = f"/kfam/v1/bindings?namespace={req.params['ns']}&role=edit"
        if min_rv:
            url += f"&minResourceVersion={min_rv}"
        resp = kfam(req, "GET", url)
        return [b["user"]["name"] for b in (resp.body or {}).get("bindings", [])]

    @app.route("/api/workgroup/get-contributors/<ns>")
    def contributors(req: Request):
        return _contributors(req)

    @app.route("/api/workgroup/add-contributor/<ns>", methods=("POST",))
    def add_contributor(req: Request):
        body = req.json or {}
        resp = kfam(
            req,
            "POST",
            "/kfam/v1/bindings",
            {
                "user": {"kind": "User", "name": body.get("contributor", "")},
                "referredNamespace": req.params["ns"],
                "roleRef": {"kind": "ClusterRole", "name": "edit"},
            },
        )
        rv = (((resp.body or {}).get("binding") or {}).get("metadata") or {}).get(
            "resourceVersion"
        )
        return _contributors(req, min_rv=rv)

    @app.route("/api/workgroup/remove-contributor/<ns>", methods=("DELETE",))
    def remove_contributor(req: Request):
        body = req.json or {}
        resp = kfam(
            req,
            "DELETE",
            "/kfam/v1/bindings",
            {
                "user": {"kind": "User", "name": body.get("contributor", "")},
                "referredNamespace": req.params["ns"],
                "roleRef": {"kind": "ClusterRole", "name": "edit"},
            },
        )
        rv = (resp.body or {}).get("resourceVersion")
        return _contributors(req, min_rv=rv)

    install_apidocs(app)
    install_spa(app, load_ui("dashboard.html"), cfg)
    return app

def main() -> None:  # python -m kubeflow_tpu.services.dashboard
    import os

    from ..runtime.bootstrap import run_webapp
    from .kfam import make_kfam_app

    os.environ.setdefault("PORT", "8082")
    run_webapp(
        "centraldashboard",
        lambda client, auth: make_dashboard_app(client, make_kfam_app(client, auth), auth),
    )


if __name__ == "__main__":
    main()
