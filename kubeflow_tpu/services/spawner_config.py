"""Spawner (admin) configuration with value/readOnly semantics.

The reference drives its notebook-spawn form from an admin YAML
(crud-web-apps/jupyter/backend/apps/common/yaml/spawner_ui_config.yaml) where
every field carries ``value`` (default) and ``readOnly`` (users may not
override — enforced server-side at form.py:16-48). This module keeps those
semantics and replaces the GPU-era ``gpus.vendors`` block
(spawner_ui_config.yaml:141-154) with a first-class ``tpus`` section:
accelerator generations + slice topology picker, validated against the
platform topology catalog.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import yaml

from ..tpu.topology import ACCELERATORS, parse_topology
from ..web.http import HttpError

DEFAULT_CONFIG: Dict[str, Any] = {
    "spawnerFormDefaults": {
        "image": {
            "value": "kubeflow-tpu/jupyter-jax-tpu:latest",
            "options": [
                "kubeflow-tpu/jupyter-jax-tpu:latest",
                "kubeflow-tpu/jupyter-jax-tpu-full:latest",
                "kubeflow-tpu/jupyter-scipy:latest",
                "kubeflow-tpu/codeserver-jax-tpu:latest",
                "kubeflow-tpu/rstudio-tidyverse:latest",
            ],
            "readOnly": False,
        },
        "cpu": {"value": "4.0", "limitFactor": "1.2", "readOnly": False},
        "memory": {"value": "8.0Gi", "limitFactor": "1.2", "readOnly": False},
        "workspaceVolume": {
            "value": {
                "mount": "/home/jovyan",
                "newPvc": {
                    "metadata": {"name": "{notebook-name}-workspace"},
                    "spec": {
                        "resources": {"requests": {"storage": "10Gi"}},
                        "accessModes": ["ReadWriteOnce"],
                    },
                },
            },
            "readOnly": False,
        },
        "dataVolumes": {"value": [], "readOnly": False},
        # The TPU block (replaces `gpus`): generation + topology, validated
        # against the catalog; num=none means CPU-only notebook.
        "tpus": {
            "value": {"generation": "none", "topology": ""},
            "generations": sorted(ACCELERATORS),
            "readOnly": False,
        },
        "configurations": {"value": [], "readOnly": False},  # PodDefault labels
        "affinityConfig": {"value": "", "options": [], "readOnly": False},
        "tolerationGroup": {"value": "", "options": [], "readOnly": False},
        "shm": {"value": True, "readOnly": False},
    }
}


class SpawnerConfig:
    def __init__(self, config: Optional[Dict[str, Any]] = None):
        import copy

        # Deep-copy: instances are mutable (admins/tests override fields) and
        # must not alias the module-level defaults.
        self.config = copy.deepcopy(config) if config else copy.deepcopy(DEFAULT_CONFIG)

    @classmethod
    def from_yaml(cls, text: str) -> "SpawnerConfig":
        return cls(yaml.safe_load(text))

    @property
    def defaults(self) -> Dict[str, Any]:
        return self.config.get("spawnerFormDefaults", {})

    def form_value(self, form: Dict[str, Any], field: str) -> Any:
        """User value unless the field is admin-locked (form.py:16-48)."""
        cfg = self.defaults.get(field, {})
        if cfg.get("readOnly"):
            return cfg.get("value")
        if field in form:
            return form[field]
        return cfg.get("value")

    def tpu_of_form(self, form: Dict[str, Any]) -> Optional[Dict[str, str]]:
        """Validated {generation, topology} or None for CPU-only."""
        tpu = self.form_value(form, "tpus") or {}
        generation = tpu.get("generation", "none")
        if generation in ("none", "", None):
            return None
        topology = tpu.get("topology", "")
        try:
            parse_topology(generation, topology)
        except ValueError as e:
            raise HttpError(400, f"invalid TPU selection: {e}") from None
        return {"generation": generation, "topology": topology}
