from .poddefault import (  # noqa: F401
    PodDefaultConflict,
    admission_hook,
    filter_pod_defaults,
    mutate_pod,
)
