"""python -m kubeflow_tpu.webhook — the PodDefault admission webhook server.

Serves ``POST /apply-poddefault`` (AdmissionReview v1 in, AdmissionReview
with a base64 JSONPatch out — reference: admission-webhook/main.go:593-608).
PodDefaults are read from the apiserver (APISERVER_URL). TLS via
``--tls-cert-file``/``--tls-key-file`` (reference config.go:40-50); without
certs it serves plain HTTP (in-mesh deployments terminate TLS upstream).
"""

from __future__ import annotations

import argparse
import base64
import json
import logging
import os

from ..api import meta as apimeta
from ..apiserver.client import Client
from ..runtime.bootstrap import block_forever, connect
from ..web.http import App, Request
from .poddefault import mutate_pod


def make_webhook_app(client: Client, cluster_domain: str = "cluster.local") -> App:
    app = App("admission-webhook")

    @app.route("/healthz")
    def healthz(req: Request):
        return {"status": "ok"}

    @app.route("/apply-poddefault", methods=("POST",))
    def apply(req: Request):
        review = req.json or {}
        request = review.get("request") or {}
        pod = request.get("object") or {}
        ns = request.get("namespace") or apimeta.namespace_of(pod) or "default"
        poddefaults = client.list("kubeflow.org/v1alpha1", "PodDefault", ns)
        mutated = mutate_pod(pod, poddefaults, cluster_domain)
        response = {"uid": request.get("uid", ""), "allowed": True}
        if mutated is not pod and mutated != pod:
            ops = [
                {"op": "replace", "path": "/metadata", "value": mutated.get("metadata", {})},
                {"op": "replace", "path": "/spec", "value": mutated.get("spec", {})},
            ]
            response["patchType"] = "JSONPatch"
            response["patch"] = base64.b64encode(json.dumps(ops).encode()).decode()
        return {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "response": response,
        }

    return app


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--tls-cert-file", default=os.environ.get("TLS_CERT_FILE", ""))
    parser.add_argument("--tls-key-file", default=os.environ.get("TLS_KEY_FILE", ""))
    parser.add_argument("--port", type=int, default=int(os.environ.get("PORT", "4443")))
    args = parser.parse_args(argv)

    logging.basicConfig(level=os.environ.get("LOG_LEVEL", "INFO"))
    store = connect()
    app = make_webhook_app(Client(store), os.environ.get("CLUSTER_DOMAIN", "cluster.local"))
    ctx = None
    if args.tls_cert_file and args.tls_key_file:
        from ..web.tls import server_context

        # Certs load (and fail) before any socket accepts a connection.
        ctx = server_context(args.tls_cert_file, args.tls_key_file)
    server = app.serve(args.port, host="0.0.0.0", ssl_context=ctx)
    logging.getLogger("kubeflow_tpu.webhook").info(
        "webhook on :%d (%s)", server.port, "TLS" if ctx else "plain HTTP"
    )
    try:
        block_forever()
    finally:
        server.close()


if __name__ == "__main__":
    main()
