"""PodDefault mutating admission: the TPU injection plane.

Re-implements the reference admission webhook's merge/conflict semantics
(reference: components/admission-webhook/main.go — filterPodDefaults :69-94,
safeToApplyPodDefaultsOnPod :98-132, mergeEnv :152-187, mergeVolumeMounts
:202-253, mergeVolumes :257-296, mergeTolerations :300-339, mergeMap
:343-364, mutatePods :443-542) and extends ``PodDefaultSpec`` with a
first-class ``tpu`` block. Where the reference injected free-form GPU-era
env, a TPU PodDefault declares a slice once:

    spec:
      selector: {matchLabels: {tpu-workload: "true"}}
      tpu:
        generation: v5e
        topology: 4x8

and the webhook derives everything: ``google.com/tpu`` chip limits on the
workload container, GKE accelerator/topology nodeSelectors, and the
deterministic JAX coordinator/worker env (computable at admission time from
the pod's headless-service subdomain — SURVEY.md §7 "hard parts").

Conflict semantics are all-or-nothing per pod, as in the reference: if any
applicable PodDefault conflicts with the pod or another PodDefault, *no*
mutation happens and the pod is annotated with the rejection reason.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

from ..api import meta as apimeta
from ..api.meta import Resource
from ..runtime.metrics import METRICS
from ..tpu.env import jax_worker_env
from ..tpu.topology import SliceTopology, parse_topology

log = logging.getLogger("kubeflow_tpu.webhook")

ANNOTATION_PREFIX = "poddefault.admission.kubeflow.org"
EXCLUDE_ANNOTATION = f"{ANNOTATION_PREFIX}/exclude"
REJECT_ANNOTATION = f"{ANNOTATION_PREFIX}/rejected-reason"


class PodDefaultConflict(Exception):
    pass


def filter_pod_defaults(
    pod: Dict[str, Any], poddefaults: List[Dict[str, Any]]
) -> List[Dict[str, Any]]:
    """PodDefaults whose selector matches the pod's labels
    (reference: main.go:69-94)."""
    labels = apimeta.labels_of(pod)
    out = []
    for pd in poddefaults:
        selector = pd.get("spec", {}).get("selector")
        if apimeta.matches_selector(labels, selector):
            out.append(pd)
    return sorted(out, key=apimeta.name_of)


# --- merge primitives (conflict = same key, different value) ---------------


def merge_env(existing: List[Dict], incoming: List[Dict], where: str) -> List[Dict]:
    by_name = {e["name"]: e for e in existing}
    out = list(existing)
    for e in incoming:
        cur = by_name.get(e["name"])
        if cur is None:
            out.append(e)
            by_name[e["name"]] = e
        elif cur != e:
            raise PodDefaultConflict(f"{where}: conflicting env {e['name']!r}")
    return out


def merge_env_from(existing: List[Dict], incoming: List[Dict]) -> List[Dict]:
    out = list(existing)
    for e in incoming:
        if e not in out:
            out.append(e)
    return out


def merge_volume_mounts(existing: List[Dict], incoming: List[Dict], where: str) -> List[Dict]:
    out = list(existing)
    for vm in incoming:
        clash = next(
            (
                c
                for c in out
                if c["name"] == vm["name"] or c.get("mountPath") == vm.get("mountPath")
            ),
            None,
        )
        if clash is None:
            out.append(vm)
        elif clash != vm:
            raise PodDefaultConflict(
                f"{where}: conflicting volumeMount {vm['name']!r} at {vm.get('mountPath')!r}"
            )
    return out


def merge_volumes(existing: List[Dict], incoming: List[Dict], where: str) -> List[Dict]:
    by_name = {v["name"]: v for v in existing}
    out = list(existing)
    for v in incoming:
        cur = by_name.get(v["name"])
        if cur is None:
            out.append(v)
            by_name[v["name"]] = v
        elif cur != v:
            raise PodDefaultConflict(f"{where}: conflicting volume {v['name']!r}")
    return out


def merge_tolerations(existing: List[Dict], incoming: List[Dict], where: str) -> List[Dict]:
    by_key = {t.get("key"): t for t in existing}
    out = list(existing)
    for t in incoming:
        cur = by_key.get(t.get("key"))
        if cur is None:
            out.append(t)
            by_key[t.get("key")] = t
        elif cur != t:
            raise PodDefaultConflict(f"{where}: conflicting toleration {t.get('key')!r}")
    return out


def merge_map(existing: Dict[str, str], incoming: Dict[str, str], where: str) -> Dict[str, str]:
    out = dict(existing)
    for k, v in incoming.items():
        if k in out and out[k] != v:
            raise PodDefaultConflict(f"{where}: conflicting key {k!r} ({out[k]!r} != {v!r})")
        out[k] = v
    return out


# --- TPU block --------------------------------------------------------------


def tpu_spec_of(pd: Dict[str, Any]) -> Optional[SliceTopology]:
    tpu = pd.get("spec", {}).get("tpu")
    if not tpu:
        return None
    return parse_topology(tpu["generation"], tpu["topology"])


def _workload_name(pod: Dict[str, Any]) -> str:
    """Headless-service coordinate for coordinator DNS.

    StatefulSet pods carry ``spec.subdomain`` (= governing service name) and a
    controller ownerReference; either names the workload. Falls back to the
    pod's own name for bare pods (single-host only).
    """
    subdomain = pod.get("spec", {}).get("subdomain")
    if subdomain:
        return subdomain
    ref = apimeta.controller_owner_of(pod)
    if ref is not None:
        return ref["name"]
    return apimeta.name_of(pod)


def _tpu_mutations(
    pd: Dict[str, Any], topo: SliceTopology, pod: Dict[str, Any], cluster_domain: str
) -> Tuple[List[Dict], Dict[str, str], Dict[str, str], List[Dict]]:
    """(env, resource limits, nodeSelector, tolerations) for the TPU block."""
    tpu = pd["spec"]["tpu"]
    name = _workload_name(pod)
    ns = apimeta.namespace_of(pod) or "default"
    env = jax_worker_env(
        topo, name, ns, cluster_domain=tpu.get("clusterDomain", cluster_domain), extra=tpu.get("env")
    )
    selector = topo.node_selector()
    tolerations = [{"key": "google.com/tpu", "operator": "Exists", "effect": "NoSchedule"}]
    return env, topo.resource_limits(), selector, tolerations


def _target_containers(pd: Dict[str, Any], pod_spec: Dict[str, Any]) -> List[Dict]:
    """TPU limits go on the workload container: named by ``spec.tpu.container``
    or the first container (the reference's JWA sets GPU limits on the single
    notebook container — form.py:262-287)."""
    containers = pod_spec.get("containers") or []
    want = pd.get("spec", {}).get("tpu", {}).get("container")
    if want:
        matched = [c for c in containers if c.get("name") == want]
        if not matched:
            raise PodDefaultConflict(f"tpu.container {want!r} not found in pod")
        return matched
    return containers[:1]


def apply_pod_defaults(
    pod: Dict[str, Any], poddefaults: List[Dict[str, Any]], cluster_domain: str = "cluster.local"
) -> Dict[str, Any]:
    """Apply all PodDefaults onto a deep copy of pod; raises PodDefaultConflict."""
    pod = apimeta.deepcopy(pod)
    spec = pod.setdefault("spec", {})
    md = pod.setdefault("metadata", {})
    for pd in poddefaults:
        pd_name = apimeta.name_of(pd)
        where = f"poddefault/{pd_name}"
        pspec = pd.get("spec", {})

        for container in spec.get("containers", []) or []:
            if pspec.get("env"):
                container["env"] = merge_env(container.get("env") or [], pspec["env"], where)
            if pspec.get("envFrom"):
                container["envFrom"] = merge_env_from(container.get("envFrom") or [], pspec["envFrom"])
            if pspec.get("volumeMounts"):
                container["volumeMounts"] = merge_volume_mounts(
                    container.get("volumeMounts") or [], pspec["volumeMounts"], where
                )
        if pspec.get("volumes"):
            spec["volumes"] = merge_volumes(spec.get("volumes") or [], pspec["volumes"], where)
        if pspec.get("tolerations"):
            spec["tolerations"] = merge_tolerations(spec.get("tolerations") or [], pspec["tolerations"], where)
        if pspec.get("labels"):
            md["labels"] = merge_map(md.get("labels") or {}, pspec["labels"], where)
        if pspec.get("annotations"):
            md["annotations"] = merge_map(md.get("annotations") or {}, pspec["annotations"], where)

        topo = tpu_spec_of(pd)
        if topo is not None:
            env, limits, node_selector, tolerations = _tpu_mutations(pd, topo, pod, cluster_domain)
            for container in _target_containers(pd, spec):
                container["env"] = merge_env(container.get("env") or [], env, where)
                resources = container.setdefault("resources", {})
                resources["limits"] = merge_map(resources.get("limits") or {}, limits, where)
                resources["requests"] = merge_map(resources.get("requests") or {}, limits, where)
            spec["nodeSelector"] = merge_map(spec.get("nodeSelector") or {}, node_selector, where)
            spec["tolerations"] = merge_tolerations(spec.get("tolerations") or [], tolerations, where)

        md.setdefault("annotations", {})[f"{ANNOTATION_PREFIX}/poddefault-{pd_name}"] = str(
            pd["metadata"].get("resourceVersion", "0")
        )
    return pod


def mutate_pod(
    pod: Dict[str, Any], poddefaults: List[Dict[str, Any]], cluster_domain: str = "cluster.local"
) -> Dict[str, Any]:
    """Full admission path: exclusion check, selector filter, all-or-nothing
    apply. Never rejects the pod — on conflict the pod passes through
    unmutated with the reason annotated (reference behavior:
    main.go:500-517 logs and allows)."""
    annotations = apimeta.annotations_of(pod)
    if annotations.get(EXCLUDE_ANNOTATION) == "true":
        return pod
    matching = filter_pod_defaults(pod, poddefaults)
    if not matching:
        return pod
    try:
        mutated = apply_pod_defaults(pod, matching, cluster_domain)
        METRICS.counter("poddefault_apply_total", result="success").inc()
        return mutated
    except (PodDefaultConflict, ValueError, KeyError, TypeError, AttributeError) as e:
        # A malformed PodDefault (bad tpu block, bad topology string) must not
        # make pod CREATE fail — same pass-through-and-annotate contract.
        result = "conflict" if isinstance(e, PodDefaultConflict) else "error"
        METRICS.counter("poddefault_apply_total", result=result).inc()
        log.warning("pod %s/%s: %s", apimeta.namespace_of(pod), apimeta.name_of(pod), e)
        pod = apimeta.deepcopy(pod)
        pod.setdefault("metadata", {}).setdefault("annotations", {})[REJECT_ANNOTATION] = str(e)
        return pod


def admission_hook(client, cluster_domain: str = "cluster.local") -> Any:
    """Store admission hook: mutate pods on CREATE using the PodDefaults in
    the pod's namespace (the in-process equivalent of registering the webhook
    with the API server)."""

    def hook(op: str, res: Resource, obj: Dict[str, Any]) -> Dict[str, Any]:
        if op != "CREATE" or res.kind != "Pod":
            return obj
        ns = apimeta.namespace_of(obj)
        poddefaults = client.list("kubeflow.org/v1alpha1", "PodDefault", namespace=ns)
        return mutate_pod(obj, poddefaults, cluster_domain)

    return hook
