"""Image-classification training task (ResNet/MNIST): one jitted step.

TPU-first structure:
- the whole step (fwd + bwd + BatchNorm stat update + optimizer) is ONE jit
  with donated state — no host round-trips inside the training loop,
- batch sharded over the mesh batch axes, params placed by LogicalRules
  (replicated / fsdp / tp) — XLA inserts the gradient reduce/all-gathers,
- loss in f32 on bf16 activations.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import flax
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding

from kubeflow_tpu.parallel.mesh import batch_spec, replicated
from kubeflow_tpu.parallel.sharding import LogicalRules, REPLICATED_RULES, shard_pytree


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    rng: jax.Array  # base key; per-step dropout key = fold_in(rng, step)


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


@dataclass
class ClassifierTask:
    """Bundles a flax image model with optimizer + mesh placement.

    ``model.apply`` must accept ``(variables, images, train=...)`` and use
    BatchNorm collection ``batch_stats`` (absent is fine — MnistCNN).
    """

    model: Any
    optimizer: optax.GradientTransformation
    mesh: Optional[Mesh] = None
    rules: LogicalRules = REPLICATED_RULES

    # -- init ----------------------------------------------------------------
    def init(self, rng: jax.Array, sample_batch: jax.Array) -> TrainState:
        variables = self.model.init(rng, sample_batch, train=True)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        state = TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=batch_stats,
            opt_state=self.optimizer.init(params),
            rng=jax.random.fold_in(rng, 1),
        )
        if self.mesh is not None:
            state = jax.device_put(state, self.state_shardings(state))
        return state

    def state_shardings(self, state: TrainState) -> TrainState:
        assert self.mesh is not None
        param_sh = shard_pytree(state.params, self.mesh, self.rules)
        rep = replicated(self.mesh)

        # Optimizer moments (sgd trace, adam mu/nu) are params-shaped pytrees
        # inside optax state; give them the params' shardings (the ZeRO-3
        # point: moments shard wherever params do), everything else replicates.
        params_struct = jax.tree_util.tree_structure(state.params)

        def place(subtree):
            if jax.tree_util.tree_structure(subtree) == params_struct:
                return param_sh
            return jax.tree_util.tree_map(lambda _: rep, subtree)

        opt_sh = jax.tree_util.tree_map(
            place, state.opt_state, is_leaf=lambda x: jax.tree_util.tree_structure(x) == params_struct
        )
        return TrainState(
            step=rep,
            params=param_sh,
            batch_stats=jax.tree_util.tree_map(lambda _: rep, state.batch_stats),
            opt_state=opt_sh,
            rng=rep,
        )

    def batch_sharding(self, extra_dims: int) -> Optional[NamedSharding]:
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, batch_spec(extra_dims))

    # -- steps ---------------------------------------------------------------
    def make_train_step(self) -> Callable[[TrainState, jax.Array, jax.Array], Tuple[TrainState, Dict[str, jax.Array]]]:
        model, optimizer = self.model, self.optimizer

        def train_step(state: TrainState, images: jax.Array, labels: jax.Array):
            def loss_fn(params):
                variables = {"params": params}
                if state.batch_stats:
                    variables["batch_stats"] = state.batch_stats
                out = model.apply(
                    variables,
                    images,
                    train=True,
                    mutable=["batch_stats"] if state.batch_stats else [],
                    rngs={"dropout": jax.random.fold_in(state.rng, state.step)},
                )
                logits, mutated = out if isinstance(out, tuple) else (out, {})
                loss = cross_entropy_loss(logits, labels)
                return loss, (logits, mutated.get("batch_stats", state.batch_stats))

            (loss, (logits, new_stats)), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
            updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            metrics = {
                "loss": loss,
                "accuracy": jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32)),
            }
            return (
                TrainState(
                    step=state.step + 1,
                    params=new_params,
                    batch_stats=new_stats,
                    opt_state=new_opt,
                    rng=state.rng,
                ),
                metrics,
            )

        return jax.jit(train_step, donate_argnums=(0,))

    def make_eval_step(self) -> Callable[[TrainState, jax.Array], jax.Array]:
        model = self.model

        def eval_step(state: TrainState, images: jax.Array) -> jax.Array:
            variables = {"params": state.params}
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
            return model.apply(variables, images, train=False)

        return jax.jit(eval_step)


def sgd_momentum(
    lr: float = 0.1,
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
    warmup_steps: int = 0,
    total_steps: Optional[int] = None,
) -> optax.GradientTransformation:
    """The standard ResNet recipe: SGD+momentum, cosine decay, warmup."""
    if total_steps:
        schedule = optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=lr, warmup_steps=max(warmup_steps, 1),
            decay_steps=total_steps,
        )
    else:
        schedule = lambda _: lr
    return optax.chain(
        optax.add_decayed_weights(weight_decay),
        optax.sgd(learning_rate=schedule, momentum=momentum, nesterov=True),
    )
