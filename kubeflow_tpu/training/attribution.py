"""Per-module training cost attribution: the report that turns "ResNet is
stuck at 30% MFU" into "these blocks, for these reasons".

The bench has timers (``StepClock``) and a whole-step FLOPs numerator
(``compiled_with_cost``); what it lacked was *attribution* — which modules
spend the step's time, whether each is compute- or HBM-bound, and how much
of the measured wall clock the fused fast paths actually cover. This
module walks a model's blocks, prices each one with XLA cost analysis
(FLOPs + bytes accessed) **and** the compiler's ``memory_analysis``
(argument/output/temp bytes), classifies every module against the
accelerator's roofline (peak bf16 FLOP/s vs peak HBM bandwidth from
``tpu/topology.py``), and decomposes a ``StepClock``-measured step into
data-wait / fused-compute / un-fused-compute / other fractions with a
top-N time-sink table.

Pricing ground rules (same as bench.py's MFU numerator):

- every module is priced in its UNFUSED form — XLA credits zero FLOPs
  inside a Pallas custom call, so pricing the fused executable would erase
  the very work being attributed; fused eligibility is classified
  separately via the model's own predicate,
- forward cost is scaled by ``TRAIN_STEP_FACTOR`` (3x: fwd + ~2x bwd,
  2 flops/MAC convention) so module shares line up with the measured
  *train* step,
- pricing lowers from ``ShapeDtypeStruct``s, so walking ResNet-50 never
  allocates a parameter.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from kubeflow_tpu.runtime.metrics import METRICS
from kubeflow_tpu.training.flops import (
    detect_generation,
    memory_stats,
    peak_flops_per_chip,
    peak_hbm_bandwidth,
)

#: train step ≈ forward + backward ≈ 3x forward FLOPs (the same convention
#: as bench.py's analytic fallback; optimizer update is O(params), noise)
TRAIN_STEP_FACTOR = 3.0


@dataclass
class ModuleCost:
    """One priced module: compiler-measured cost + roofline verdict."""

    name: str
    kind: str                 # "stem" | "bottleneck" | "gpt_block" | "loss_head" | ...
    detail: str = ""          # "strided+projection", "projection", "identity", ...
    fused: bool = False       # routed through a Pallas/fused fast path at runtime
    count: int = 1            # identical applications priced once (scanned blocks)
    flops: float = 0.0        # train-step FLOPs, all applications
    hbm_bytes: float = 0.0    # train-step bytes accessed, all applications
    peak_hbm_bytes: int = 0   # resident bytes of ONE application (memory_analysis)
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    intensity: float = 0.0    # flops / hbm_bytes (arithmetic intensity)
    verdict: str = "unknown"  # "compute-bound" | "hbm-bound"
    est_seconds: float = 0.0  # roofline time: max(flops/peak, bytes/bandwidth)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "kind": self.kind, "detail": self.detail,
            "fused": self.fused, "count": self.count,
            "flops": self.flops, "hbm_bytes": self.hbm_bytes,
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "intensity": round(self.intensity, 2), "verdict": self.verdict,
            "est_seconds": self.est_seconds,
        }


def _cost_dict(compiled: Any) -> Dict[str, float]:
    analysis = compiled.cost_analysis()
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    return dict(analysis or {})


def price_callable(
    fn: Any,
    *args: Any,
    name: str,
    kind: str = "module",
    detail: str = "",
    fused: bool = False,
    count: int = 1,
    generation: str = "v5e",
    train_factor: float = TRAIN_STEP_FACTOR,
) -> ModuleCost:
    """Compile ``fn(*args)`` (arrays or ``ShapeDtypeStruct``s — nothing is
    executed) and price it: cost-analysis FLOPs/bytes scaled by ``count``
    applications and ``train_factor``, memory_analysis footprint, roofline
    verdict against ``generation``'s peak specs."""
    compiled = jax.jit(fn).lower(*args).compile()
    cost = _cost_dict(compiled)
    mem = memory_stats(compiled) or {}
    flops1 = float(cost.get("flops", 0.0) or 0.0)
    bytes1 = float(cost.get("bytes accessed", 0.0) or 0.0)
    if bytes1 <= 0.0:  # backend reports no traffic: floor at the footprint
        bytes1 = float(mem.get("argument_bytes", 0) + mem.get("output_bytes", 0)
                       + mem.get("temp_bytes", 0))
    flops = flops1 * count * train_factor
    hbm = bytes1 * count * train_factor
    peak_f = peak_flops_per_chip(generation)
    peak_b = peak_hbm_bandwidth(generation)
    intensity = flops / hbm if hbm > 0 else float("inf")
    balance = peak_f / peak_b if peak_b > 0 else float("inf")
    verdict = "compute-bound" if intensity >= balance else "hbm-bound"
    est = max(flops / peak_f if peak_f > 0 else 0.0,
              hbm / peak_b if peak_b > 0 else 0.0)
    return ModuleCost(
        name=name, kind=kind, detail=detail, fused=fused, count=count,
        flops=flops, hbm_bytes=hbm,
        peak_hbm_bytes=int(mem.get("peak_hbm_bytes", 0)),
        argument_bytes=int(mem.get("argument_bytes", 0)),
        output_bytes=int(mem.get("output_bytes", 0)),
        temp_bytes=int(mem.get("temp_bytes", 0)),
        intensity=intensity, verdict=verdict, est_seconds=est,
    )


# -- ResNet walk --------------------------------------------------------------

def attribute_resnet(
    batch: int = 256,
    image: int = 224,
    num_classes: int = 1000,
    stem: str = "conv7x7",
    fused_blocks: bool = True,
    generation: Optional[str] = None,
    stage_sizes: tuple = (3, 4, 6, 3),
    num_filters: int = 64,
) -> List[ModuleCost]:
    """Price every module of a ``ResNet(stage_sizes, BottleneckBlock)``:
    stem, each bottleneck (classified fused vs un-fused by the block's own
    ``_fusable``/``_fusable_transition`` predicates — the truth, not the
    docs; transition heads count as fused since the ``fused_transition``
    kernel landed), and the pooled classifier head. Defaults mirror
    ``ResNet50`` and the bench shape."""
    import flax.linen as nn

    from kubeflow_tpu.models.resnet import BottleneckBlock, space_to_depth

    gen = generation or detect_generation()
    conv = partial(nn.Conv, use_bias=False, dtype=jnp.bfloat16,
                   param_dtype=jnp.float32)
    norm = partial(nn.BatchNorm, use_running_average=True, momentum=0.9,
                   epsilon=1e-5, dtype=jnp.bfloat16, param_dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    costs: List[ModuleCost] = []

    # stem (+ the pool): priced as one module, f32 image in, bf16 out
    def stem_fn(x):
        x = x.astype(jnp.bfloat16)
        if stem == "s2d" and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
            x = space_to_depth(x, 2)
            x = _stateless_conv(x, num_filters, (4, 4), (1, 1),
                                [(2, 1), (2, 1)])
        else:
            x = _stateless_conv(x, num_filters, (7, 7), (2, 2),
                                [(3, 3), (3, 3)])
        x = nn.relu(x)
        return nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

    img = jax.ShapeDtypeStruct((batch, image, image, 3), jnp.float32)
    costs.append(price_callable(stem_fn, img, name="stem", kind="stem",
                                detail=stem, generation=gen))

    size, cin = image // 4, num_filters
    for i, blocks in enumerate(stage_sizes):
        filters = num_filters * 2 ** i
        for j in range(blocks):
            strides = (2, 2) if i > 0 and j == 0 else (1, 1)
            x = jax.ShapeDtypeStruct((batch, size, size, cin), jnp.bfloat16)
            block = BottleneckBlock(filters=filters, strides=strides,
                                    conv=conv, norm=norm, act=nn.relu,
                                    fused=False)
            fused_here = bool(fused_blocks) and (
                block._fusable(x) or block._fusable_transition(x))
            if strides != (1, 1) and cin != filters * 4:
                detail = "strided+projection"
            elif cin != filters * 4:
                detail = "projection"
            elif strides != (1, 1):
                detail = "strided"
            else:
                detail = "identity"
            if fused_here and not block._fusable(x):
                detail += "/transition"
            variables = jax.eval_shape(block.init, rng, x)
            costs.append(price_callable(
                lambda v, a, b=block: b.apply(v, a), variables, x,
                name=f"stage{i + 1}_block{j + 1}", kind="bottleneck",
                detail=detail, fused=fused_here, generation=gen))
            if strides == (2, 2):
                size //= 2
            cin = filters * 4

    def head_fn(w, x):
        pooled = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
        return pooled @ w

    feat = jax.ShapeDtypeStruct((batch, size, size, cin), jnp.bfloat16)
    w = jax.ShapeDtypeStruct((cin, num_classes), jnp.float32)
    costs.append(price_callable(head_fn, w, feat, name="classifier_head",
                                kind="head", generation=gen))
    return costs


def _stateless_conv(x, features, kernel, strides, padding):
    """Conv priced without a param tree: lax.conv on a zeros kernel struct
    would drop the FLOPs, so materialize a constant kernel of the right
    shape (constants fold into the executable; cost analysis still counts
    the conv)."""
    import jax.lax as lax

    k = jnp.zeros((*kernel, x.shape[-1], features), x.dtype)
    return lax.conv_general_dilated(
        x, k, window_strides=strides, padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


# -- GPT walk -----------------------------------------------------------------

def attribute_gpt(
    cfg: Any,
    batch: int = 8,
    seq: Optional[int] = None,
    fused_loss: bool = True,
    generation: Optional[str] = None,
) -> List[ModuleCost]:
    """Price a ``GptConfig`` stack: one transformer block (priced once,
    counted ``n_layers`` times — the scanned stack runs the same program
    per layer) plus the logits/loss head. The loss head is priced in its
    materialized form (the work the blockwise fused loss restructures);
    ``fused_loss`` only flips its fused classification."""
    from kubeflow_tpu.models.gpt import GptBlock, causal_lm_loss

    gen = generation or detect_generation()
    L = seq or cfg.max_seq
    rng = jax.random.PRNGKey(0)
    x = jax.ShapeDtypeStruct((batch, L, cfg.d_model), jnp.bfloat16)
    positions = jax.ShapeDtypeStruct((L,), jnp.int32)
    block = GptBlock(cfg)
    variables = jax.eval_shape(block.init, rng, x, positions)
    costs = [price_callable(
        lambda v, a, p, b=block: b.apply(v, a, p), variables, x, positions,
        name="gpt_block", kind="gpt_block", count=cfg.n_layers,
        detail=f"x{cfg.n_layers}", generation=gen)]

    def loss_head(h, emb, ids):
        logits = h.astype(jnp.float32) @ emb.T.astype(jnp.float32)
        return causal_lm_loss(logits, ids)

    emb = jax.ShapeDtypeStruct((cfg.vocab_size, cfg.d_model), jnp.float32)
    ids = jax.ShapeDtypeStruct((batch, L), jnp.int32)
    costs.append(price_callable(
        loss_head, x, emb, ids, name="loss_head", kind="loss_head",
        fused=fused_loss, detail="blockwise" if fused_loss else "materialized",
        generation=gen))
    return costs


# -- step decomposition + report ----------------------------------------------

@dataclass
class AttributionReport:
    """Module table + the measured step decomposed into fractions."""

    generation: str
    modules: List[ModuleCost]
    step_seconds: float                      # measured per-step wall clock
    measured: Dict[str, float] = field(default_factory=dict)   # per-step phases
    fractions: Dict[str, float] = field(default_factory=dict)  # of step_seconds

    def top_sinks(self, n: int = 5, fused: Optional[bool] = None) -> List[ModuleCost]:
        mods = [m for m in self.modules if fused is None or m.fused == fused]
        return sorted(mods, key=lambda m: m.est_seconds, reverse=True)[:n]

    def coverage(self, kind: str = "bottleneck") -> Dict[str, int]:
        """Fused-kernel coverage over modules of ``kind`` (the acceptance
        metric: 16/16 bottlenecks at 224x224 since the transition kernel)."""
        of_kind = [m for m in self.modules if m.kind == kind]
        return {"fused": sum(1 for m in of_kind if m.fused),
                "total": len(of_kind)}

    def to_dict(self, top_n: int = 5) -> Dict[str, Any]:
        return {
            "generation": self.generation,
            "step_seconds": self.step_seconds,
            "fractions": {k: round(v, 4) for k, v in self.fractions.items()},
            "modules": len(self.modules),
            "fused_modules": sum(1 for m in self.modules if m.fused),
            "coverage": self.coverage(),
            "top_unfused_sinks": [m.to_dict() for m in
                                  self.top_sinks(top_n, fused=False)],
            "top_fused_sinks": [m.to_dict() for m in
                                self.top_sinks(top_n, fused=True)],
        }

    def render(self, top_n: int = 10) -> str:
        lines = [
            f"# Attribution report ({self.generation}: "
            f"{peak_flops_per_chip(self.generation) / 1e12:.0f} TF/s peak, "
            f"{peak_hbm_bandwidth(self.generation) / 1e9:.0f} GB/s HBM)",
            f"measured step: {self.step_seconds * 1e3:.3f} ms  "
            + "  ".join(f"{k}={v * 1e3:.3f}ms" for k, v in self.measured.items()),
            "fractions: " + "  ".join(f"{k}={v:.1%}"
                                      for k, v in self.fractions.items()),
            "fused coverage: {fused}/{total} bottlenecks".format(
                **self.coverage()),
            "",
            f"{'module':<22}{'kind':<12}{'detail':<31}{'fused':<7}"
            f"{'GFLOPs':>9}{'HBM MiB':>10}{'int.':>8}  {'verdict':<14}{'est ms':>8}",
        ]
        for m in sorted(self.modules, key=lambda m: m.est_seconds, reverse=True)[:top_n]:
            lines.append(
                f"{m.name:<22}{m.kind:<12}{m.detail:<31}"
                f"{'yes' if m.fused else 'NO':<7}"
                f"{m.flops / 1e9:>9.2f}{m.hbm_bytes / 2**20:>10.1f}"
                f"{m.intensity:>8.1f}  {m.verdict:<14}{m.est_seconds * 1e3:>8.3f}")
        return "\n".join(lines)


def attribution_report(
    modules: List[ModuleCost],
    clock: Optional[Any] = None,
    steps_per_record: int = 1,
    step_seconds: Optional[float] = None,
    generation: Optional[str] = None,
) -> AttributionReport:
    """Decompose the measured step into data-wait / fused-compute /
    un-fused-compute / other. Phases come from ``clock.summary()`` (one
    clock record = ``steps_per_record`` real steps — bench windows); the
    measured ``compute`` phase is split between fused and un-fused module
    groups in proportion to their roofline estimates, and ``other``
    absorbs the remainder (fetch + host), so the fractions sum to the
    measured step exactly."""
    gen = generation or detect_generation()
    if clock is not None:
        s = clock.summary()
        spr = max(1, steps_per_record)
        measured = {k: s.get(k, 0.0) / spr
                    for k in ("data_wait", "compute", "fetch", "other")}
        total = s.get("total", 0.0) / spr
    else:
        total = float(step_seconds or 0.0)
        measured = {"data_wait": 0.0, "compute": total, "fetch": 0.0,
                    "other": 0.0}
    est_fused = sum(m.est_seconds for m in modules if m.fused)
    est_unfused = sum(m.est_seconds for m in modules if not m.fused)
    compute = measured.get("compute", 0.0)
    if est_fused + est_unfused > 0:
        fused_c = compute * est_fused / (est_fused + est_unfused)
    else:
        fused_c = 0.0
    unfused_c = compute - fused_c
    data_wait = measured.get("data_wait", 0.0)
    other = max(0.0, total - data_wait - compute)
    fractions = {}
    if total > 0:
        fractions = {
            "data_wait": data_wait / total,
            "fused_compute": fused_c / total,
            "unfused_compute": unfused_c / total,
            "other": other / total,
        }
    return AttributionReport(generation=gen, modules=modules,
                             step_seconds=total, measured=measured,
                             fractions=fractions)


def record_step_peak_hbm(mem: Optional[Dict[str, int]],
                         metrics: Optional[Any] = None) -> Optional[int]:
    """Publish a compiled train step's ``memory_analysis`` footprint as
    gauges: ``training_step_peak_hbm_bytes`` plus per-component
    ``training_step_hbm_bytes{component=...}``. Takes the dict from
    ``training.flops.memory_stats`` (None-safe: backends without the
    analysis skip silently). Returns the peak bytes recorded."""
    if not mem:
        return None
    reg = metrics if metrics is not None else METRICS.namespace("training")
    peak = int(mem.get("peak_hbm_bytes", 0))
    reg.gauge("step_peak_hbm_bytes").set(peak)
    for key in ("argument_bytes", "output_bytes", "temp_bytes"):
        if key in mem:
            reg.gauge("step_hbm_bytes",
                      component=key.replace("_bytes", "")).set(mem[key])
    return peak
