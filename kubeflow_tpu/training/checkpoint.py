"""Checkpoint/resume: mesh-aware, crash-safe training state persistence.

The reference has no training checkpoints (platform 'resume' = stop/start
annotations + PVC-backed home dirs — SURVEY §5); for the TPU build this is
the workload half of elastic recovery: after the scheduler drains a
preempted gang (docs/ELASTICITY.md), the training process resumes from the
latest checkpoint on the PVC — possibly on a different slice topology.

Self-contained format (no external checkpoint library), built for the
failure the elastic path must survive: a process killed -9 in the middle
of a save.

- **Atomic commit** — every save writes leaf ``.npy`` files plus a
  ``manifest.json`` into a temp dir, fsyncs the manifest and the dir, then
  ``os.rename``s it to ``step_<N>`` and fsyncs the parent. A checkpoint
  either exists completely or not at all; a crash mid-save leaves only an
  invisible ``_tmp.*`` dir (garbage-collected on the next open).
- **Corruption skip-over** — ``latest_step``/``restore`` validate the
  manifest and every leaf file (size + crc32) and silently skip
  partial/corrupt step dirs instead of raising; only when NO complete
  checkpoint exists does ``restore`` raise ``FileNotFoundError``.
- **Bounded retention** — ``max_to_keep`` deletes the oldest complete
  checkpoints after each save and never touches the newest complete one.
- **Cross-topology restore** — ``restore`` places every leaf onto the
  sharding of the caller's ``state_template``, so a checkpoint written on
  one mesh factorization restores onto another (the reshard happens at
  load). ``restore_numpy`` returns plain numpy + the saved ``meta`` dict
  for callers (the ElasticTrainer) that decide the target factorization
  AFTER reading the checkpoint.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import uuid
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..runtime.metrics import METRICS

_STEP_PREFIX = "step_"
_TMP_PREFIX = "_tmp."
_MANIFEST = "manifest.json"
_FORMAT = 1

#: urgent drain saves must land inside the preemption grace window —
#: sub-second buckets matter as much as the multi-second tail
SAVE_BUCKETS = (0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


class CorruptCheckpoint(Exception):
    """A step dir failed validation (internal — callers see skip-over)."""


def _path_tokens(path) -> List[List[Any]]:
    """JSON-able identity of one pytree leaf path (dict/seq/attr keys)."""
    toks: List[List[Any]] = []
    for p in path:
        if isinstance(p, jax.tree_util.DictKey):
            toks.append(["d", p.key])
        elif isinstance(p, jax.tree_util.SequenceKey):
            toks.append(["s", p.idx])
        elif isinstance(p, jax.tree_util.GetAttrKey):
            toks.append(["a", p.name])
        elif isinstance(p, jax.tree_util.FlattenedIndexKey):
            toks.append(["i", p.key])
        else:  # pragma: no cover - future key types degrade to strings
            toks.append(["x", str(p)])
    return toks


def _leaf_to_numpy(leaf: Any) -> np.ndarray:
    if isinstance(leaf, jax.Array):
        return np.asarray(jax.device_get(leaf))
    return np.asarray(leaf)


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class Checkpointer:
    """Save/restore a training-state pytree under a step-indexed directory.

    Usage (inside a training loop):
        ckpt = Checkpointer("/home/jovyan/ckpt", max_to_keep=3)
        start = ckpt.latest_step()
        state = ckpt.restore(state) if start is not None else state
        for step in range((start or -1) + 1, total):
            state = train_step(state, ...)
            ckpt.maybe_save(step, state, every=100)
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max(1, int(max_to_keep))
        os.makedirs(self.directory, exist_ok=True)
        self._lock = threading.Lock()
        # a previous process killed mid-save leaves _tmp.* droppings; they
        # were never renamed, hence never visible — reclaim the space
        for entry in os.listdir(self.directory):
            if entry.startswith(_TMP_PREFIX):
                shutil.rmtree(os.path.join(self.directory, entry), ignore_errors=True)

    # -- introspection -------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        """Newest step with a COMPLETE checkpoint (partial dirs skipped)."""
        steps = self.all_steps()
        return steps[-1] if steps else None

    def all_steps(self) -> List[int]:
        """Sorted steps whose checkpoints validate (manifest + leaf sizes)."""
        return [s for s in self._candidate_steps() if self._is_complete(s)]

    def read_meta(self, step: Optional[int] = None) -> Dict[str, Any]:
        """The ``meta`` dict stored alongside a checkpoint ({} if absent)."""
        manifest = self._load_manifest(self._resolve_step(step))
        return manifest.get("meta") or {}

    # -- save/restore --------------------------------------------------------
    def save(
        self,
        step: int,
        state: Any,
        wait: bool = True,  # kept for API compat; saves are synchronous
        meta: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Atomically persist ``state`` (any pytree) plus a JSON ``meta``
        dict (mesh factorization, data cursor, ...). Thread-safe: an urgent
        drain save serializes against an in-flight periodic save."""
        del wait
        t0 = time.perf_counter()
        with self._lock:
            self._save_locked(int(step), state, meta)
        METRICS.histogram("checkpoint_save_seconds", buckets=SAVE_BUCKETS).observe(
            time.perf_counter() - t0
        )

    def _save_locked(self, step: int, state: Any, meta: Optional[Dict[str, Any]]) -> None:
        leaves = jax.tree_util.tree_flatten_with_path(state)[0]
        tmp = os.path.join(self.directory, f"{_TMP_PREFIX}{step}.{uuid.uuid4().hex[:8]}")
        os.makedirs(tmp)
        entries = []
        for i, (path, leaf) in enumerate(leaves):
            arr = _leaf_to_numpy(leaf)
            fname = f"leaf_{i:05d}.npy"
            with open(os.path.join(tmp, fname), "wb") as f:
                np.save(f, arr, allow_pickle=False)
                f.flush()
                os.fsync(f.fileno())
            entries.append(
                {
                    "path": _path_tokens(path),
                    "key": jax.tree_util.keystr(path),
                    "file": fname,
                    "shape": list(arr.shape),
                    "dtype": str(arr.dtype),
                    "bytes": os.path.getsize(os.path.join(tmp, fname)),
                    "crc32": zlib.crc32(np.ascontiguousarray(arr).tobytes()),
                }
            )
        manifest = {"format": _FORMAT, "step": step, "meta": meta or {}, "leaves": entries}
        mpath = os.path.join(tmp, _MANIFEST)
        with open(mpath, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(tmp)
        final = self._step_dir(step)
        if os.path.exists(final):  # re-save of an existing step replaces it
            shutil.rmtree(final, ignore_errors=True)
        os.rename(tmp, final)
        _fsync_dir(self.directory)
        self._gc_locked(newest=step)

    def maybe_save(
        self,
        step: int,
        state: Any,
        every: int,
        wait: bool = False,
        meta: Optional[Dict[str, Any]] = None,
    ) -> bool:
        if every <= 0 or step % every != 0:
            return False
        self.save(step, state, wait=wait, meta=meta)
        return True

    def restore(self, state_template: Any, step: Optional[int] = None) -> Any:
        """Restore into the shardings/dtypes of ``state_template`` — arrays
        land directly on the template's mesh (cross-topology resume). With
        ``step=None``, walks newest→oldest past corrupt checkpoints."""
        t0 = time.perf_counter()
        for chosen in self._restore_order(step):
            try:
                arrays, _meta = self._load_arrays(chosen)
            except CorruptCheckpoint:
                continue
            paths, treedef = jax.tree_util.tree_flatten_with_path(state_template)
            out = []
            for path, leaf in paths:
                key = jax.tree_util.keystr(path)
                if key not in arrays:
                    # structure drifted from the template — unusable, but
                    # an older checkpoint may still match
                    break
                out.append(_place_like(arrays[key], leaf))
            else:
                self._observe_restore(t0)
                return jax.tree_util.tree_unflatten(treedef, out)
        raise FileNotFoundError(f"no usable checkpoint under {self.directory}")

    def restore_numpy(
        self, step: Optional[int] = None
    ) -> Tuple[Any, Dict[str, Any]]:
        """(pytree of numpy arrays, meta) without a template — only for
        checkpoints whose structure is nested dicts/lists (the canonical
        elastic format). Walks newest→oldest past corrupt checkpoints."""
        t0 = time.perf_counter()
        for chosen in self._restore_order(step):
            try:
                manifest = self._load_manifest(chosen)
                arrays, meta = self._load_arrays(chosen, manifest)
            except CorruptCheckpoint:
                continue
            tree: Any = None
            for entry in manifest["leaves"]:
                tree = _insert_by_tokens(tree, entry["path"], arrays[entry["key"]])
            self._observe_restore(t0)
            return tree, meta
        raise FileNotFoundError(f"no usable checkpoint under {self.directory}")

    @staticmethod
    def _observe_restore(t0: float) -> None:
        # only successful restores count: a FileNotFoundError walk over an
        # empty directory is init-path control flow, not restore cost
        METRICS.histogram(
            "checkpoint_restore_seconds", buckets=SAVE_BUCKETS
        ).observe(time.perf_counter() - t0)

    def wait(self) -> None:
        with self._lock:
            pass  # saves are synchronous; returning means none is in flight

    def close(self) -> None:
        self.wait()

    # -- internals -----------------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step}")

    def _candidate_steps(self) -> List[int]:
        steps = []
        try:
            entries = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for entry in entries:
            if entry.startswith(_STEP_PREFIX):
                try:
                    steps.append(int(entry[len(_STEP_PREFIX):]))
                except ValueError:
                    continue
        return sorted(steps)

    def _restore_order(self, step: Optional[int]) -> List[int]:
        if step is not None:
            return [int(step)] if self._is_complete(int(step)) else []
        return list(reversed(self.all_steps()))

    def _resolve_step(self, step: Optional[int]) -> int:
        if step is None:
            latest = self.latest_step()
            if latest is None:
                raise FileNotFoundError(f"no usable checkpoint under {self.directory}")
            return latest
        return int(step)

    def _load_manifest(self, step: int) -> Dict[str, Any]:
        mpath = os.path.join(self._step_dir(step), _MANIFEST)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise CorruptCheckpoint(f"step {step}: unreadable manifest: {e}") from None
        if manifest.get("format") != _FORMAT or "leaves" not in manifest:
            raise CorruptCheckpoint(f"step {step}: unknown manifest format")
        return manifest

    def _is_complete(self, step: int) -> bool:
        try:
            manifest = self._load_manifest(step)
        except CorruptCheckpoint:
            return False
        d = self._step_dir(step)
        for entry in manifest["leaves"]:
            fpath = os.path.join(d, entry["file"])
            try:
                if os.path.getsize(fpath) != entry["bytes"]:
                    return False
            except OSError:
                return False
        return True

    def _load_arrays(
        self, step: int, manifest: Optional[Dict[str, Any]] = None
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
        manifest = manifest if manifest is not None else self._load_manifest(step)
        d = self._step_dir(step)
        arrays: Dict[str, np.ndarray] = {}
        for entry in manifest["leaves"]:
            try:
                arr = np.load(os.path.join(d, entry["file"]), allow_pickle=False)
            except (OSError, ValueError) as e:
                raise CorruptCheckpoint(f"step {step}: {entry['file']}: {e}") from None
            if zlib.crc32(np.ascontiguousarray(arr).tobytes()) != entry["crc32"]:
                raise CorruptCheckpoint(f"step {step}: {entry['file']}: crc mismatch")
            arrays[entry["key"]] = arr
        return arrays, manifest.get("meta") or {}

    def _gc_locked(self, newest: int) -> None:
        """Retention: keep the newest ``max_to_keep`` COMPLETE checkpoints.
        Only steps strictly older than the newest complete one are ever
        deleted, so a retention bug can never eat the checkpoint a restart
        is about to read."""
        complete = self.all_steps()
        if not complete:
            return
        keep_floor = complete[-1]
        doomed = [s for s in complete[:-1] if s < keep_floor][: max(0, len(complete) - self.max_to_keep)]
        for s in doomed:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)


def _place_like(arr: np.ndarray, template_leaf: Any) -> Any:
    """Put a restored array where the template leaf says it lives."""
    if isinstance(template_leaf, jax.Array):
        return jax.device_put(arr.astype(template_leaf.dtype), template_leaf.sharding)
    if isinstance(template_leaf, jax.ShapeDtypeStruct):
        sharding = getattr(template_leaf, "sharding", None)
        arr = arr.astype(template_leaf.dtype)
        return jax.device_put(arr, sharding) if sharding is not None else arr
    if isinstance(template_leaf, (int, float, bool)):
        return type(template_leaf)(arr.item())
    return arr


def _insert_by_tokens(tree: Any, tokens: List[List[Any]], value: Any) -> Any:
    """Rebuild a dict/list pytree from tokenized leaf paths."""
    if not tokens:
        return value
    kind, key = tokens[0]
    if kind == "d":
        node = tree if isinstance(tree, dict) else {}
        node[key] = _insert_by_tokens(node.get(key), tokens[1:], value)
        return node
    if kind in ("s", "i"):
        node = tree if isinstance(tree, list) else []
        while len(node) <= key:
            node.append(None)
        node[key] = _insert_by_tokens(node[key], tokens[1:], value)
        return node
    raise CorruptCheckpoint(
        f"restore_numpy supports dict/list trees only; saw path token {kind!r}"
    )
