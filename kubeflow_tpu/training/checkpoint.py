"""Checkpoint/resume: mesh-aware training state persistence.

The reference has no training checkpoints (platform 'resume' = stop/start
annotations + PVC-backed home dirs — SURVEY §5); for the TPU build this is
the workload half of elastic recovery: after the controller's gang restart
(notebook controller slice recovery), the training process resumes from the
latest checkpoint on the PVC.

Orbax-backed: sharded arrays restore onto whatever mesh the *restoring*
process provides (resume on a different slice topology works — the
reshard happens at load), saves are atomic (tmp dir + rename via orbax),
and a retention budget bounds PVC usage.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


class Checkpointer:
    """Save/restore a training-state pytree under a step-indexed directory.

    Usage (inside a training loop):
        ckpt = Checkpointer("/home/jovyan/ckpt", max_to_keep=3)
        start = ckpt.latest_step()
        state = ckpt.restore(state) if start is not None else state
        for step in range((start or -1) + 1, total):
            state = train_step(state, ...)
            ckpt.maybe_save(step, state, every=100)
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )
        self._ocp = ocp

    # -- introspection -------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    # -- save/restore --------------------------------------------------------
    def save(self, step: int, state: Any, wait: bool = True) -> None:
        self._mgr.save(step, args=self._ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def maybe_save(self, step: int, state: Any, every: int, wait: bool = False) -> bool:
        if every <= 0 or step % every != 0:
            return False
        self.save(step, state, wait=wait)
        return True

    def restore(self, state_template: Any, step: Optional[int] = None) -> Any:
        """Restore into the shardings/dtypes of ``state_template`` — arrays
        land directly on the template's mesh (cross-topology resume)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        abstract = jax.tree_util.tree_map(_abstractify, state_template)
        return self._mgr.restore(step, args=self._ocp.args.StandardRestore(abstract))

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


def _abstractify(leaf: Any) -> Any:
    if isinstance(leaf, jax.Array):
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=leaf.sharding)
    return leaf
